"""Packaging (reference analog: setup.py — pip package with loader deps).

The dependency set is the TPU stack (jax + pyarrow) instead of the
reference's ray/pandas/torch (reference: setup.py:14-20); torch is an
extra for the migration-compat Torch binding.
"""

from setuptools import find_packages, setup

setup(
    name="ray_shuffling_data_loader_tpu",
    version="0.1.0",
    description=("TPU-native pipelined per-epoch distributed shuffling "
                 "data loader for JAX"),
    packages=find_packages(
        include=["ray_shuffling_data_loader_tpu",
                 "ray_shuffling_data_loader_tpu.*"]),
    package_data={
        "ray_shuffling_data_loader_tpu.native": ["src/*.cpp"],
    },
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "numpy",
        "pyarrow",
    ],
    extras_require={
        "torch": ["torch"],
        "models": ["flax", "optax"],
    },
)
