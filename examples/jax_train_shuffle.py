"""End-to-end distributed training example on the shuffling pipeline.

TPU-native replacement for the reference's Horovod example (reference:
examples/horovod/ray_torch_shuffle.py:1-336): the driver creates the batch
queue and kicks off the multi-epoch shuffle before training starts
(consumer-only trainers, reference: :316-322); the trainer is a
``jax.jit``'d step over a device mesh — DP gradient sync is an XLA psum
over ICI instead of Horovod NCCL allreduce (reference: :173-177) — and the
example records **batch wait times**, the north-star stall metric
(reference: :186-218).

Like the reference, the train step can be mocked with a fixed sleep
(``--mock-train-step-time``, reference: :91,199-200) to measure the input
pipeline alone, or run for real (DLRM on the DATA_SPEC schema).

Single host (drives all local devices):
    python examples/jax_train_shuffle.py --num-rows 200000 --num-files 8 \
        --num-epochs 3 --batch-size 8192

Multi-host (one process per TPU-VM host, launched on every host):
    RSDL_HOSTS="host0:18515,host1:18515" \
    python examples/jax_train_shuffle.py --distributed ...
    # rank/world come from jax.distributed. With RSDL_HOSTS set, hosts run
    # the GLOBAL distributed shuffle (cross-host reducer exchange over the
    # host network, parallel/distributed.py); otherwise each host shuffles
    # only its own contiguous shard of the file list. Either way the train
    # step is one jit program over the global mesh: every host assembles
    # the global batch from its local shard
    # (jax.make_array_from_process_local_data) and XLA psums gradients
    # over ICI/DCN. A per-step all-hosts continue-vote keeps collective
    # programs aligned when hosts' epochs have unequal batch counts.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
import timeit

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--num-rows", type=int, default=200_000)
    p.add_argument("--num-files", type=int, default=8)
    p.add_argument("--num-row-groups-per-file", type=int, default=2)
    p.add_argument("--num-epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=8192)
    p.add_argument("--num-reducers", type=int, default=None)
    p.add_argument("--max-concurrent-epochs", type=int, default=2)
    p.add_argument("--mock-train-step-time", type=float, default=None,
                   help="Replace the real train step with a sleep of this "
                        "many seconds (input-pipeline-only measurement)")
    p.add_argument("--data-dir", type=str, default="./example_data")
    p.add_argument("--use-old-data", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--learning-rate", type=float, default=1e-3)
    p.add_argument("--file-cache", choices=["auto", "none", "disk"],
                   default="auto",
                   help="decoded-table cache tier: auto (RAM), none "
                        "(re-decode every epoch), disk (decode once, "
                        "stream later epochs from mmap'd Arrow IPC — the "
                        "corpus-exceeds-RAM regime)")
    p.add_argument("--max-inflight-bytes", type=int, default=None,
                   help="transient pipeline memory budget (bytes); see "
                        "examples/memory_budget.md")
    p.add_argument("--spill-dir", type=str, default=None,
                   help="with --max-inflight-bytes: spill over-budget "
                        "reducer outputs to Arrow IPC files here")
    p.add_argument("--cpu", action="store_true",
                   help="Force the CPU backend (smoke runs)")
    p.add_argument("--tiny-model", action="store_true",
                   help="Cap embedding vocabularies and widths so the real "
                        "train step compiles quickly on small hosts")
    p.add_argument("--distributed", action="store_true",
                   help="Initialize jax.distributed (one process per host)")
    p.add_argument("--stats-dir", type=str, default=None,
                   help="Write a per-rank CSV of epoch stats (steps, "
                        "rows/s, loss, batch waits) here; local path or "
                        "any utils/fileio URI (gs://, s3://, memory://)")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.cpu:
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        import jax
    if args.distributed:
        # On TPU pods initialize() self-configures from the metadata
        # service; elsewhere (the slice launcher's SSH/local fan-out) the
        # coordinates come from env vars it sets per host.
        if os.environ.get("JAX_NUM_PROCESSES"):
            jax.distributed.initialize(
                coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
                num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
                process_id=int(os.environ["JAX_PROCESS_ID"]))
        else:
            jax.distributed.initialize()

    import numpy as np
    import optax

    from ray_shuffling_data_loader_tpu import data_generation as dg
    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
    from ray_shuffling_data_loader_tpu.models import dlrm
    from ray_shuffling_data_loader_tpu.parallel import mesh as mesh_mod
    from ray_shuffling_data_loader_tpu.parallel.trainer import SpmdTrainer

    rank, world = mesh_mod.local_data_shard_info()

    if args.use_old_data:
        import glob
        filenames = sorted(
            glob.glob(os.path.join(args.data_dir, "*.parquet.snappy")))
    else:
        # Every host generates the same seeded files locally — the no-shared-
        # filesystem pattern of the reference's dummy_data_generator
        # (reference: examples/dummy_data_generator.py:11-32), made exact by
        # the seeded generator.
        filenames, _ = dg.generate_data(
            args.num_rows, args.num_files, args.num_row_groups_per_file,
            0.0, args.data_dir, seed=args.seed)
    # Global ("data",) DP mesh over every chip of every host. In
    # distributed mode each host contributes its local shard of each global
    # batch; single-host this is just the local devices.
    mesh = mesh_mod.make_mesh()
    multi_host = world > 1
    if args.tiny_model:
        # Indices above the capped vocab are clipped by jnp.take's default
        # out-of-bounds mode — fine for a smoke run.
        cfg = dlrm.DLRMConfig(
            vocab_sizes=tuple(min(v, 1000)
                              for v in dlrm.DATA_SPEC_VOCAB_SIZES),
            embed_dim=8, top_hidden=(64, 32))
    else:
        cfg = dlrm.DLRMConfig()
    trainer = None
    if args.mock_train_step_time is None:
        params = dlrm.init(cfg, jax.random.key(args.seed))
        trainer = SpmdTrainer(
            mesh, lambda p, s, y: dlrm.loss_fn(cfg, p, None, s, y),
            params, optax.adam(args.learning_rate))

    sorted_files = sorted(filenames)
    dataset_kwargs = dict(
        num_epochs=args.num_epochs, num_trainers=1,
        batch_size=args.batch_size, rank=0,
        feature_columns=list(dg.FEATURE_COLUMNS),
        feature_types=[np.int32] * len(dg.FEATURE_COLUMNS),
        label_column=dg.LABEL_COLUMN,
        max_concurrent_epochs=args.max_concurrent_epochs, seed=args.seed,
        drop_last=True, queue_name=f"example-queue-{rank}",
        max_inflight_bytes=args.max_inflight_bytes,
        spill_dir=args.spill_dir,
        file_cache={"auto": "auto", "none": None,
                    "disk": "disk"}[args.file_cache])
    transport = None
    if multi_host and os.environ.get("RSDL_HOSTS"):
        # GLOBAL shuffle: rows from any host's files can reach any trainer
        # (the reference's cluster-wide semantics). RSDL_HOSTS lists every
        # host's shuffle endpoint, same order as jax.process_index.
        from ray_shuffling_data_loader_tpu.parallel.distributed import (
            create_distributed_batch_queue_and_shuffle)
        from ray_shuffling_data_loader_tpu.parallel.transport import TcpTransport
        addresses = []
        for spec in os.environ["RSDL_HOSTS"].split(","):
            host, _, port = spec.strip().rpartition(":")
            addresses.append((host, int(port)))
        assert len(addresses) == world, "RSDL_HOSTS entries != process count"
        transport = TcpTransport(rank, addresses)
        transport.start()
        transport.connect()
        # The ShardPlan (hence every send/recv tag) is a function of
        # num_reducers, so the value must be identical on every host.
        # default_num_reducers() depends on the *local* cpu count, which can
        # differ across hosts — derive the default from world only.
        num_reducers = args.num_reducers or 8 * world
        batch_queue, shuffle_result = (
            create_distributed_batch_queue_and_shuffle(
                sorted_files, args.num_epochs,
                num_reducers, transport,
                max_concurrent_epochs=args.max_concurrent_epochs,
                seed=args.seed, queue_name=dataset_kwargs["queue_name"],
                max_inflight_bytes=args.max_inflight_bytes,
                spill_dir=args.spill_dir,
                file_cache=dataset_kwargs["file_cache"]))
        ds = JaxShufflingDataset(
            sorted_files, batch_queue=batch_queue,
            shuffle_result=shuffle_result,
            # In multi-host mode batches stay host-local numpy; the global
            # device array is assembled below.
            device_put=False, **dataset_kwargs)
    else:
        # Per-host shuffle of a contiguous file shard (deterministic shard
        # routing — no cross-host exchange, weaker global mixing).
        local_files = [f for i, f in enumerate(sorted_files)
                       if i % world == rank]
        # A 1-device mesh needs no sharded transfers; passing mesh=None
        # lets the dataset use device re-batching (bulk chunk transfers +
        # on-device slicing). jit resolves the trivial sharding itself.
        dataset_mesh = (None if multi_host or len(mesh.devices.flat) == 1
                        else mesh)
        ds = JaxShufflingDataset(
            local_files, num_reducers=args.num_reducers,
            mesh=dataset_mesh,
            device_put=not multi_host, **dataset_kwargs)

    import jax.numpy as jnp
    if multi_host:
        from jax.experimental import multihost_utils
        from jax.sharding import NamedSharding, PartitionSpec as P

        def to_global(arr):
            return jax.make_array_from_process_local_data(
                NamedSharding(mesh, P("data", *([None] * (arr.ndim - 1)))),
                np.asarray(arr))

    epoch_rows = []
    run_wait_total, run_wait_count = 0.0, 0
    for epoch in range(args.num_epochs):
        ds.set_epoch(epoch)
        epoch_start = timeit.default_timer()
        steps, last_loss = 0, float("nan")
        it = iter(ds)
        while True:
            batch = next(it, None)
            if multi_host:
                # Continue-vote: all hosts step, or none do — keeps every
                # host issuing the same sequence of collective programs
                # even when per-host batch counts differ by one.
                votes = multihost_utils.process_allgather(
                    np.array([batch is not None]))
                if not votes.all():
                    break
            elif batch is None:
                break
            features, label = batch
            if args.mock_train_step_time is not None:
                time.sleep(args.mock_train_step_time)
            else:
                sparse = jnp.concatenate(features, axis=1)
                if multi_host:
                    sparse, label = to_global(sparse), to_global(label)
                last_loss = trainer.train_step(sparse, label)
            steps += 1
        if trainer is not None:
            trainer.block_until_ready()
            last_loss = float(last_loss)
        duration = timeit.default_timer() - epoch_start
        # Per-EPOCH waits: reset after each epoch so rows/prints aren't
        # cumulative; totals for the DONE line are kept by hand.
        waits = ds.batch_wait_stats.summary()
        run_wait_total += waits["total"]
        run_wait_count += waits["count"]
        ds.batch_wait_stats.reset()
        print(f"[rank {rank}] epoch {epoch}: {steps} steps in "
              f"{duration:.2f}s ({steps * args.batch_size / duration:,.0f} "
              f"rows/s), loss={last_loss:.4f}, "
              f"batch-wait mean={waits['mean'] * 1e3:.1f}ms "
              f"max={waits['max'] * 1e3:.1f}ms total={waits['total']:.2f}s")
        epoch_rows.append({
            "rank": rank, "epoch": epoch, "steps": steps,
            "duration_s": round(duration, 4),
            "rows_per_s": round(steps * args.batch_size / duration, 1),
            "loss": (round(last_loss, 6)
                     if isinstance(last_loss, float) else ""),
            "batch_wait_mean_ms": round(waits["mean"] * 1e3, 3),
            "batch_wait_max_ms": round(waits["max"] * 1e3, 3),
            "batch_wait_total_s": round(waits["total"], 4),
        })
    print(f"[rank {rank}] DONE: {run_wait_count} batches, "
          f"total stall {run_wait_total:.2f}s "
          f"(mean {run_wait_total / max(1, run_wait_count) * 1e3:.1f}"
          "ms/batch)")
    if args.stats_dir:
        # Per-host stats CSV — what the slice launcher
        # (examples/launch_slice.py) gathers after a run (the reference's
        # per-trial CSVs role, reference: ray_torch_shuffle.py:228-237).
        import csv

        from ray_shuffling_data_loader_tpu.utils import fileio
        fileio.makedirs(args.stats_dir)
        path = fileio.join(args.stats_dir, f"host_{rank}_epochs.csv")
        with fileio.open_text(path, "w") as f:
            writer = csv.DictWriter(f, fieldnames=list(epoch_rows[0])
                                    if epoch_rows else ["rank"])
            writer.writeheader()
            for row in epoch_rows:
                writer.writerow(row)
        print(f"[rank {rank}] stats written to {path}")
    # Release the persistent prefetch producer (no-op if it already exited
    # after the final epoch).
    ds.close()
    if transport is not None:
        transport.close()


if __name__ == "__main__":
    main()
