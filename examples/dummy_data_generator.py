"""Generate identical dummy Parquet files on this host.

Parity with the reference's per-node dummy generator (reference:
examples/dummy_data_generator.py:11-36, a Fire CLI wrapping
``generate_data_local``): run the same command on every TPU-VM host when
there is no shared filesystem; the seeded generator makes the files
bit-identical across hosts (the reference relies on unseeded luck).

Usage:
    python examples/dummy_data_generator.py --num-rows 1000000 \
        --num-files 10 --data-dir /tmp/data
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_shuffling_data_loader_tpu.data_generation import (  # noqa: E402
    generate_data_local)


def generate_dummy_data_local(num_rows: int = 100_000,
                              num_files: int = 10,
                              num_row_groups_per_file: int = 1,
                              max_row_group_skew: float = 0.0,
                              data_dir: str = "./example_data",
                              seed: int = 0):
    filenames, num_bytes = generate_data_local(
        num_rows, num_files, num_row_groups_per_file, max_row_group_skew,
        data_dir, seed=seed)
    print(f"Generated {len(filenames)} files ({num_bytes / 1e6:.1f} MB "
          f"in-memory) in {data_dir}")
    return filenames


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--num-rows", type=int, default=100_000)
    parser.add_argument("--num-files", type=int, default=10)
    parser.add_argument("--num-row-groups-per-file", type=int, default=1)
    parser.add_argument("--max-row-group-skew", type=float, default=0.0)
    parser.add_argument("--data-dir", type=str, default="./example_data")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()
    generate_dummy_data_local(args.num_rows, args.num_files,
                              args.num_row_groups_per_file,
                              args.max_row_group_skew, args.data_dir,
                              args.seed)
