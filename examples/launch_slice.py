"""One-command multi-host launcher for a TPU slice.

The runnable replacement for the reference's Ray-autoscaler YAMLs
(reference: benchmarks/cluster.yaml:1-183, examples/horovod/cluster.yaml:
1-105): where the reference provisions AWS nodes and `ray exec`s the
benchmark onto them, a TPU slice's hosts already exist — this script fans
`examples/jax_train_shuffle.py --distributed` out to every host of the
slice, wires up the JAX coordination service and the RSDL_HOSTS shuffle
endpoints, waits for completion, and gathers each host's stats CSV into
one local directory.

Usage (SSH mode — real slice; run from any machine that can SSH to the
hosts, e.g. with `gcloud compute tpus tpu-vm ssh` configured hosts):

    RSDL_HOSTS="10.0.0.2:18515,10.0.0.3:18515" \\
    python examples/launch_slice.py \\
        --ssh user@tpu-host-0,user@tpu-host-1 \\
        --repo /home/user/ray_shuffling_data_loader_tpu \\
        --out ./slice_stats \\
        -- --num-rows 2000000 --num-files 16 --num-epochs 4 \\
           --batch-size 131072

Usage (local mode — smoke the whole control flow with N processes on
this machine, no SSH):

    RSDL_HOSTS="127.0.0.1:18515,127.0.0.1:18516" \\
    python examples/launch_slice.py --local --out /tmp/slice_stats \\
        -- --cpu --tiny-model --num-rows 4000 --num-files 2 \\
           --num-epochs 2 --batch-size 500

Everything after ``--`` is passed through to jax_train_shuffle.py
verbatim. ``--distributed`` and ``--stats-dir`` are appended
automatically. RSDL_HOSTS (host:port shuffle endpoints, one per host,
ordered by process index) defines the world; host i gets
``JAX_PROCESS_ID=i``, and host 0's address (with ``--coordinator-port``)
is the JAX coordination service.
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import threading


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--ssh", type=str, default=None,
                   help="Comma-separated SSH targets, one per RSDL_HOSTS "
                        "entry, same order (user@host or a Host alias "
                        "from ~/.ssh/config)")
    p.add_argument("--local", action="store_true",
                   help="Run every 'host' as a local process instead of "
                        "over SSH (smoke mode)")
    p.add_argument("--repo", type=str, default=None,
                   help="Repo checkout path on the remote hosts "
                        "(default: this repo's path, assumed identical)")
    p.add_argument("--out", type=str, default="./slice_stats",
                   help="Local directory to gather per-host stats CSVs")
    p.add_argument("--coordinator-port", type=int, default=8476,
                   help="JAX coordination-service port on host 0")
    p.add_argument("--remote-stats-dir", type=str,
                   default="/tmp/rsdl_slice_stats",
                   help="Where each host writes its CSV before gathering")
    p.add_argument("--python", type=str, default="python3",
                   help="Python interpreter on the hosts")
    if argv is None:
        argv = sys.argv[1:]
    # Everything after a literal "--" goes to jax_train_shuffle.py verbatim
    # (argparse would otherwise reject the dashed passthrough flags).
    train_args: list = []
    if "--" in argv:
        split = argv.index("--")
        argv, train_args = argv[:split], argv[split + 1:]
    args = p.parse_args(argv)
    args.train_args = train_args
    return args


def _stream(proc: subprocess.Popen, tag: str) -> None:
    for line in proc.stdout:
        sys.stdout.write(f"[{tag}] {line}")
        sys.stdout.flush()


def main(argv=None) -> int:
    args = parse_args(argv)
    hosts_env = os.environ.get("RSDL_HOSTS")
    if not hosts_env:
        print("RSDL_HOSTS is required: comma-separated host:port shuffle "
              "endpoints, one per slice host, ordered by process index",
              file=sys.stderr)
        return 2
    endpoints = [h.strip() for h in hosts_env.split(",") if h.strip()]
    world = len(endpoints)
    if args.local and args.ssh:
        print("--local and --ssh are mutually exclusive", file=sys.stderr)
        return 2
    ssh_targets = None
    if not args.local:
        if not args.ssh:
            print("need --ssh targets (or --local for smoke mode)",
                  file=sys.stderr)
            return 2
        ssh_targets = [t.strip() for t in args.ssh.split(",") if t.strip()]
        if len(ssh_targets) != world:
            print(f"--ssh lists {len(ssh_targets)} targets but RSDL_HOSTS "
                  f"has {world} endpoints", file=sys.stderr)
            return 2

    repo_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    remote_repo = args.repo or repo_dir
    coordinator = (f"{endpoints[0].rsplit(':', 1)[0]}:"
                   f"{args.coordinator_port}")
    # Children run with cwd=repo_dir, so a relative --out must be resolved
    # against the LAUNCHER's cwd or local-mode CSVs land inside the repo.
    args.out = os.path.abspath(args.out)
    os.makedirs(args.out, exist_ok=True)

    procs = []
    for i in range(world):
        stats_dir = (os.path.join(args.out, f"host_{i}") if args.local
                     else args.remote_stats_dir)
        env_pairs = {
            "RSDL_HOSTS": hosts_env,
            "JAX_COORDINATOR_ADDRESS": coordinator,
            "JAX_NUM_PROCESSES": str(world),
            "JAX_PROCESS_ID": str(i),
        }
        csv_name = f"host_{i}_epochs.csv"
        train_cmd = [
            args.python, "examples/jax_train_shuffle.py", "--distributed",
            "--stats-dir", stats_dir, *args.train_args,
        ]
        if args.local:
            env = dict(os.environ, **env_pairs,
                       PYTHONPATH=repo_dir + os.pathsep
                       + os.environ.get("PYTHONPATH", ""))
            env.setdefault("PYTHONUNBUFFERED", "1")
            proc = subprocess.Popen(
                [sys.executable] + train_cmd[1:], cwd=repo_dir, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
        else:
            exports = " ".join(f"{k}={shlex.quote(v)}"
                               for k, v in env_pairs.items())
            # Remove this host's CSV from any previous run first, so the
            # gather below can never pick up stale data.
            stale = shlex.quote(os.path.join(stats_dir, csv_name))
            remote = (f"cd {shlex.quote(remote_repo)} && rm -f {stale} && "
                      f"{exports} "
                      + " ".join(shlex.quote(c) for c in train_cmd))
            # -tt forces a pty: killing the local ssh client then HUPs the
            # remote session, so "stop them (peer failed)" actually stops
            # the remote trainer instead of only the local client.
            # stdin=DEVNULL: -tt must not adopt (and raw-mode) the
            # launcher's own tty — killing ssh on the peer-failure path
            # would leave the user's terminal without echo.
            proc = subprocess.Popen(
                ["ssh", "-tt", "-o", "BatchMode=yes", ssh_targets[i],
                 remote],
                stdin=subprocess.DEVNULL,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
        t = threading.Thread(target=_stream, args=(proc, f"host {i}"),
                             daemon=True, name=f"rsdl-slice-stream-{i}")
        t.start()
        procs.append((proc, t))

    # Poll ALL hosts rather than wait in order: one host dying early (bad
    # --repo, import error) strands the rest on collectives, and an
    # in-order wait on host 0 would never observe the failure.
    import time as _time
    rc = 0
    running = dict(enumerate(procs))
    while running:
        for i in list(running):
            proc, t = running[i]
            if proc.poll() is None:
                continue
            del running[i]
            t.join(timeout=10)
            if proc.returncode != 0:
                print(f"[launcher] host {i} exited rc={proc.returncode}",
                      file=sys.stderr)
                rc = rc or proc.returncode
        if rc and running:
            # One dead host strands the others on collectives — stop them.
            for i, (proc, _) in running.items():
                print(f"[launcher] stopping host {i} (peer failed)",
                      file=sys.stderr)
                proc.kill()
        if running:
            _time.sleep(0.2)
    if rc:
        return rc

    if not args.local:
        # Gather every host's CSVs next to each other locally.
        for i, target in enumerate(ssh_targets):
            dest = os.path.join(args.out, f"host_{i}")
            os.makedirs(dest, exist_ok=True)
            # Copy only THIS run's file for THIS rank (never a stale
            # leftover from a previous run with more hosts).
            gather = subprocess.run(
                ["scp", "-o", "BatchMode=yes",
                 f"{target}:{args.remote_stats_dir}/host_{i}_epochs.csv",
                 dest],
                capture_output=True, text=True)
            if gather.returncode != 0:
                print(f"[launcher] gather from host {i} failed: "
                      f"{gather.stderr.strip()}", file=sys.stderr)
                rc = rc or gather.returncode
    print(f"[launcher] done; stats under {args.out}/host_*/")
    return rc


if __name__ == "__main__":
    sys.exit(main())
