#!/usr/bin/env bash
# Developer environment setup (reference: setup.sh). Installs the package
# plus the optional dev tools the format gate uses when present.
set -euo pipefail
cd "$(dirname "$0")"

bash install.sh
pip install pytest yapf flake8 2>/dev/null || \
    echo "dev tools unavailable (offline image) — format.sh degrades gracefully"
