#!/usr/bin/env python
"""Repo-root launcher for rsdl-lint.

Equivalent to ``python -m ray_shuffling_data_loader_tpu.analysis`` but
runnable from anywhere (it pins sys.path to the repo checkout that
contains it), e.g. as an editor/file-watcher hook::

    tools/rsdl_lint.py ray_shuffling_data_loader_tpu tests benchmarks
"""

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from ray_shuffling_data_loader_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
