#!/usr/bin/env python
"""rsdl-regress: differential forensics between two bench rounds.

``tools/rsdl_bench_diff.py`` tells you *that* ``train_rows_per_sec``
dropped 20%; this tool tells you *why*, from the flight capsules
``bench.py`` writes beside each ``BENCH_r*.json`` record: which stage's
critical-path share grew (per-epoch normalized, so rounds with
different epoch counts align), which latency distribution actually
shifted shape (bucket-overlap significance over the committed
histogram/sketch buckets — not just a mean), which resolved policy knob
or ``RSDL_*`` env var appeared/changed, and a ranked suspect list with
what-if attribution.

Usage::

    tools/rsdl_regress.py BENCH_r10.json BENCH_r11.json
    tools/rsdl_regress.py BENCH_r09.json BENCH_r10.json   # no capsules:
                                        # loud record-only degrade
    tools/rsdl_regress.py base.json cur.json --json       # full report
    tools/rsdl_regress.py --check     # self-test: synthesizes a round
                                      # pair with a planted suspect and
                                      # requires the ranking to name it

Exit codes: 0 report produced (even record-only), 2 inputs unusable.
``--check`` exits 1 if the planted suspect is not ranked first.

Stdlib-only: loads ``runtime/regress.py`` by file path (the rsdl_top
pattern), so it runs on hosts without numpy/pyarrow/jax — an operator
laptop holding two downloaded records and capsules.
"""

import argparse
import importlib.util
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_RUNTIME = os.path.join(_REPO_ROOT, "ray_shuffling_data_loader_tpu",
                        "runtime")


def _load_regress():
    try:
        import importlib
        return importlib.import_module(
            "ray_shuffling_data_loader_tpu.runtime.regress")
    except ImportError:
        spec = importlib.util.spec_from_file_location(
            "_rsdl_regress", os.path.join(_RUNTIME, "regress.py"))
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        return module


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="rsdl_regress",
        description="differential forensics between two bench rounds")
    parser.add_argument("base", nargs="?",
                        help="baseline bench record (raw or BENCH_r* "
                             "wrapper)")
    parser.add_argument("cur", nargs="?",
                        help="current bench record to explain")
    parser.add_argument("--json", action="store_true",
                        help="emit the full report as JSON instead of "
                             "the rendered summary")
    parser.add_argument("--max-suspects", type=int, default=8,
                        help="suspect list length (default 8)")
    parser.add_argument("--whatif-speedup", type=float, default=2.0,
                        help="what-if speedup factor for the current "
                             "round's trace attribution (default 2.0)")
    parser.add_argument("--check", action="store_true",
                        help="self-test: plant a known suspect in a "
                             "synthetic round pair and verify the "
                             "ranking names it")
    args = parser.parse_args(argv)

    regress = _load_regress()

    if args.check:
        ok, lines = regress.self_check()
        for line in lines:
            print(line)
        print("rsdl_regress --check: %s" % (
            "planted suspect ranked #1" if ok
            else "FAILED (planted suspect not ranked #1)"))
        return 0 if ok else 1

    if not args.base or not args.cur:
        parser.error("two record paths required (or --check)")
    for path in (args.base, args.cur):
        if not os.path.isfile(path):
            print(f"rsdl_regress: no such record: {path}",
                  file=sys.stderr)
            return 2
    try:
        report = regress.diff_rounds(
            args.base, args.cur,
            whatif_speedup=args.whatif_speedup,
            max_suspects=args.max_suspects)
    except (ValueError, OSError, json.JSONDecodeError) as e:
        print(f"rsdl_regress: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for line in regress.render_report(report):
            print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
