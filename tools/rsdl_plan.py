#!/usr/bin/env python
"""rsdl-plan: render and validate serialized epoch plans (plan/ir.py).

The epoch plan is the pipeline's declarative task graph — files -> map
partitions -> reduce slices -> queue routes, every node carrying its
``(seed, epoch, task)`` lineage key, dependency edges and telemetry-fed
cost annotations. This tool is the operator's window into one:

Usage::

    tools/rsdl_plan.py render plan.json            # node/edge table
    tools/rsdl_plan.py render plan.json --json     # normalized JSON dump
    tools/rsdl_plan.py validate plan.json          # rc 0 valid / 1 not
    tools/rsdl_plan.py --check                     # self-test (format.sh)

``validate`` exits 0 when the file deserializes AND passes the IR's
structural validation (key consistency, closed acyclic deps, reduce
coverage, contiguous route coverage); 1 otherwise, with the reason on
stderr. ``--check`` builds a small demo plan in memory, round-trips it
through JSON, and validates — the informational self-test format.sh
runs.

Stdlib-only: ``plan/ir.py`` is loaded straight by file path, so this
tool runs on hosts without numpy/pyarrow/jax installed.
"""

import argparse
import importlib.util
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_IR_PATH = os.path.join(_REPO_ROOT, "ray_shuffling_data_loader_tpu",
                        "plan", "ir.py")


def _load_ir_module():
    """Load plan/ir.py WITHOUT importing the package (whose __init__
    pulls numpy/pyarrow); ir.py itself is stdlib-only."""
    spec = importlib.util.spec_from_file_location("rsdl_plan_ir", _IR_PATH)
    module = importlib.util.module_from_spec(spec)
    # Registered before exec: ir.py's dataclasses resolve their module
    # through sys.modules at class-creation time.
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


def _load_plan(ir, path: str):
    with open(path, encoding="utf-8") as f:
        return ir.from_json(f.read())


def _fmt_cost(cost) -> str:
    return "-" if cost is None else f"{cost * 1e3:.1f}ms"


def render(ir, plan, as_json: bool) -> int:
    if as_json:
        print(plan.to_json(indent=2))
        return 0
    print(f"epoch plan: seed={plan.seed} epoch={plan.epoch} "
          f"files={len(plan.filenames)} reducers={plan.num_reducers} "
          f"trainers={plan.num_trainers} nodes={len(plan.nodes)}")
    header = f"{'node':<16} {'lineage':<12} {'cost':>8}  deps / meta"
    print(header)
    print("-" * len(header))
    for node in plan.nodes.values():
        if node.stage == "map":
            detail = node.meta.get("file", "")
        elif node.stage == "reduce":
            deps = list(node.deps)
            detail = (f"<- {deps[0]} .. {deps[-1]} ({len(deps)} maps)"
                      if deps else "<- (no maps)")
        else:
            span = node.meta.get("reducers", [])
            span_text = (f"reducers {span[0]}..{span[-1]}" if span
                         else "reducers (none)")
            detail = f"queue {node.meta.get('queue')} <- {span_text}"
        print(f"{node.id:<16} {str(node.key):<12} "
              f"{_fmt_cost(node.cost_s):>8}  {detail}")
    return 0


def validate(ir, path: str) -> int:
    try:
        plan = _load_plan(ir, path)
        plan.validate()
    except (OSError, ir.PlanError) as e:
        print(f"rsdl-plan: INVALID {path}: {e}", file=sys.stderr)
        return 1
    print(f"rsdl-plan: OK {path} ({len(plan.nodes)} nodes, "
          f"seed={plan.seed}, epoch={plan.epoch})")
    return 0


def check(ir) -> int:
    """Self-test: build -> serialize -> parse -> validate -> re-serialize
    byte-identically; exercise one malformed-plan rejection."""
    plan = ir.build_epoch_plan(["a.parquet", "b.parquet", "c.parquet"],
                               num_reducers=4, num_trainers=2, seed=7,
                               epoch=3)
    plan.annotate_costs({"map": 0.012, "reduce": 0.034})
    text = plan.to_json()
    again = ir.from_json(text)
    again.validate()
    if again.to_json() != text:
        print("rsdl-plan: FAIL round-trip not byte-stable",
              file=sys.stderr)
        return 1
    broken = json.loads(text)
    broken["nodes"][0]["key"] = [99, 99, 99]
    try:
        ir.EpochPlan.from_dict(broken).validate()
    except ir.PlanError:
        pass
    else:
        print("rsdl-plan: FAIL validation accepted a corrupt lineage key",
              file=sys.stderr)
        return 1
    print(f"rsdl-plan: check OK ({len(plan.nodes)} nodes round-tripped, "
          "corrupt key rejected)")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="rsdl-plan",
        description="Render / validate serialized epoch plans.")
    parser.add_argument("command", nargs="?",
                        choices=("render", "validate"),
                        help="what to do with the plan file")
    parser.add_argument("plan", nargs="?", help="serialized plan JSON")
    parser.add_argument("--json", action="store_true",
                        help="render as normalized JSON")
    parser.add_argument("--check", action="store_true",
                        help="run the built-in self-test and exit")
    args = parser.parse_args(argv)
    ir = _load_ir_module()
    if args.check:
        return check(ir)
    if not args.command or not args.plan:
        parser.error("command and plan file required (or --check)")
    if args.command == "validate":
        return validate(ir, args.plan)
    try:
        plan = _load_plan(ir, args.plan)
    except (OSError, ir.PlanError) as e:
        print(f"rsdl-plan: cannot load {args.plan}: {e}", file=sys.stderr)
        return 1
    return render(ir, plan, args.json)


if __name__ == "__main__":
    sys.exit(main())
