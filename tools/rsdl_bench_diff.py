#!/usr/bin/env python
"""rsdl-bench-diff: per-metric regression gate between two bench records.

BENCH_r05 regressed cached ingest from BENCH_r03's 26.2M rows/s to
9.2M and NOTHING in the repo noticed — the record format carries the
numbers but no machinery compared them. This tool is that machinery:

    tools/rsdl_bench_diff.py BENCH_r03.json BENCH_r05.json
        # rc 1, 'value ... REGRESSED' — the r03->r05 drop, flagged

    tools/rsdl_bench_diff.py --check [DIR]
        # informational mode for format.sh: compares the two newest
        # committed BENCH_r*.json records, prints the verdict, rc 0
        # (add --strict to make it a hard gate)

    python bench.py --baseline BENCH_r03.json
        # the hard gate at measurement time: bench loads this module
        # and exits non-zero on a threshold breach

Records are either a raw bench JSON line (``{"metric", "value", ...}``)
or the committed ``BENCH_r*.json`` wrapper (``{"parsed": {...}}``) —
both load. Only metrics present in BOTH records are compared (schemas
grew over rounds), except ceilings, which apply to the current record
alone. Thresholds are relative drops/rises chosen to sit above host
noise (the committed records span 1-core and many-core hosts) but far
below the regressions worth catching; override any of them with
``--threshold key=pct``.

Exit codes: 0 clean, 1 regression (two-file mode / --strict), 2 usage.
Stdlib-only (runs on a bare CI image, the rsdl_top pattern).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

#: (key, mode, threshold). Modes:
#:   lower_bad:  fail when cur < base * (1 - pct/100)
#:   higher_bad: fail when cur > base * (1 + pct/100) AND cur - base
#:               exceeds the absolute slack in ``slack`` (noise floor)
#:   ceiling:    fail when cur > threshold (current record alone)
#:   require_true: fail when the key is present but falsy (current
#:               record alone; absent key skips — pre-feature records)
DEFAULT_RULES: List[Dict[str, Any]] = [
    {"key": "value", "mode": "lower_bad", "pct": 10.0},
    {"key": "rows_per_s_per_core", "mode": "lower_bad", "pct": 10.0},
    {"key": "cold_rows_per_sec", "mode": "lower_bad", "pct": 10.0},
    {"key": "train_rows_per_sec", "mode": "lower_bad", "pct": 10.0},
    {"key": "train_mfu_pct", "mode": "lower_bad", "pct": 10.0},
    {"key": "stall_pct_under_train", "mode": "higher_bad", "pct": 20.0,
     "slack": 2.0},
    {"key": "fill_s", "mode": "higher_bad", "pct": 50.0, "slack": 1.0},
    {"key": "telemetry_overhead_pct", "mode": "ceiling", "limit": 1.0},
    # Serving-plane leg (multiqueue_service v3): aggregate remote-stream
    # throughput on the sharded fabric, and the shard-scaling ratio
    # itself — a shard-placement regression can keep absolute rows/s
    # afloat on a faster host while the scaling evidence collapses.
    {"key": "serve_rows_per_sec", "mode": "lower_bad", "pct": 10.0},
    {"key": "serve_speedup_vs_single_shard", "mode": "lower_bad",
     "pct": 15.0},
    # Handle delivery must keep beating v2 streaming on wire bytes by a
    # wide margin (the >= 10x acceptance ratio, with noise headroom).
    {"key": "serve_handle_wire_reduction_x", "mode": "lower_bad",
     "pct": 50.0},
    # Delivery-latency leg (runtime/latency.py): end-to-end
    # birth->delivered p99 over the sharded serving plane, and the
    # birth->device freshness p99. Latency on a shared 1-core host is
    # noisy, so the relative threshold is wide and the absolute slack
    # (ms) absorbs scheduler jitter; a real regression (a serialization
    # copy creeping back in, a replay-path stall) blows through both.
    {"key": "delivery_p99_ms", "mode": "higher_bad", "pct": 150.0,
     "slack": 100.0},
    {"key": "freshness_p99_ms", "mode": "higher_bad", "pct": 150.0,
     "slack": 150.0},
    # Storage-plane cold leg (storage/): prefetch-on ingest rate against
    # the simulated object store, and the prefetch A/B speedup itself —
    # a prefetch regression (lanes never idle, warms the wrong files,
    # cancels everything) can hide behind a faster host's absolute
    # rows/s while the on-vs-off ratio collapses toward 1.0.
    # Tightened from 20/25 after the r08->r09 drift (-17.1% rows/s,
    # -13.5% speedup — the streaming PR's shuffled map-read order halved
    # prefetch efficiency, see examples/performance.md) sailed UNDER the
    # old thresholds: both metrics now fail the diff well before a
    # regression of that size lands silently again.
    {"key": "remote_rows_per_sec", "mode": "lower_bad", "pct": 10.0},
    {"key": "remote_prefetch_speedup_x", "mode": "lower_bad",
     "pct": 8.0},
    # Streaming leg (streaming/): windowed end-to-end rate over the
    # synthetic stream, the pipelining watermark lag (stream seconds —
    # deterministic arrivals, so a lag jump means the assembler or the
    # serve path stalled, not the host), and the window seal cost.
    # Records older than r09 lack these keys, so the relative rules
    # skip cleanly against pre-streaming baselines.
    {"key": "stream_rows_per_sec", "mode": "lower_bad", "pct": 20.0},
    {"key": "watermark_lag_p99_s", "mode": "higher_bad", "pct": 100.0,
     "slack": 5.0},
    {"key": "window_close_ms", "mode": "higher_bad", "pct": 150.0,
     "slack": 200.0},
    # Tenancy contention leg (tenancy/): the hot:cold delivered-rows
    # ratio under a 3:1 weight split must track the weights (fairness
    # physics, not host speed — wide but meaningful), and the hot
    # tenant's contended p99 must stay within its solo multiple.
    # Records older than r10 lack these keys; relative rules skip.
    {"key": "tenancy_fairness_ratio", "mode": "lower_bad", "pct": 25.0},
    {"key": "tenancy_hot_rows_per_sec", "mode": "lower_bad", "pct": 20.0},
    {"key": "tenancy_latency_ratio_x", "mode": "higher_bad", "pct": 50.0,
     "slack": 0.5},
    # Elastic-membership leg (membership/): kill->DOWN detection wall
    # time and the shrink's recompute stall are latency physics (wide
    # relative thresholds + absolute slack for shared-host scheduler
    # jitter), while rows_lost and elastic_ok are hard invariants — a
    # single lost row or a failed leg is a gate failure at ANY host
    # speed. Records older than r10 lack these keys; the relative and
    # require_true rules skip cleanly, and the ceiling judges the
    # current record alone (absence there is a non-finding).
    {"key": "member_down_detect_ms", "mode": "higher_bad", "pct": 100.0,
     "slack": 250.0},
    {"key": "resize_stall_ms", "mode": "higher_bad", "pct": 200.0,
     "slack": 250.0},
    {"key": "rows_lost", "mode": "ceiling", "limit": 0.0},
    {"key": "elastic_ok", "mode": "require_true"},
    # Self-healing serving-plane leg (rebalance/): the SLO tenant's p99
    # recovery across the live migration must stay well above 1x (the
    # leg's own rebalance_ok already pins > 1.0 — the relative rule
    # catches the slow slide a boolean can't), the seal window is
    # latency physics (wide + slack for shared-host jitter), and the
    # delivered rate across the move is feed-paced and so nearly
    # deterministic. rows_lost rides the elastic ceiling above
    # (max-merged in bench.py when both legs run); rebalance_ok is the
    # exactly-once + breach-driven-decision + journal-replay verdict.
    # Records older than r12 lack these keys; relative and require_true
    # rules skip cleanly.
    {"key": "rebalance_p99_recovery_x", "mode": "lower_bad", "pct": 50.0},
    {"key": "rebalance_stall_ms", "mode": "higher_bad", "pct": 200.0,
     "slack": 250.0},
    {"key": "rebalance_slo_rows_per_sec", "mode": "lower_bad",
     "pct": 25.0},
    {"key": "rebalance_ok", "mode": "require_true"},
]


def load_record(path: str) -> Dict[str, Any]:
    """A bench record from disk: raw bench JSON or BENCH_r* wrapper."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        return data["parsed"]
    if not isinstance(data, dict) or "value" not in data:
        raise ValueError(f"{path}: not a bench record "
                         "(no 'value' and no 'parsed' wrapper)")
    return data


def derive_metrics(record: Dict[str, Any]) -> Dict[str, Any]:
    """Fill in metrics computable from what the record does carry.

    ``rows_per_s_per_core`` only started being emitted in r05, but
    r03/r04 already carried ``value`` (rows/s) and ``host_cpus`` — and
    the per-core rule is the one that survives a host-width change, so
    silently skipping it against pre-r05 baselines hides exactly the
    normalization it exists for. Derive it (value / host_cpus) when
    absent; emitted values always win over derived ones.
    """
    if _num(record, "rows_per_s_per_core") is None:
        value = _num(record, "value")
        cpus = _num(record, "host_cpus")
        if value is not None and cpus is not None and cpus > 0:
            record = dict(record)
            record["rows_per_s_per_core"] = value / cpus
    return record


def _num(record: Dict[str, Any], key: str) -> Optional[float]:
    value = record.get(key)
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    return float(value)


def compare_records(base: Dict[str, Any], cur: Dict[str, Any],
                    overrides: Optional[Dict[str, float]] = None
                    ) -> List[Dict[str, Any]]:
    """Apply the rule table; returns one finding dict per applicable
    rule: ``{key, mode, base, cur, delta_pct, threshold_pct, ok,
    reason}``. ``overrides`` replaces a rule's pct/limit by key."""
    overrides = overrides or {}
    findings: List[Dict[str, Any]] = []
    for rule in DEFAULT_RULES:
        key, mode = rule["key"], rule["mode"]
        threshold = overrides.get(key, rule.get("pct", rule.get("limit")))
        if mode == "require_true":
            # Boolean verdicts (elastic_ok): judged on the current
            # record alone; an absent key is a pre-feature record and
            # skips cleanly (absence is NOT the "disappeared" failure —
            # these legs are opt-in via RSDL_BENCH_PHASES).
            if key not in cur:
                continue
            ok = bool(cur.get(key))
            findings.append({
                "key": key, "mode": mode, "base": None,
                "cur": 1.0 if ok else 0.0, "delta_pct": None,
                "threshold_pct": None, "ok": ok,
                "reason": ("verdict true" if ok
                           else "verdict false (leg failed its own "
                                "invariants)"),
            })
            continue
        cur_v = _num(cur, key)
        if cur_v is None:
            # A metric the baseline measured but the current record lost
            # is a gate failure, not a skip — BENCH_r05 went out with
            # train_mfu_pct silently null and nothing flagged it.
            # Ceilings apply to the current record alone, so absence
            # there stays a non-finding.
            if mode != "ceiling":
                base_v = _num(base, key)
                if base_v is not None:
                    findings.append({
                        "key": key, "mode": mode, "base": base_v,
                        "cur": None, "delta_pct": None,
                        "threshold_pct": threshold, "ok": False,
                        "reason": f"metric disappeared (base {base_v:g}, "
                                  "current record has no numeric value)",
                    })
            continue
        if mode == "ceiling":
            ok = cur_v <= threshold
            findings.append({
                "key": key, "mode": mode, "base": None, "cur": cur_v,
                "delta_pct": None, "threshold_pct": threshold, "ok": ok,
                "reason": (f"{cur_v:g} <= ceiling {threshold:g}" if ok
                           else f"{cur_v:g} exceeds ceiling {threshold:g}"),
            })
            continue
        base_v = _num(base, key)
        if base_v is None or base_v == 0:
            continue
        delta_pct = 100.0 * (cur_v - base_v) / base_v
        if mode == "lower_bad":
            ok = delta_pct >= -threshold
        else:  # higher_bad: relative rise AND absolute slack both breached
            slack = rule.get("slack", 0.0)
            ok = delta_pct <= threshold or (cur_v - base_v) <= slack
        findings.append({
            "key": key, "mode": mode, "base": base_v, "cur": cur_v,
            "delta_pct": round(delta_pct, 2), "threshold_pct": threshold,
            "ok": ok,
            "reason": f"{base_v:g} -> {cur_v:g} ({delta_pct:+.1f}%, "
                      f"threshold {'-' if mode == 'lower_bad' else '+'}"
                      f"{threshold:g}%)",
        })
    return findings


def render_findings(findings: List[Dict[str, Any]]) -> List[str]:
    lines = []
    for f in findings:
        verdict = "ok        " if f["ok"] else "REGRESSED "
        lines.append(f"{verdict}{f['key']:<26} {f['reason']}")
    if not findings:
        lines.append("no comparable metrics between the two records")
    return lines


def _latest_records(directory: str) -> List[str]:
    paths = sorted(glob.glob(os.path.join(directory, "BENCH_r*.json")))
    return paths[-2:]


def _load_regress():
    """runtime/regress.py by file path (fail-soft: the gate's verdict
    never depends on the forensics plane loading)."""
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "ray_shuffling_data_loader_tpu",
                        "runtime", "regress.py")
    spec = importlib.util.spec_from_file_location("_rsdl_regress", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def forensic_lines(base_path: str, cur_path: str) -> List[str]:
    """The differential forensics footer printed under a failed gate:
    the runtime/regress.py suspect ranking when both records carry
    flight capsules, its loud record-only degrade when they don't.
    Never raises — thresholds and exit codes stay this tool's only
    contract; the footer is evidence, not verdict."""
    try:
        regress = _load_regress()
        report = regress.diff_rounds(base_path, cur_path)
        return regress.render_report(report)
    except Exception as e:  # noqa: BLE001 - evidence, not verdict
        return [f"forensics unavailable: {type(e).__name__}: {e}"]


def provenance_lines(base: Dict[str, Any],
                     cur: Dict[str, Any]) -> List[str]:
    """Hard comparability warnings (dirty tree, cross-host) from the
    records' provenance stamps — printed even when every threshold
    passes, because a cross-host pair passing the gate is as misleading
    as one failing it (the r09->r10 lesson)."""
    try:
        regress = _load_regress()
        return [f"WARNING {w}" for w in regress.provenance_warnings(
            base, cur, include_missing=False)]
    except Exception:  # noqa: BLE001 - evidence, not verdict
        return []


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="per-metric regression gate between two bench records")
    parser.add_argument("baseline", nargs="?",
                        help="baseline record (e.g. BENCH_r03.json)")
    parser.add_argument("current", nargs="?",
                        help="current record (e.g. BENCH_r05.json)")
    parser.add_argument("--check", metavar="DIR", nargs="?", const=".",
                        default=None,
                        help="informational mode: compare the two newest "
                             "BENCH_r*.json in DIR (default .), rc 0")
    parser.add_argument("--strict", action="store_true",
                        help="with --check: regressions exit non-zero")
    parser.add_argument("--threshold", action="append", default=[],
                        metavar="KEY=PCT",
                        help="override one rule's threshold, repeatable")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings")
    args = parser.parse_args(argv)

    overrides: Dict[str, float] = {}
    for spec in args.threshold:
        if "=" not in spec:
            parser.error(f"--threshold wants KEY=PCT, got {spec!r}")
        key, pct = spec.split("=", 1)
        try:
            overrides[key] = float(pct)
        except ValueError:
            parser.error(f"--threshold {spec!r}: {pct!r} is not a number")

    if args.check is not None:
        latest = _latest_records(args.check)
        if len(latest) < 2:
            print(f"bench-diff check: fewer than two BENCH_r*.json in "
                  f"{args.check!r}; nothing to compare")
            return 0
        base_path, cur_path = latest
        hard = args.strict
    else:
        if not args.baseline or not args.current:
            parser.error("need BASELINE and CURRENT records "
                         "(or --check [DIR])")
        base_path, cur_path = args.baseline, args.current
        hard = True

    try:
        base = derive_metrics(load_record(base_path))
        cur = derive_metrics(load_record(cur_path))
    except (OSError, ValueError) as e:
        print(f"bench-diff: {e}", file=sys.stderr)
        return 2

    findings = compare_records(base, cur, overrides)
    regressions = [f for f in findings if not f["ok"]]
    if args.json:
        print(json.dumps({"baseline": base_path, "current": cur_path,
                          "findings": findings,
                          "regressed": len(regressions)}))
    else:
        print(f"bench-diff: {base_path} -> {cur_path}")
        for line in provenance_lines(base, cur):
            print(f"  {line}")
        for line in render_findings(findings):
            print(f"  {line}")
        if regressions:
            print(f"  {len(regressions)} metric(s) REGRESSED")
            for line in forensic_lines(base_path, cur_path):
                print(f"  {line}")
    return 1 if regressions and hard else 0


if __name__ == "__main__":
    sys.exit(main())
