#!/usr/bin/env python
"""rsdl-trace: merge per-process recorder dumps; critical path + Perfetto.

The flight recorder (runtime/telemetry.py) dumps one JSONL per process
(``RSDL_TRACE_DIR`` makes every process — driver, trainers, supervised
queue servers — dump at exit). This tool merges those dumps onto one
clock and answers the questions one process can't:

- which tasks on which process form each epoch's **critical path**;
- per-stage **self time** vs the consumer's wait;
- the **straggler ranking** ((stage, task) by critical-path share);
- **what-if attribution**: "2x faster <stage> => -X% epoch time";
- a **Perfetto export** (`--perfetto out.json`): chrome-trace JSON with
  real pid/tid mapping, loadable in ui.perfetto.dev / chrome://tracing.

Usage::

    tools/rsdl_trace.py /run/rsdl-trace/              # dir of dumps
    tools/rsdl_trace.py dump1.jsonl dump2.jsonl --epoch 3
    tools/rsdl_trace.py /run/rsdl-trace/ --perfetto trace.json
    tools/rsdl_trace.py /run/rsdl-trace/ --json       # machine-readable

Stdlib-only: the analyzer (``runtime/trace.py``) is loaded straight by
file path, so this runs on hosts without numpy/pyarrow/jax (the
rsdl_top pattern — a monitoring sidecar, an operator laptop).
"""

import argparse
import glob
import importlib.util
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TRACE_PATH = os.path.join(_REPO_ROOT, "ray_shuffling_data_loader_tpu",
                           "runtime", "trace.py")


def _load_trace_module():
    """Load runtime/trace.py WITHOUT importing the package (whose
    __init__ pulls numpy/pyarrow); trace.py itself is stdlib-only."""
    spec = importlib.util.spec_from_file_location("_rsdl_trace",
                                                  _TRACE_PATH)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _expand_paths(args_paths):
    paths = []
    for p in args_paths:
        if os.path.isdir(p):
            paths.extend(sorted(glob.glob(os.path.join(p, "*.jsonl"))))
        else:
            paths.append(p)
    return paths


def render(analysis, processes) -> str:
    lines = []
    lines.append(f"processes: {len(processes)} "
                 f"(pids {', '.join(str(m['pid']) for m in processes)})")
    lines.append(f"epochs analyzed: {analysis['epochs']}  "
                 f"wall {analysis['wall_ms']:.1f} ms")
    lines.append("")
    header = f"{'stage':<18} {'critical-path ms':>16} {'%':>6} " \
             f"{'self ms':>10}"
    lines.append(header)
    lines.append("-" * len(header))
    self_ms = analysis["self_time_ms"]
    for entry in analysis["critical_path"]:
        lines.append(f"{entry['stage']:<18} {entry['cp_ms']:>16.1f} "
                     f"{entry['pct']:>6.1f} "
                     f"{self_ms.get(entry['stage'], 0.0):>10.1f}")
    stragglers = [s for s in analysis["stragglers"] if s["cp_ms"] > 0][:5]
    if stragglers:
        lines.append("")
        lines.append("stragglers (by critical-path share):")
        for i, s in enumerate(stragglers):
            lines.append(f"  {i + 1}. {s['stage']} task {s['task']}: "
                         f"{s['cp_ms']:.1f} ms on the path "
                         f"({s['self_ms']:.1f} ms self)")
    if analysis["whatif"]:
        lines.append("")
        lines.append("what-if (2x faster stage => epoch time saved):")
        for stage, w in sorted(analysis["whatif"].items(),
                               key=lambda kv: -kv[1]
                               ["epoch_time_saved_pct"]):
            lines.append(f"  {stage:<18} -{w['epoch_time_saved_pct']:.1f}%")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="merge recorder dumps; critical path, stragglers, "
                    "what-if, Perfetto export")
    parser.add_argument("paths", nargs="+",
                        help="dump files and/or directories of *.jsonl")
    parser.add_argument("--epoch", type=int, default=None,
                        help="analyze one epoch only")
    parser.add_argument("--speedup", type=float, default=2.0,
                        help="what-if speedup factor (default 2)")
    parser.add_argument("--perfetto", metavar="OUT",
                        help="write chrome-trace JSON here")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable analysis")
    args = parser.parse_args(argv)

    trace = _load_trace_module()
    paths = _expand_paths(args.paths)
    if not paths:
        print("no dump files found", file=sys.stderr)
        return 2
    merged = trace.merge_dumps(paths)
    if not merged["events"]:
        print("dumps parsed but contain no events", file=sys.stderr)
        return 2
    seeds = [m.get("trace_seed") for m in merged["processes"]
             if m.get("trace_seed") is not None]
    seed = seeds[0] if seeds else 0
    analysis = trace.analyze(merged["events"], epoch=args.epoch,
                             whatif_speedup=args.speedup)
    if args.perfetto:
        with open(args.perfetto, "w", encoding="utf-8") as f:
            json.dump(trace.to_perfetto(merged, seed=seed), f)
        print(f"perfetto trace -> {args.perfetto} "
              f"({len(merged['events'])} events)", file=sys.stderr)
    if args.json:
        analysis = dict(analysis)
        analysis.pop("path_segments", None)
        analysis["processes"] = [
            {"pid": m["pid"], "role": m.get("role"),
             "trace_seed": m.get("trace_seed")}
            for m in merged["processes"]]
        print(json.dumps(analysis))
    else:
        print(render(analysis, merged["processes"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
