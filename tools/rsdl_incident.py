#!/usr/bin/env python
"""rsdl-incident: triage renderer for auto-captured incident capsules.

A capsule (written by ``runtime/health.py`` when an SLO detector fires,
or on ``SIGUSR2``) is a self-contained directory::

    rsdl-incident-<pid>-<seq>[-<detector>]/
      capsule.json    # manifest: reason, detector verdict, pids, files
      history.json    # metrics time-series slice (rsdl-history-v1)
      metrics.prom    # merged multi-process exposition at capture time
      policy.json     # resolved policy snapshot + RSDL_* environment
      profile.folded  # sampling-profiler burst (flamegraph input)
      traces/rsdl-telemetry-<pid>-*.jsonl   # per-pid recorder dumps

This tool validates the layout and renders the triage story in one
screen: what fired and why, the activity series around the breach, the
merged critical path across every captured pid, and where to go next
(``rsdl_trace`` on the capsule's traces/, ``flamegraph.pl`` on the
profile). ``--json`` emits the machine form; exit code 0 means the
capsule parsed (1: invalid/incomplete, 2: usage).

Usage::

    tools/rsdl_incident.py /tmp/rsdl-incident-1234-1-throughput_droop/
    tools/rsdl_incident.py <capsule> --json

Stdlib-only: loads ``runtime/trace.py`` / ``runtime/history.py`` by
file path (the rsdl_top pattern), so it runs on hosts without
numpy/pyarrow/jax.
"""

import argparse
import glob
import importlib.util
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_RUNTIME = os.path.join(_REPO_ROOT, "ray_shuffling_data_loader_tpu",
                        "runtime")


def _load_by_path(stem: str):
    """Load a runtime/ module WITHOUT importing the package (whose
    __init__ pulls numpy/pyarrow); the runtime/ modules are stdlib-only.
    Modules that import siblings via the package path get the package
    pre-aliased to stubs only when the real package is unavailable."""
    try:
        import importlib
        return importlib.import_module(
            f"ray_shuffling_data_loader_tpu.runtime.{stem}")
    except ImportError:
        spec = importlib.util.spec_from_file_location(
            f"_rsdl_{stem}", os.path.join(_RUNTIME, f"{stem}.py"))
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        return module


def load_capsule(path: str) -> dict:
    """Parse + validate one capsule directory; raises ValueError on an
    invalid/incomplete capsule."""
    manifest_path = os.path.join(path, "capsule.json")
    if not os.path.isfile(manifest_path):
        raise ValueError(f"no capsule.json under {path!r} — not a capsule")
    with open(manifest_path, encoding="utf-8") as f:
        manifest = json.load(f)
    if manifest.get("schema") != "rsdl-incident-v1":
        raise ValueError(
            f"unknown capsule schema {manifest.get('schema')!r}")
    traces = sorted(glob.glob(os.path.join(path, "traces", "*.jsonl")))
    if not traces:
        raise ValueError("capsule has no trace dumps under traces/")
    out = {"path": path, "manifest": manifest, "traces": traces}
    history_path = os.path.join(path, "history.json")
    if os.path.isfile(history_path):
        with open(history_path, encoding="utf-8") as f:
            out["history"] = json.load(f)
        if out["history"].get("schema") != "rsdl-history-v1":
            raise ValueError("history.json is not an rsdl-history-v1 slice")
    policy_path = os.path.join(path, "policy.json")
    if os.path.isfile(policy_path):
        with open(policy_path, encoding="utf-8") as f:
            out["policy"] = json.load(f)
    return out


def analyze_traces(capsule: dict) -> dict:
    """Merged multi-pid trace analysis over the capsule's dumps."""
    trace = _load_by_path("trace")
    merged = trace.merge_dumps(capsule["traces"])
    pids = sorted({m["pid"] for m in merged["processes"]})
    analysis = trace.analyze(merged["events"]) if merged["events"] else None
    return {"pids": pids, "processes": merged["processes"],
            "analysis": analysis}


def _sparkline(values, width: int = 40) -> str:
    """Unicode block sparkline (terminal triage; the HTML report has the
    real charts)."""
    if not values:
        return ""
    blocks = " ▁▂▃▄▅▆▇█"
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    if len(values) > width:
        stride = len(values) / width
        values = [values[int(i * stride)] for i in range(width)]
    return "".join(blocks[1 + int((v - lo) / span * (len(blocks) - 2))]
                   for v in values)


def activity_series(capsule: dict) -> list:
    """Per-tick activity rate from the embedded history slice."""
    if "history" not in capsule:
        return []
    history = _load_by_path("history")
    ring = history.load_slice(capsule["history"])
    rates = ring.rate("rsdl_events_total", window_ticks=1)
    return [rate for _, rate in rates]


def render(capsule: dict, traced: dict) -> str:
    manifest = capsule["manifest"]
    verdict = manifest.get("verdict") or {}
    lines = []
    lines.append(f"incident capsule: {capsule['path']}")
    lines.append(f"reason: {manifest.get('reason')}   "
                 f"captured by pid {manifest.get('pid')} on "
                 f"{manifest.get('host')}")
    if verdict:
        lines.append(
            f"detector: {verdict.get('detector')}  value "
            f"{verdict.get('value')} vs threshold "
            f"{verdict.get('threshold')}  (episode {verdict.get('fires')})")
        if verdict.get("detail"):
            lines.append(f"  {verdict['detail']}")
    lines.append(f"trace dumps: {len(capsule['traces'])} file(s) from "
                 f"{len(traced['pids'])} pid(s) {traced['pids']}")
    rates = activity_series(capsule)
    if rates:
        lines.append(f"activity (events/s over the history window, "
                     f"{len(rates)} ticks):")
        lines.append(f"  {_sparkline(rates)}  "
                     f"[{min(rates):.0f} .. {max(rates):.0f}]")
    analysis = traced.get("analysis")
    if analysis and analysis.get("critical_path"):
        top = analysis["critical_path"][:3]
        lines.append("critical path: " + " > ".join(
            f"{e['stage']} {e['cp_ms']:.0f}ms" for e in top))
        stragglers = [s for s in analysis.get("stragglers", [])
                      if s.get("cp_ms", 0) > 0][:3]
        for i, s in enumerate(stragglers):
            lines.append(f"  straggler {i + 1}: {s['stage']} task "
                         f"{s['task']} ({s['cp_ms']:.0f}ms on path)")
    lines.append("")
    lines.append("next steps:")
    lines.append(f"  tools/rsdl_trace.py {capsule['path']}/traces/")
    if os.path.isfile(os.path.join(capsule["path"], "profile.folded")):
        lines.append(f"  flamegraph.pl {capsule['path']}/profile.folded "
                     "> flame.svg")
    lines.append(f"  tools/rsdl_report.py --capsule {capsule['path']} "
                 "-o report.html")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="validate + render an rsdl incident capsule")
    parser.add_argument("capsule", help="capsule directory")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable summary")
    args = parser.parse_args(argv)
    try:
        capsule = load_capsule(args.capsule)
        traced = analyze_traces(capsule)
    except (ValueError, OSError) as e:
        print(f"invalid capsule: {e}", file=sys.stderr)
        return 1
    if args.json:
        analysis = traced.get("analysis") or {}
        print(json.dumps({
            "path": capsule["path"],
            "reason": capsule["manifest"].get("reason"),
            "verdict": capsule["manifest"].get("verdict"),
            "pids": traced["pids"],
            "traces": [os.path.basename(t) for t in capsule["traces"]],
            "critical_path": analysis.get("critical_path"),
            "stragglers": (analysis.get("stragglers") or [])[:5],
            "activity_rates": activity_series(capsule),
        }))
    else:
        print(render(capsule, traced))
    return 0


if __name__ == "__main__":
    sys.exit(main())
