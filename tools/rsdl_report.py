#!/usr/bin/env python
"""rsdl-report: standalone-HTML run report over the ops-plane artifacts.

One self-contained HTML file (inline CSS/SVG, zero dependencies, opens
from a file:// path on an operator laptop) assembling the run story the
individual tools tell separately:

- **throughput / stall sparklines** from a history slice
  (``--history history.json``, or the one embedded in a capsule);
- **critical path + what-if** from recorder dumps (``--trace-dir``, or
  the capsule's ``traces/``);
- **health**: detector verdicts from a capsule and/or the bench
  record's ``health`` section;
- **worker scaling** from the newest bench record's ``worker_scaling``;
- **bench trajectory** across the committed ``BENCH_r*.json`` rounds.

Usage::

    tools/rsdl_report.py -o report.html                  # BENCH_r* in .
    tools/rsdl_report.py --history hist.json --trace-dir /tmp/rsdl-trace \
        -o report.html
    tools/rsdl_report.py --capsule <capsule-dir> -o report.html
    tools/rsdl_report.py --check [DIR]    # schema-only smoke, no HTML

``--check`` validates whatever inputs exist (bench records parse,
history slices load, trace dumps merge) and prints one line per source
— informational mode for format.sh, always exit 0 unless the arguments
themselves are unusable.

Stdlib-only: loads ``runtime/{trace,history}.py`` by file path (the
rsdl_top pattern).
"""

import argparse
import glob
import html
import importlib.util
import json
import os
import re
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_RUNTIME = os.path.join(_REPO_ROOT, "ray_shuffling_data_loader_tpu",
                        "runtime")


def _load_by_path(stem: str):
    try:
        import importlib
        return importlib.import_module(
            f"ray_shuffling_data_loader_tpu.runtime.{stem}")
    except ImportError:
        spec = importlib.util.spec_from_file_location(
            f"_rsdl_{stem}", os.path.join(_RUNTIME, f"{stem}.py"))
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        return module


# ---------------------------------------------------------------------------
# Input loading (each loader returns None when its source is absent)
# ---------------------------------------------------------------------------


def load_bench_records(directory: str):
    """``[(round, record)]`` sorted by round number; raw bench JSON or
    the committed ``BENCH_r*`` wrapper form."""
    out = []
    for path in sorted(glob.glob(os.path.join(directory, "BENCH_r*.json"))):
        match = re.search(r"BENCH_r(\d+)\.json$", path)
        if not match:
            continue
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        record = data.get("parsed") if isinstance(
            data.get("parsed"), dict) else data
        if not isinstance(record, dict) or "value" not in record:
            # A failed round commits a wrapper with parsed=null — part
            # of the trajectory's honesty, not a reason to refuse the
            # report; the round simply has no numbers to plot.
            continue
        out.append((int(match.group(1)), record))
    return out or None


def load_history(path):
    if not path or not os.path.isfile(path):
        return None
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("schema") != "rsdl-history-v1":
        raise ValueError(f"{path}: not an rsdl-history-v1 slice")
    return _load_by_path("history").load_slice(data)


def load_traces(trace_dir):
    if not trace_dir or not os.path.isdir(trace_dir):
        return None
    paths = sorted(glob.glob(os.path.join(trace_dir, "*.jsonl")))
    if not paths:
        return None
    trace = _load_by_path("trace")
    merged = trace.merge_dumps(paths)
    if not merged["events"]:
        return None
    return {
        "pids": sorted({m["pid"] for m in merged["processes"]}),
        "analysis": trace.analyze(merged["events"]),
    }


def load_capsule_manifest(capsule_dir):
    if not capsule_dir:
        return None
    path = os.path.join(capsule_dir, "capsule.json")
    with open(path, encoding="utf-8") as f:
        manifest = json.load(f)
    if manifest.get("schema") != "rsdl-incident-v1":
        raise ValueError(f"{path}: unknown capsule schema")
    return manifest


# ---------------------------------------------------------------------------
# HTML assembly (method: single-series sparklines carry no legend — the
# title names the series; values wear text ink, never the series color;
# every chart has a table twin; hover via native SVG <title> tooltips;
# light/dark from one custom-property block)
# ---------------------------------------------------------------------------

_CSS = """
.rsdl-report { color-scheme: light;
  --surface-1: #fcfcfb; --surface-2: #f1f0ee;
  --text-primary: #0b0b0b; --text-secondary: #52514e;
  --grid: #d8d7d3; --series-1: #2a78d6; --bad: #e34948;
  background: var(--surface-1); color: var(--text-primary);
  font: 14px/1.5 system-ui, sans-serif; max-width: 72rem;
  margin: 0 auto; padding: 1.5rem; }
@media (prefers-color-scheme: dark) {
  :root:where(:not([data-theme="light"])) .rsdl-report {
    color-scheme: dark;
    --surface-1: #1a1a19; --surface-2: #242423;
    --text-primary: #ffffff; --text-secondary: #c3c2b7;
    --grid: #3a3a38; --series-1: #3987e5; --bad: #e66767; } }
.rsdl-report h1 { font-size: 1.3rem; margin: 0 0 .25rem; }
.rsdl-report h2 { font-size: 1.05rem; margin: 1.75rem 0 .5rem; }
.rsdl-report .sub { color: var(--text-secondary); margin: 0 0 1rem; }
.rsdl-report table { border-collapse: collapse; margin: .5rem 0; }
.rsdl-report th, .rsdl-report td { padding: .3rem .75rem;
  border-bottom: 1px solid var(--grid); text-align: right; }
.rsdl-report th:first-child, .rsdl-report td:first-child {
  text-align: left; }
.rsdl-report th { color: var(--text-secondary); font-weight: 600; }
.rsdl-report .spark { display: block; margin: .25rem 0 .5rem; }
.rsdl-report .spark .line { fill: none; stroke: var(--series-1);
  stroke-width: 2; stroke-linejoin: round; }
.rsdl-report .spark .dot { fill: var(--series-1); }
.rsdl-report .spark .grid { stroke: var(--grid); stroke-width: 1; }
.rsdl-report .spark text { fill: var(--text-secondary); font-size: 11px; }
.rsdl-report .breach { color: var(--bad); font-weight: 600; }
.rsdl-report .stat { font-size: 1.6rem; font-weight: 650; }
.rsdl-report .stat small { font-size: .85rem; font-weight: 400;
  color: var(--text-secondary); margin-left: .35rem; }
"""


def _fmt(value) -> str:
    if value is None:
        return "–"
    if isinstance(value, float):
        return f"{value:,.1f}" if abs(value) >= 10 else f"{value:,.3f}"
    if isinstance(value, int):
        return f"{value:,}"
    return html.escape(str(value))


def spark_svg(points, width=560, height=72, unit="") -> str:
    """Single-series sparkline: 2px line, baseline grid, last-value dot
    with a direct label, per-point native tooltips."""
    if len(points) < 2:
        return "<p class='sub'>not enough points</p>"
    xs = [p[0] for p in points]
    ys = [p[1] for p in points]
    x_lo, x_hi = min(xs), max(xs)
    y_lo, y_hi = min(ys), max(ys)
    x_span = (x_hi - x_lo) or 1.0
    y_span = (y_hi - y_lo) or 1.0
    pad, label_w = 6, 84
    plot_w, plot_h = width - pad - label_w, height - 2 * pad

    def sx(x):
        return pad + (x - x_lo) / x_span * plot_w

    def sy(y):
        return pad + (1.0 - (y - y_lo) / y_span) * plot_h

    path = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in points)
    dots = "".join(
        f"<circle cx='{sx(x):.1f}' cy='{sy(y):.1f}' r='6' fill='none' "
        f"pointer-events='all'><title>{_fmt(y)}{unit}</title></circle>"
        for x, y in points)
    last_x, last_y = points[-1]
    return (
        f"<svg class='spark' width='{width}' height='{height}' "
        f"viewBox='0 0 {width} {height}' role='img'>"
        f"<line class='grid' x1='{pad}' y1='{sy(y_lo):.1f}' "
        f"x2='{pad + plot_w}' y2='{sy(y_lo):.1f}'/>"
        f"<polyline class='line' points='{path}'/>"
        f"<circle class='dot' cx='{sx(last_x):.1f}' "
        f"cy='{sy(last_y):.1f}' r='4'/>"
        f"<text x='{sx(last_x) + 8:.1f}' y='{sy(last_y) + 4:.1f}'>"
        f"{_fmt(last_y)}{unit}</text>"
        f"<text x='{pad}' y='{height - 1}'>min {_fmt(y_lo)}{unit} · "
        f"max {_fmt(y_hi)}{unit}</text>"
        f"{dots}</svg>")


def _table(headers, rows) -> str:
    head = "".join(f"<th>{html.escape(str(h))}</th>" for h in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{cell}</td>" for cell in row) + "</tr>"
        for row in rows)
    return f"<table><thead><tr>{head}</tr></thead><tbody>{body}</tbody></table>"


def _biggest_change(prev, rec) -> str:
    """One phrase per trajectory row: the largest relative move among
    the numeric keys two consecutive rounds share (the per-round 'what
    changed vs previous' cell — the forensic headline, with the full
    story in `tools/rsdl_regress.py prev cur`)."""
    best_key, best_pct = None, 0.0
    for key in set(prev) & set(rec):
        p, c = prev.get(key), rec.get(key)
        if isinstance(p, bool) or isinstance(c, bool):
            continue
        if not isinstance(p, (int, float)) or \
                not isinstance(c, (int, float)) or p == 0:
            continue
        pct = 100.0 * (c - p) / abs(p)
        if abs(pct) > abs(best_pct):
            best_key, best_pct = key, pct
    if best_key is None or abs(best_pct) < 2.0:
        return "–"
    return f"{html.escape(best_key)} {best_pct:+.0f}%"


def _section_bench(records) -> str:
    if not records:
        return ""
    latest_round, latest = records[-1]
    parts = [f"<h2>Bench trajectory (r{records[0][0]}–r{latest_round})</h2>"]
    parts.append(
        f"<p class='stat'>{_fmt(latest.get('value'))}"
        f"<small>{html.escape(str(latest.get('unit', 'rows/s')))} — "
        f"{html.escape(str(latest.get('metric', '')))} @ r{latest_round}"
        "</small></p>")
    pts = [(r, rec.get("value", 0.0)) for r, rec in records]
    parts.append(spark_svg(pts, unit=" rows/s"))
    rows = []
    prev_rec = None
    for r, rec in records:
        health = rec.get("health") or {}
        fires = health.get("fires")
        rows.append((
            f"r{r:02d}", _fmt(rec.get("value")),
            _fmt(rec.get("stall_pct")),
            _fmt(rec.get("train_mfu_pct")),
            html.escape(str(rec.get("bottleneck_stage") or "–")),
            html.escape(str(rec.get("executor_backend") or "–")),
            ("<span class='breach'>" + str(fires) + " FIRED</span>"
             if fires else ("0" if fires == 0 else "–")),
            (_biggest_change(prev_rec, rec) if prev_rec else "–"),
        ))
        prev_rec = rec
    parts.append(_table(
        ("round", "rows/s", "stall %", "mfu %", "bottleneck", "backend",
         "health fires", "vs prev"), rows))
    return "".join(parts)


def _section_history(ring) -> str:
    if ring is None:
        return ""
    parts = ["<h2>Time series (history ring)</h2>"]
    rates = ring.rate("rsdl_events_total", window_ticks=1)
    if len(rates) >= 2:
        parts.append("<p class='sub'>pipeline activity — recorder "
                     "events/s</p>")
        parts.append(spark_svg(rates, unit="/s"))
    waits = ring.series("rsdl_batch_wait_seconds_sum")
    stall_pts = []
    for i in range(1, len(waits)):
        (t0, w0), (t1, w1) = waits[i - 1], waits[i]
        if t1 - t0 > 0:
            stall_pts.append(
                (t1, min(100.0, 100.0 * max(0.0, w1 - w0) / (t1 - t0))))
    if len(stall_pts) >= 2:
        parts.append("<p class='sub'>consumer stall — batch-wait share "
                     "of wall clock</p>")
        parts.append(spark_svg(stall_pts, unit="%"))
    rss = ring.series("rsdl_process_rss_bytes")
    if len(rss) >= 2:
        parts.append("<p class='sub'>resident set size</p>")
        parts.append(spark_svg([(t, v / (1 << 20)) for t, v in rss],
                               unit=" MiB"))
    if len(parts) == 1:
        return ""
    return "".join(parts)


def _section_traces(traced) -> str:
    if not traced:
        return ""
    analysis = traced["analysis"]
    parts = [f"<h2>Critical path ({len(traced['pids'])} process(es): "
             f"{html.escape(str(traced['pids']))})</h2>"]
    self_ms = analysis.get("self_time_ms", {})
    rows = [(html.escape(e["stage"]), _fmt(e["cp_ms"]), _fmt(e["pct"]),
             _fmt(self_ms.get(e["stage"])))
            for e in analysis.get("critical_path", [])]
    parts.append(_table(("stage", "critical-path ms", "%", "self ms"),
                        rows))
    whatif = analysis.get("whatif") or {}
    if whatif:
        rows = [(html.escape(stage),
                 f"-{w['epoch_time_saved_pct']:.1f}%")
                for stage, w in sorted(
                    whatif.items(),
                    key=lambda kv: -kv[1]["epoch_time_saved_pct"])]
        parts.append("<p class='sub'>what-if: 2× faster stage → epoch "
                     "time saved</p>")
        parts.append(_table(("stage", "epoch time"), rows))
    return "".join(parts)


def _section_health(manifest, records) -> str:
    parts = []
    if manifest:
        verdict = manifest.get("verdict") or {}
        parts.append("<h2>Incident</h2>")
        parts.append(
            "<p><span class='breach'>"
            + html.escape(str(verdict.get("detector")
                              or manifest.get("reason", "incident")))
            + " FIRED</span> — "
            + html.escape(str(verdict.get("detail", "")))
            + f" (pids {html.escape(str(manifest.get('pids')))})</p>")
    latest = records[-1][1] if records else None
    health = (latest or {}).get("health")
    if health:
        parts.append("<h2>Health (latest bench record)</h2>")
        rows = []
        for phase, entry in sorted(health.get("by_phase", {}).items()):
            for name, d in sorted(entry.get("detectors", {}).items()):
                fires = d.get("fires", 0)
                rows.append((
                    html.escape(phase), html.escape(name),
                    ("<span class='breach'>" + str(fires)
                     + " FIRED</span>") if fires else "0",
                    html.escape(str((d.get("last") or {}).get(
                        "detail", "–"))),
                ))
        if rows:
            parts.append(_table(("phase", "detector", "fires", "last "
                                 "breach"), rows))
        else:
            parts.append(f"<p class='sub'>armed, {health.get('fires', 0)} "
                         "fires</p>")
    return "".join(parts)


def _section_latency(records, ring, manifest) -> str:
    """Delivery latency & freshness: the bench latency leg's headline
    p99s, the capsule's frozen per-queue quantiles, and a freshness
    sparkline (worst queue per tick) from the history ring."""
    parts = []
    latest = records[-1][1] if records else {}
    rows = []
    for key, label in (("delivery_p50_ms", "delivery p50"),
                       ("delivery_p95_ms", "delivery p95"),
                       ("delivery_p99_ms", "delivery p99"),
                       ("freshness_p99_ms", "freshness p99")):
        if latest.get(key) is not None:
            rows.append((html.escape(label), _fmt(latest.get(key)),
                         "ms"))
    capsule_latency = (manifest or {}).get("latency") or {}
    fresh_pts = []
    if ring is not None:
        for snap in ring.snapshots():
            series = snap["samples"].get(
                "rsdl_delivery_freshness_seconds")
            if series:
                fresh_pts.append((snap["t"], max(series.values())))
    if not rows and not capsule_latency and len(fresh_pts) < 2:
        return ""
    parts.append("<h2>Delivery latency &amp; freshness</h2>")
    if rows:
        parts.append("<p class='sub'>bench latency leg "
                     "(birth→delivered / birth→device)</p>")
        parts.append(_table(("span", "value", "unit"), rows))
    if capsule_latency:
        parts.append("<p class='sub'>capsule snapshot — per hop/queue "
                     "(seconds)</p>")
        parts.append(_table(
            ("series", "p50", "p95", "p99", "n"),
            [(html.escape(key), _fmt(entry.get("p50")),
              _fmt(entry.get("p95")), _fmt(entry.get("p99")),
              _fmt(entry.get("count")))
             for key, entry in sorted(capsule_latency.items())]))
    if len(fresh_pts) >= 2:
        parts.append("<p class='sub'>freshness — worst queue's payload "
                     "age at the consumer's last hop</p>")
        parts.append(spark_svg(fresh_pts, unit="s"))
    return "".join(parts)


def _section_scaling(records) -> str:
    latest = records[-1][1] if records else None
    scaling = (latest or {}).get("worker_scaling")
    if not scaling:
        return ""
    parts = ["<h2>Worker scaling</h2>"]
    legs = scaling.get("legs") or scaling.get("runs")
    if isinstance(legs, list) and legs:
        headers = sorted({k for leg in legs for k in leg
                          if isinstance(leg, dict)})
        rows = [tuple(_fmt(leg.get(h)) for h in headers) for leg in legs]
        parts.append(_table(headers, rows))
    else:
        rows = [(html.escape(str(k)), _fmt(v))
                for k, v in sorted(scaling.items())
                if not isinstance(v, (dict, list))]
        parts.append(_table(("metric", "value"), rows))
    return "".join(parts)


def _section_tenancy(records) -> str:
    """Per-tenant QoS columns from the contention bench leg (bench.py
    ``tenancy`` phase): fairness ratio against the configured weight
    split, per-tenant throughput, and the hot tenant's contended-vs-
    solo p99 multiple. Skips cleanly for records predating the leg."""
    rows = []
    for r, rec in records:
        if rec.get("tenancy_fairness_ratio") is None:
            continue
        rows.append((
            f"r{r:02d}",
            _fmt(rec.get("tenancy_fairness_ratio")),
            _fmt(rec.get("tenancy_weight_ratio")),
            _fmt(rec.get("tenancy_hot_rows_per_sec")),
            _fmt(rec.get("tenancy_cold_rows_per_sec")),
            _fmt(rec.get("tenancy_hot_p99_ms_solo")),
            _fmt(rec.get("tenancy_hot_p99_ms_contended")),
            _fmt(rec.get("tenancy_latency_ratio_x")),
            ("ok" if rec.get("tenancy_ok") else
             "<span class='breach'>FAIL</span>")
            if rec.get("tenancy_ok") is not None else "–",
        ))
    if not rows:
        return ""
    return "".join([
        "<h2>Tenancy contention</h2>",
        "<p class='sub'>hot streaming tenant vs cold batch-replay "
        "tenant on shared shards — delivered-rows ratio should track "
        "the weight split, hot p99 should hold near solo</p>",
        _table(("round", "fairness ratio", "weights", "hot rows/s",
                "cold rows/s", "hot p99 solo ms", "hot p99 cont ms",
                "p99 ratio", "ok"), rows),
    ])


def build_html(records, ring, traced, manifest) -> str:
    latest = records[-1][1] if records else {}
    sub = []
    if latest:
        sub.append(f"host_cpus {latest.get('host_cpus')}")
        sub.append(f"backend {latest.get('executor_backend')}")
        sub.append(f"workers {latest.get('executor_workers')}")
    body = (
        "<h1>rsdl run report</h1>"
        f"<p class='sub'>{html.escape(' · '.join(str(s) for s in sub))}</p>"
        + _section_health(manifest, records)
        + _section_latency(records, ring, manifest)
        + _section_history(ring)
        + _section_traces(traced)
        + _section_scaling(records)
        + _section_tenancy(records)
        + _section_bench(records))
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>rsdl run report</title>"
            f"<style>{_CSS}</style></head>"
            f"<body class='rsdl-report'>{body}</body></html>")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="standalone-HTML run report over bench records, "
                    "history slices, trace dumps and incident capsules")
    parser.add_argument("--bench-dir", default=".",
                        help="directory of BENCH_r*.json (default .)")
    parser.add_argument("--history", default=None,
                        help="history slice JSON (rsdl-history-v1)")
    parser.add_argument("--trace-dir", default=None,
                        help="directory of recorder dumps")
    parser.add_argument("--capsule", default=None,
                        help="incident capsule directory (uses its "
                             "embedded history + traces unless given "
                             "explicitly)")
    parser.add_argument("-o", "--out", default="rsdl_report.html",
                        help="output HTML path")
    parser.add_argument("--check", action="store_true",
                        help="schema-only smoke over the inputs; no "
                             "HTML write; informational rc 0")
    args = parser.parse_args(argv)

    history_path, trace_dir = args.history, args.trace_dir
    if args.capsule:
        if history_path is None:
            history_path = os.path.join(args.capsule, "history.json")
        if trace_dir is None:
            trace_dir = os.path.join(args.capsule, "traces")

    sources = []
    failures = []

    def _load(name, fn):
        try:
            value = fn()
        except (ValueError, OSError, KeyError) as e:
            failures.append(f"{name}: {e}")
            return None
        sources.append(f"{name}: "
                       + ("ok" if value is not None else "absent"))
        return value

    records = _load("bench-records",
                    lambda: load_bench_records(args.bench_dir))
    ring = _load("history", lambda: load_history(history_path))
    traced = _load("traces", lambda: load_traces(trace_dir))
    manifest = _load("capsule",
                     lambda: load_capsule_manifest(args.capsule))

    if args.check:
        for line in sources:
            print(f"rsdl-report: {line}")
        for line in failures:
            print(f"rsdl-report: INVALID {line}")
        print(f"rsdl-report: check done ({len(sources)} source(s), "
              f"{len(failures)} invalid)")
        return 0
    if failures:
        for line in failures:
            print(f"rsdl-report: INVALID {line}", file=sys.stderr)
        return 1
    if not any((records, ring, traced, manifest)):
        print("rsdl-report: no inputs found (no BENCH_r*.json, history, "
              "traces, or capsule)", file=sys.stderr)
        return 2
    text = build_html(records, ring, traced, manifest)
    with open(args.out, "w", encoding="utf-8") as f:
        f.write(text)
    print(f"rsdl-report: {args.out} ({len(text)} bytes; "
          + "; ".join(sources) + ")")
    return 0


if __name__ == "__main__":
    sys.exit(main())
