#!/usr/bin/env python
"""Stage microbenchmarks for the shuffle hot path.

The end-to-end bench (bench.py) answers "how fast is the pipeline"; this
tool answers "which KERNEL regressed" — each stage of the map/reduce hot
path is timed in isolation over synthetic data, so a per-stage drift
surfaces before the next full bench round (the r03 -> r05 ingest
regression hid for two PRs because only the end-to-end number was
watched).

Stages:

- ``parquet_decode``   — pq.read_table of a page-cache-warm file
- ``partition_fused``  — the one-kernel hash partition plan
                         (ops.plan_partition_flat)
- ``partition_philox`` — the legacy two-stage Philox draw + counting sort
- ``fused_gather``     — shuffle._fused_reduce over one source
- ``ipc_handoff``      — Arrow IPC segment write + zero-copy mmap open
                         (the process backend's shm handoff)
- ``crc``              — frame-checksum throughput: native.crc32 (HW or
                         slice-by-8) timed as the stage, zlib.crc32 as
                         the comparison extra, equality spot-checked
                         across buffer sizes and alignments first
- ``wire_syscall``     — GET-response wire write: one sendmsg
                         scatter-gather batch timed as the stage, the
                         legacy per-buffer sendall loop as the
                         comparison extra (socketpair, drained by a
                         reader thread)
- ``telemetry_record`` — per-event flight-recorder cost (enabled path)

Output: a JSON record on stdout whose ``stages`` block mirrors the bench
record's ``stage_latency_ms`` schema (``p50_ms``/``p95_ms``/``p99_ms``
per stage) plus throughput fields, and a human table on stderr.

``--check`` is the format.sh informational mode: small row count, always
rc 0 — the gate surfaces the numbers without failing the build (the hard
regression gate stays ``bench.py --baseline`` / ``tools/rsdl_bench_diff``
at measurement time).
"""

import argparse
import json
import os
import statistics
import sys
import tempfile
import timeit

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _time_stage(fn, repeats):
    """Per-repeat wall seconds (first call primed separately, so one-time
    costs — import, page-cache fill, pool spin-up — don't pollute p50)."""
    fn()
    samples = []
    for _ in range(repeats):
        start = timeit.default_timer()
        fn()
        samples.append(timeit.default_timer() - start)
    return samples


def _stage_record(samples, rows):
    ordered = sorted(samples)

    def pct(p):
        return ordered[min(len(ordered) - 1, int(p / 100 * len(ordered)))]

    p50 = statistics.median(ordered)
    return {
        "p50_ms": round(p50 * 1e3, 4),
        "p95_ms": round(pct(95) * 1e3, 4),
        "p99_ms": round(pct(99) * 1e3, 4),
        "rows_per_s": round(rows / p50, 1) if p50 > 0 else None,
        "ns_per_row": round(1e9 * p50 / rows, 3) if rows else None,
    }


def _crc_stage(rows, repeats):
    """native.crc32 over an 8-bytes-per-row buffer (the stage; what the
    wire/spill/journal paths actually call) with zlib.crc32 as the
    comparison extra. ``rows`` maps to bytes/8 so ns_per_row stays
    comparable with the other stages' per-row accounting."""
    import zlib

    import numpy as np

    from ray_shuffling_data_loader_tpu import native

    nbytes = rows * 8
    blob = np.random.default_rng(1).integers(
        0, 256, size=nbytes, dtype=np.uint8).tobytes()
    # Equality first, speed second: odd sizes and misaligned views are
    # where a word-at-a-time CRC kernel goes wrong.
    view = memoryview(blob)
    for size in (0, 1, 7, 8, 63, 64, 4095, min(1 << 16, nbytes)):
        for offset in (0, 1, 3, 7):
            piece = view[offset:offset + size]
            assert native.crc32(piece) == (zlib.crc32(piece) & 0xFFFFFFFF)

    native_samples = _time_stage(lambda: native.crc32(blob), repeats)
    zlib_samples = _time_stage(lambda: zlib.crc32(blob), repeats)
    record = _stage_record(native_samples, rows)
    zlib_p50 = statistics.median(zlib_samples)
    record["backend"] = native.crc_backend()
    record["zlib_p50_ms"] = round(zlib_p50 * 1e3, 4)
    native_p50 = statistics.median(native_samples)
    record["speedup_vs_zlib_x"] = (round(zlib_p50 / native_p50, 2)
                                   if native_p50 > 0 else None)
    return record


def _wire_syscall_stage(rows, repeats):
    """One GET-response worth of frames over a socketpair: the sendmsg
    scatter-gather batch (the stage) vs the legacy per-buffer sendall
    loop (the comparison extra). A reader thread drains so the send side
    measures syscall + copy cost, not backpressure."""
    import socket
    import threading

    import ray_shuffling_data_loader_tpu.multiqueue_service as mq

    # ~GET-batch shape: many small header-sized buffers interleaved with
    # payload chunks, sized so total bytes track ``rows`` (8 B/row).
    total = rows * 8
    chunk = 16 << 10
    nframes = max(1, total // (chunk + 64))
    payload = b"\xa5" * chunk
    header = b"\x5a" * 64
    buffers = [header, payload] * nframes

    sender, receiver = socket.socketpair()
    done = threading.Event()

    def drain():
        try:
            while receiver.recv(1 << 20):
                pass
        except OSError:
            pass
        done.set()

    reader = threading.Thread(target=drain, daemon=True)
    reader.start()
    try:
        sendmsg_samples = _time_stage(
            lambda: mq._sendmsg_all(sender, buffers), repeats)

        def sendall_loop():
            for buf in buffers:
                # The measured LEGACY leg — the syscall-per-buffer shape
                # this stage exists to compare against.
                # rsdl-lint: disable=sendall-in-loop
                sender.sendall(buf)

        sendall_samples = _time_stage(sendall_loop, repeats)
    finally:
        sender.close()
        done.wait(timeout=5)
        receiver.close()

    record = _stage_record(sendmsg_samples, rows)
    sendall_p50 = statistics.median(sendall_samples)
    sendmsg_p50 = statistics.median(sendmsg_samples)
    record["buffers_per_batch"] = len(buffers)
    record["sendall_p50_ms"] = round(sendall_p50 * 1e3, 4)
    record["speedup_vs_sendall_x"] = (round(sendall_p50 / sendmsg_p50, 2)
                                      if sendmsg_p50 > 0 else None)
    return record


def run(rows: int, repeats: int, num_reducers: int) -> dict:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    import importlib
    sh = importlib.import_module("ray_shuffling_data_loader_tpu.shuffle")
    from ray_shuffling_data_loader_tpu import procpool
    from ray_shuffling_data_loader_tpu.ops import partition as ops_p
    from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_tel

    rng = np.random.default_rng(0)
    table = pa.table({
        "dense": rng.random(rows),
        "sparse": rng.integers(0, 1 << 20, rows).astype(np.int64),
        "label": rng.integers(0, 2, rows).astype(np.int32),
    })
    stages = {}

    with tempfile.TemporaryDirectory(prefix="rsdl-microbench-") as tmp:
        parquet_path = os.path.join(tmp, "part.parquet")
        pq.write_table(table, parquet_path)

        stages["parquet_decode"] = _stage_record(
            _time_stage(lambda: pq.read_table(parquet_path), repeats), rows)

        stages["partition_fused"] = _stage_record(
            _time_stage(lambda: ops_p.plan_partition_flat(
                rows, num_reducers, seed=0, epoch=0, file_index=0,
                nthreads=sh._SCATTER_GATHER_THREADS), repeats), rows)

        def philox():
            gen = ops_p.map_rng(0, 0, 0)
            assignments = ops_p.assign_reducers(rows, num_reducers, gen)
            ops_p.partition_indices(assignments, num_reducers)

        stages["partition_philox"] = _stage_record(
            _time_stage(philox, repeats), rows)

        cols = {name: table.column(name).chunk(0).to_numpy()
                for name in table.column_names}
        sources = [(cols, None, rows)]
        stages["fused_gather"] = _stage_record(
            _time_stage(lambda: sh._fused_reduce(
                0, seed=0, epoch=0, sources=list(sources),
                column_names=table.column_names,
                gather_threads=sh._SCATTER_GATHER_THREADS), repeats), rows)

        seg_dir = procpool.shm_base_dir()
        seg_path = os.path.join(
            tempfile.mkdtemp(prefix="rsdl-microbench-", dir=seg_dir),
            "seg.arrow")

        def handoff():
            procpool.write_table_segment(table, seg_path)
            procpool.open_table_segment(seg_path)

        try:
            stages["ipc_handoff"] = _stage_record(
                _time_stage(handoff, repeats), rows)
        finally:
            try:
                os.unlink(seg_path)
                os.rmdir(os.path.dirname(seg_path))
            except OSError:
                pass

    stages["crc"] = _crc_stage(rows, repeats)
    stages["wire_syscall"] = _wire_syscall_stage(rows, repeats)

    per_event_s = rt_tel.measure_record_overhead()
    stages["telemetry_record"] = {
        "p50_ms": round(per_event_s * 1e3, 6),
        "p95_ms": None,
        "p99_ms": None,
        "rows_per_s": None,
        "ns_per_event": round(per_event_s * 1e9, 1),
    }

    return {
        "metric": "microbench_stage_latency",
        "rows": rows,
        "repeats": repeats,
        "num_reducers": num_reducers,
        "host_cpus": os.cpu_count(),
        "stages": stages,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--rows", type=int, default=1_000_000)
    parser.add_argument("--repeats", type=int, default=5)
    parser.add_argument("--reducers", type=int, default=16)
    parser.add_argument("--json", metavar="PATH",
                        help="also write the record to PATH")
    parser.add_argument("--check", action="store_true",
                        help="format.sh informational mode: quick sizes, "
                             "always exit 0")
    args = parser.parse_args(argv)
    if args.check:
        args.rows = min(args.rows, 200_000)
        args.repeats = min(args.repeats, 3)
    try:
        record = run(args.rows, args.repeats, args.reducers)
    except Exception as e:  # noqa: BLE001 - informational tool
        print(f"rsdl-microbench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 0 if args.check else 1
    for name, stage in record["stages"].items():
        rate = stage.get("rows_per_s")
        rate_txt = f"{rate:>14,.0f} rows/s" if rate else " " * 21
        extra = (f"  {stage['ns_per_event']:.0f} ns/event"
                 if "ns_per_event" in stage else
                 f"  {stage['ns_per_row']:.1f} ns/row"
                 if stage.get("ns_per_row") is not None else "")
        print(f"# {name:<18} p50 {stage['p50_ms']:>10.3f} ms"
              f"{rate_txt}{extra}", file=sys.stderr)
    payload = json.dumps(record, indent=2)
    print(payload)
    if args.json:
        with open(args.json, "w") as f:
            f.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
