#!/usr/bin/env python
"""rsdl-top: live per-stage throughput + stall table for a running loader.

Tails the Prometheus exposition a pipeline exports (file via
``RSDL_METRICS_FILE=/run/rsdl.prom``, or the localhost endpoint via
``RSDL_METRICS_PORT``) and renders the one view that matters online:
which stage is doing the work, at what rate, at what latency, and how
much of the consumer's time is stalled waiting on the loader.

Usage::

    tools/rsdl_top.py --file /run/rsdl.prom            # refresh loop
    tools/rsdl_top.py --url http://127.0.0.1:9200/metrics
    tools/rsdl_top.py --file /run/rsdl.prom --once     # one snapshot
    tools/rsdl_top.py --dir /run/rsdl-shards           # federated view

``--dir`` (default ``$RSDL_TELEMETRY_DIR``) reads the per-pid metric
shards every federated process writes (driver, procpool workers,
supervised queue servers), renders the table over the MERGED totals,
and appends a per-process line for every shard — pool-worker pids (the
``rsdl_executor_worker_up`` gauge) are marked, so the processes doing
the map/reduce work are visible instead of under-counted.

Stdlib-only: the exposition parser is loaded straight from
``runtime/metrics.py`` by file path, so this tool runs on hosts without
numpy/pyarrow/jax installed (a monitoring sidecar, an operator laptop).
Rates and interval percentiles come from deltas between consecutive
samples; ``--once`` prints process-lifetime totals instead.
"""

import argparse
import importlib.util
import os
import sys
import time
import urllib.request

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_METRICS_PATH = os.path.join(_REPO_ROOT, "ray_shuffling_data_loader_tpu",
                             "runtime", "metrics.py")


def _load_metrics_module():
    """Load runtime/metrics.py WITHOUT importing the package (whose
    __init__ pulls numpy/pyarrow); metrics.py itself is stdlib-only."""
    spec = importlib.util.spec_from_file_location("_rsdl_metrics",
                                                  _METRICS_PATH)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


_metrics = _load_metrics_module()
parse_exposition = _metrics.parse_exposition

#: Stage display order (mirrors runtime/telemetry.py STAGES).
STAGES = ("map_read", "reduce", "queue_wait", "fetch", "convert",
          "device_transfer", "train_step")


def read_exposition(file: str = None, url: str = None) -> dict:
    if url:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return parse_exposition(resp.read().decode())
    with open(file, encoding="utf-8") as f:
        return parse_exposition(f.read())


def read_shard_dir(directory: str) -> "tuple[dict, dict]":
    """``(merged_samples, per_pid_shards)`` over a federation shard dir
    (runtime/metrics.py read_shards/merge_series, loaded by path)."""
    shards = _metrics.read_shards(directory)
    merged, _ = _metrics.merge_series(list(shards.values()))
    return merged, shards


def render_processes(shards: dict, merged: dict) -> str:
    """Per-process lines: one row per shard pid, pool-worker pids (the
    rsdl_executor_worker_up gauge) marked — the blind spot the
    federation exists to close."""
    worker_pids = {dict(labels).get("pid")
                   for labels, value in merged.get(
                       "rsdl_executor_worker_up", {}).items()
                   if value >= 1}
    header = (f"{'pid':<9} {'role':<8} {'events':>10} {'tasks':>7} "
              f"{'stage s':>9} {'age':>6}")
    lines = ["", header, "-" * len(header)]
    for pid, (samples, _types, age_s) in sorted(shards.items()):
        events = sum(samples.get("rsdl_events_total", {}).values())
        tasks = sum(samples.get("rsdl_worker_tasks_total", {}).values())
        stage_s = sum(samples.get("rsdl_stage_seconds_sum", {}).values())
        role = "worker" if str(pid) in worker_pids else "proc"
        lines.append(f"{pid:<9} {role:<8} {int(events):>10} "
                     f"{int(tasks):>7} {stage_s:>9.2f} {age_s:>5.0f}s")
    return "\n".join(lines)


def _series(parsed: dict, name: str, **want) -> dict:
    """{labels_dict_frozen: value} for samples of ``name`` matching the
    given label filters (ignoring extra labels like ``le``)."""
    out = {}
    for labels, value in parsed.get(name, {}).items():
        d = dict(labels)
        if all(d.get(k) == v for k, v in want.items()):
            out[labels] = value
    return out


def _stage_scalar(parsed: dict, suffix: str, stage: str) -> float:
    for labels, value in parsed.get(f"rsdl_stage_seconds{suffix}",
                                    {}).items():
        if dict(labels).get("stage") == stage:
            return value
    return 0.0


def _stage_buckets(parsed: dict, stage: str) -> dict:
    """{le_bound_float: cumulative_count} for one stage."""
    out = {}
    for labels, value in parsed.get("rsdl_stage_seconds_bucket",
                                    {}).items():
        d = dict(labels)
        if d.get("stage") != stage or "le" not in d:
            continue
        le = float("inf") if d["le"] == "+Inf" else float(d["le"])
        out[le] = value
    return out


def _p95_from_bucket_delta(now: dict, before: dict) -> float:
    """p95 (seconds) of the interval distribution between two cumulative
    bucket snapshots, by linear interpolation in the winning bucket."""
    bounds = sorted(now)
    deltas = []
    prev_now = prev_before = 0.0
    for bound in bounds:
        d = ((now[bound] - prev_now)
             - (before.get(bound, 0.0) - prev_before))
        deltas.append((bound, max(0.0, d)))
        prev_now, prev_before = now[bound], before.get(bound, 0.0)
    total = sum(d for _, d in deltas)
    if total <= 0:
        return 0.0
    rank = 0.95 * total
    seen = 0.0
    lo = 0.0
    for bound, d in deltas:
        if d and seen + d >= rank:
            hi = bound if bound != float("inf") else lo
            return lo + (hi - lo) * ((rank - seen) / d)
        seen += d
        if bound != float("inf"):
            lo = bound
    return lo


def _scalar(parsed: dict, name: str) -> float:
    return sum(parsed.get(name, {}).values())


def _by_label(parsed: dict, name: str, label: str) -> dict:
    """{label_value: summed value} for one metric's samples."""
    out = {}
    for labels, value in parsed.get(name, {}).items():
        key = dict(labels).get(label)
        if key is not None:
            out[key] = out.get(key, 0.0) + value
    return out


def _human_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.0f} {unit}" if unit == "B" else f"{n:.1f} {unit}"
        n /= 1024.0
    return f"{n:.1f} TB"


def render_shards(parsed: dict) -> list:
    """One line per serving-plane shard (multiqueue_service v3): queue
    depth, handle-hit share of table frames, actual wire bytes, and the
    compression saving — the per-shard federated view the shard map
    spreads across processes."""
    depth = _by_label(parsed, "rsdl_queue_shard_depth", "shard")
    hits = _by_label(parsed, "rsdl_queue_handle_hits_total", "shard")
    misses = _by_label(parsed, "rsdl_queue_handle_misses_total", "shard")
    wire = _by_label(parsed, "rsdl_queue_bytes_on_wire_total", "shard")
    saved = _by_label(parsed, "rsdl_queue_compression_saved_bytes_total",
                      "shard")
    shards = sorted(set(depth) | set(hits) | set(misses) | set(wire),
                    key=lambda s: (len(s), s))
    if not shards:
        return []
    lines = ["serving shards:"]
    for shard in shards:
        h, m = hits.get(shard, 0.0), misses.get(shard, 0.0)
        hit_pct = 100.0 * h / (h + m) if h + m else 0.0
        line = (f"  shard {shard}: depth {int(depth.get(shard, 0)):>5}  "
                f"handle-hit {hit_pct:5.1f}%  "
                f"wire {_human_bytes(wire.get(shard, 0.0)):>10}")
        if saved.get(shard):
            line += f"  saved {_human_bytes(saved[shard])}"
        lines.append(line)
    return lines


def render_storage(parsed: dict) -> list:
    """Per-tier storage-cache lines (storage/cache.py): hit share,
    eviction/corruption counts and resident bytes per tier, plus the
    prefetch plane's issued/hit/canceled efficiency and total remote
    bytes — the "is the cold path actually caching" one-liner."""
    hits = _by_label(parsed, "rsdl_storage_hits_total", "tier")
    misses = _by_label(parsed, "rsdl_storage_misses_total", "tier")
    evictions = _by_label(parsed, "rsdl_storage_evictions_total", "tier")
    corrupt = _by_label(parsed, "rsdl_storage_corrupt_total", "tier")
    tier_bytes = _by_label(parsed, "rsdl_storage_tier_bytes", "tier")
    tiers = [t for t in ("hot", "disk", "remote")
             if t in set(hits) | set(misses) | set(tier_bytes)]
    if not tiers:
        return []
    lines = ["storage tiers:"]
    for tier in tiers:
        h, m = hits.get(tier, 0.0), misses.get(tier, 0.0)
        hit_pct = 100.0 * h / (h + m) if h + m else 0.0
        line = (f"  {tier:<6} hit {hit_pct:5.1f}% ({int(h)}/{int(h + m)})"
                f"  bytes {_human_bytes(tier_bytes.get(tier, 0.0)):>10}")
        if evictions.get(tier):
            line += f"  evicted {int(evictions[tier])}"
        if corrupt.get(tier):
            line += f"  CORRUPT {int(corrupt[tier])}"
        lines.append(line)
    issued = _scalar(parsed, "rsdl_storage_prefetch_issued_total")
    if issued:
        p_hits = _scalar(parsed, "rsdl_storage_prefetch_hits_total")
        canceled = _scalar(parsed, "rsdl_storage_prefetch_canceled_total")
        lines.append(f"  prefetch: {int(issued)} issued  "
                     f"{int(p_hits)} hit "
                     f"({100.0 * p_hits / issued:.0f}% efficient)  "
                     f"{int(canceled)} canceled")
    remote = _scalar(parsed, "rsdl_storage_remote_bytes_read_total")
    if remote:
        lines.append(f"  remote bytes read: {_human_bytes(remote)}")
    return lines


def render_streaming(parsed: dict) -> list:
    """One streaming line (streaming/): current window, watermark lag in
    stream seconds (the watermark_lag detector's series), and the late-
    event count — the "is online training keeping up" one-liner. Silent
    when the process never streamed."""
    closed = _scalar(parsed, "rsdl_stream_windows_closed_total")
    events = _scalar(parsed, "rsdl_stream_events_admitted_total")
    if not closed and not events:
        return []
    window = _scalar(parsed, "rsdl_stream_window")
    lag = _scalar(parsed, "rsdl_stream_watermark_lag_seconds")
    late = _scalar(parsed, "rsdl_stream_late_events_total")
    line = (f"streaming: window {int(window)} ({int(closed)} closed, "
            f"{int(events)} events)   lag {lag:.1f}s")
    if late:
        by_policy = _by_label(parsed, "rsdl_stream_late_events_total",
                              "policy")
        detail = " ".join(f"{policy}={int(n)}"
                          for policy, n in sorted(by_policy.items()))
        line += f"   late {int(late)} ({detail})"
    return [line]


def render_tenants(parsed: dict) -> list:
    """Per-tenant QoS lines (tenancy/): delivered bytes, in-flight
    replay vs fair-share budget, cache residency vs quota, prefetch
    throttles, and the delivery-latency sketch's p50/p99 — the "is the
    weighted-fair scheduler actually honoring the weights" view.
    Silent in single-tenant deployments (no tenant series minted)."""
    delivered = _by_label(parsed, "rsdl_tenant_bytes_delivered_total",
                          "tenant")
    replay = _by_label(parsed, "rsdl_tenant_replay_bytes", "tenant")
    budget = _by_label(parsed, "rsdl_tenant_budget_bytes", "tenant")
    cache = _by_label(parsed, "rsdl_tenant_cache_bytes", "tenant")
    quota = _by_label(parsed, "rsdl_tenant_cache_quota_bytes", "tenant")
    throttled = _by_label(parsed, "rsdl_tenant_prefetch_throttled_total",
                          "tenant")
    tenants = sorted(set(delivered) | set(replay) | set(cache))
    if not tenants:
        return []
    sketch = parsed.get("rsdl_tenant_delivery_latency_seconds_centroid", {})
    stats = _metrics.sketch_quantiles(
        {"rsdl_tenant_delivery_latency_seconds_centroid": sketch},
        "rsdl_tenant_delivery_latency_seconds",
        hop="queued_to_delivered") if sketch else {}
    by_tenant_lat = {}
    for labels, entry in stats.items():
        tenant = dict(labels).get("tenant")
        if tenant is not None:
            by_tenant_lat[tenant] = entry
    lines = ["tenants:"]
    for tenant in tenants:
        line = (f"  {tenant:<12} "
                f"delivered {_human_bytes(delivered.get(tenant, 0.0)):>10}")
        if tenant in budget:
            line += (f"  inflight {_human_bytes(replay.get(tenant, 0.0))}"
                     f"/{_human_bytes(budget[tenant])}")
        if tenant in cache:
            line += f"  cache {_human_bytes(cache[tenant])}"
            if quota.get(tenant):
                line += f"/{_human_bytes(quota[tenant])}"
        if throttled.get(tenant):
            line += f"  throttled {int(throttled[tenant])}"
        entry = by_tenant_lat.get(tenant)
        if entry:
            line += (f"  p50 {entry['p50'] * 1e3:.1f}ms "
                     f"p99 {entry['p99'] * 1e3:.1f}ms")
        lines.append(line)
    waiting = _scalar(parsed, "rsdl_admission_waiting")
    used = _scalar(parsed, "rsdl_admission_used_bytes")
    rejected = sum(v for labels, v in
                   parsed.get("rsdl_admission_decisions_total", {}).items()
                   if dict(labels).get("action") == "reject")
    if waiting or used or rejected:
        lines.append(f"  admission: {_human_bytes(used)} charged  "
                     f"{int(waiting)} waiting  {int(rejected)} rejected")
    return lines


def render_membership(parsed: dict) -> list:
    """One membership line (membership/): current view id, live vs
    suspect rank counts, per-rank incarnations, the fenced-frame count,
    and the age of the last view transition — the "did the world just
    resize, and is anything flapping" one-liner. Silent when the
    process never ran elastic membership."""
    import time as _time
    view = _scalar(parsed, "rsdl_member_view_id")
    live = _scalar(parsed, "rsdl_member_live")
    transitions = sum(
        parsed.get("rsdl_member_transitions_total", {}).values())
    if not live and not transitions:
        return []
    suspect = _scalar(parsed, "rsdl_member_suspect")
    fenced = _scalar(parsed, "rsdl_member_fenced_frames_total")
    flaps = _scalar(parsed, "rsdl_member_flaps_total")
    incarnations = _by_label(parsed, "rsdl_member_incarnation", "rank")
    line = (f"membership: view {int(view)}   live {int(live)}"
            f"  suspect {int(suspect)}")
    if incarnations:
        detail = " ".join(
            f"r{rank}:{int(inc)}"
            for rank, inc in sorted(incarnations.items(),
                                    key=lambda kv: int(kv[0])))
        line += f"   incarnations {detail}"
    last = _scalar(parsed, "rsdl_member_last_transition_unixtime")
    if last:
        # Cross-process age: the gauge IS a serialized wall-clock
        # timestamp, so wall clock is the only comparable clock here.
        # rsdl-lint: disable=wallclock-interval
        age = max(0.0, _time.time() - last)
        line += f"   last transition {age:.0f}s ago"
    if fenced:
        line += f"   FENCED {int(fenced)}"
    if flaps:
        line += f"   flaps {int(flaps)}"
    return [line]


def render_rebalance(parsed: dict) -> list:
    """One rebalance line (rebalance/): placement generation, live
    override count, decisions by kind, fenced zombie frames, and the
    age of the last committed move — the "did the serving plane just
    move a rank, and did anything leak" one-liner. Silent when no
    rebalance controller ever ran."""
    import time as _time
    generation = _scalar(parsed, "rsdl_rebalance_generation")
    decisions = _by_label(parsed, "rsdl_rebalance_decisions_total", "kind")
    if not generation and not decisions:
        return []
    overrides = _scalar(parsed, "rsdl_rebalance_overrides")
    moves = _scalar(parsed, "rsdl_rebalance_moves_total")
    fenced = _scalar(parsed, "rsdl_rebalance_fenced_frames_total")
    line = (f"rebalance: generation {int(generation)}   "
            f"moves {int(moves)}   overrides {int(overrides)}")
    if decisions:
        detail = " ".join(f"{kind}={int(n)}"
                          for kind, n in sorted(decisions.items()))
        line += f"   decisions {detail}"
    last = _scalar(parsed, "rsdl_rebalance_last_move_unixtime")
    if last:
        # Cross-process age: the gauge IS a serialized wall-clock
        # timestamp, so wall clock is the only comparable clock here.
        # rsdl-lint: disable=wallclock-interval
        age = max(0.0, _time.time() - last)
        line += f"   last move {age:.0f}s ago"
    if fenced:
        line += f"   FENCED {int(fenced)}"
    return [line]


def render_latency(parsed: dict, before: dict = None) -> list:
    """Per-queue delivery-latency lines (runtime/latency.py sketch):
    p50/p95/p99 of the end-to-end birth->delivered hop plus the queue's
    freshness gauge. In refresh mode the quantiles are computed over the
    INTERVAL's centroid deltas (cumulative counts subtract exactly);
    ``--once`` shows lifetime quantiles."""
    series = "rsdl_delivery_latency_seconds_centroid"
    now = parsed.get(series, {})
    if not now:
        return []
    if before is not None:
        base = before.get(series, {})
        now = {labels: value - base.get(labels, 0.0)
               for labels, value in now.items()
               if value - base.get(labels, 0.0) > 0}
        if not now:
            return []
    stats = _metrics.sketch_quantiles(
        {series: now}, "rsdl_delivery_latency_seconds",
        hop="birth_to_delivered")
    if not stats:
        return []
    fresh = _by_label(parsed, "rsdl_delivery_freshness_seconds", "queue")
    lines = ["delivery latency (birth->delivered):"]
    for labels, entry in sorted(stats.items()):
        queue = dict(labels).get("queue", "?")
        line = (f"  queue {queue}: p50 {entry['p50'] * 1e3:7.1f}ms  "
                f"p95 {entry['p95'] * 1e3:7.1f}ms  "
                f"p99 {entry['p99'] * 1e3:7.1f}ms  "
                f"n {int(entry['count'])}")
        if queue in fresh:
            line += f"  fresh {fresh[queue]:.1f}s"
        lines.append(line)
    return lines


def check_latency() -> int:
    """Sketch merge self-test (``--check-latency``, wired into
    format.sh's informational block): observe disjoint values in two
    registries, render -> parse -> federation-merge, and require the
    merged quantiles to equal a directly-merged sketch's — so a schema
    drift anywhere in the sketch's shard exposition (series suffix,
    centroid label, merge math) fails fast, before a real run's p99
    silently reads wrong."""
    name = "rsdl_delivery_latency_seconds"
    values_a = [0.002, 0.004, 0.008, 0.05]
    values_b = [0.1, 0.9, 2.0]
    regs = [_metrics.Registry(), _metrics.Registry()]
    for reg, values in zip(regs, (values_a, values_b)):
        sk = reg.sketch(name, "self-test", hop="birth_to_delivered",
                        queue="0")
        for v in values:
            sk.observe(v)
    shards = [_metrics.parse_exposition_typed(reg.render())
              for reg in regs]
    merged, types = _metrics.merge_series(shards)
    if types.get(name) != "sketch":
        print(f"check-latency: TYPE line lost (got {types.get(name)!r})")
        return 1
    stats = _metrics.sketch_quantiles(merged, name)
    direct = _metrics.Sketch()
    for v in values_a + values_b:
        direct.observe(v)
    for labels, entry in stats.items():
        if int(entry["count"]) != direct.count:
            print(f"check-latency: merged count {entry['count']} != "
                  f"direct {direct.count}")
            return 1
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            if abs(entry[key] - direct.percentile(q)) > 1e-12:
                print(f"check-latency: merged {key} {entry[key]} != "
                      f"direct {direct.percentile(q)}")
                return 1
    if not stats:
        print("check-latency: no sketch series survived the round-trip")
        return 1
    # The merged exposition must itself round-trip (the federation file
    # and HTTP endpoint serve render_merged output).
    reparsed, _ = _metrics.parse_exposition_typed(
        _metrics.render_merged(merged, types))
    if reparsed.get(f"{name}_centroid") != merged.get(f"{name}_centroid"):
        print("check-latency: render_merged did not round-trip")
        return 1
    print("check-latency: sketch merge/exposition round-trip OK "
          f"(p99 {direct.percentile(0.99)}s over {direct.count} samples)")
    return 0


def render(parsed: dict, before: dict = None, interval_s: float = None
           ) -> str:
    """One table: per-stage events/s (or totals), busy share, p95."""
    lines = []
    rate_mode = before is not None and interval_s
    header = (f"{'stage':<16} {'events/s':>10} {'busy%':>7} {'p95 ms':>9}"
              if rate_mode else
              f"{'stage':<16} {'events':>10} {'total s':>8} {'mean ms':>9}")
    lines.append(header)
    lines.append("-" * len(header))
    for stage in STAGES:
        count = _stage_scalar(parsed, "_count", stage)
        total = _stage_scalar(parsed, "_sum", stage)
        if rate_mode:
            d_count = count - _stage_scalar(before, "_count", stage)
            d_sum = total - _stage_scalar(before, "_sum", stage)
            if d_count == 0 and count == 0:
                continue
            p95_s = _p95_from_bucket_delta(
                _stage_buckets(parsed, stage), _stage_buckets(before, stage))
            lines.append(f"{stage:<16} {d_count / interval_s:>10.1f} "
                         f"{100.0 * d_sum / interval_s:>6.1f}% "
                         f"{p95_s * 1e3:>9.1f}")
        else:
            if count == 0:
                continue
            mean_ms = total / count * 1e3
            lines.append(f"{stage:<16} {int(count):>10} "
                         f"{total:>8.2f} {mean_ms:>9.1f}")
    wait_sum = _scalar(parsed, "rsdl_batch_wait_seconds_sum")
    wait_count = _scalar(parsed, "rsdl_batch_wait_seconds_count")
    if rate_mode:
        d_wait = wait_sum - _scalar(before, "rsdl_batch_wait_seconds_sum")
        d_batches = (wait_count
                     - _scalar(before, "rsdl_batch_wait_seconds_count"))
        lines.append("")
        lines.append(f"stall: {100.0 * d_wait / interval_s:.1f}% of wall "
                     f"({d_batches / interval_s:.1f} batches/s)")
    elif wait_count:
        lines.append("")
        lines.append(f"stall: {wait_sum:.2f}s total batch-wait over "
                     f"{int(wait_count)} batches")
    stalls = _scalar(parsed, "rsdl_watchdog_events_total")
    faults = _scalar(parsed, "rsdl_faults_injected_total")
    if stalls or faults:
        lines.append(f"watchdog stalls: {int(stalls)}   "
                     f"faults injected: {int(faults)}")
    # Queue-service consumer health (multiqueue_service leases): live
    # consumers, expired leases (dead trainers), and crash-recovery
    # activity — the operator's first question when ranks go quiet.
    alive = _scalar(parsed, "rsdl_queue_consumers_alive")
    expiries = _scalar(parsed, "rsdl_queue_lease_expiries_total")
    replayed = _scalar(parsed, "rsdl_queue_frames_replayed_total")
    restarts = _scalar(parsed, "rsdl_queue_server_restarts_total")
    if alive or expiries or replayed or restarts:
        lines.append(
            f"consumers: {int(alive)} alive   "
            f"dead (lease expired): {int(expiries)}   "
            f"frames replayed: {int(replayed)}   "
            f"server restarts: {int(restarts)}")
    lines.extend(render_shards(parsed))
    lines.extend(render_storage(parsed))
    lines.extend(render_tenants(parsed))
    lines.extend(render_membership(parsed))
    lines.extend(render_rebalance(parsed))
    lines.extend(render_streaming(parsed))
    lines.extend(render_latency(parsed, before=before if rate_mode
                                else None))
    # Critical-path line (runtime/trace.py gauges, refreshed per epoch):
    # the top-3 stages by critical-path self time plus the current
    # straggler task — the "what do I optimize" one-liner.
    cp = [(dict(labels).get("stage"), value) for labels, value in
          parsed.get("rsdl_trace_cp_seconds", {}).items()]
    cp = sorted(((s, v) for s, v in cp if s), key=lambda kv: -kv[1])[:3]
    if cp:
        line = "critical path: " + " > ".join(
            f"{stage} {value:.2f}s" for stage, value in cp)
        strag = [(dict(labels).get("stage"), value) for labels, value in
                 parsed.get("rsdl_trace_straggler_seconds", {}).items()]
        strag = sorted(((s, v) for s, v in strag if s),
                       key=lambda kv: -kv[1])
        if strag:
            stage = strag[0][0]
            task = None
            for labels, value in parsed.get("rsdl_trace_straggler_task",
                                            {}).items():
                if dict(labels).get("stage") == stage:
                    task = int(value)
            line += (f"   straggler: {stage}"
                     + (f" task {task}" if task is not None else "")
                     + f" ({strag[0][1]:.2f}s)")
        lines.append(line)
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="live per-stage throughput + stall table over the "
                    "rsdl Prometheus exposition")
    parser.add_argument("--file", default=os.environ.get("RSDL_METRICS_FILE")
                        or None, help="exposition file path "
                        "(default: $RSDL_METRICS_FILE)")
    parser.add_argument("--url", default=None,
                        help="exposition HTTP URL, e.g. "
                             "http://127.0.0.1:9200/metrics")
    parser.add_argument("--dir", default=os.environ.get(
        "RSDL_TELEMETRY_DIR") or None,
        help="federation shard directory: merged table + per-process "
             "lines (default: $RSDL_TELEMETRY_DIR)")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh seconds (default 2)")
    parser.add_argument("--once", action="store_true",
                        help="print one lifetime-totals snapshot and exit")
    parser.add_argument("--check-latency", action="store_true",
                        help="run the latency-sketch merge/exposition "
                             "self-test and exit (0 = OK)")
    args = parser.parse_args(argv)
    if args.check_latency:
        return check_latency()
    if not args.file and not args.url and not args.dir:
        parser.error("need --file, --url or --dir "
                     "(or set RSDL_METRICS_FILE / RSDL_TELEMETRY_DIR)")

    def _read():
        if args.file or args.url:
            parsed = read_exposition(args.file, args.url)
            shards = (_metrics.read_shards(args.dir) if args.dir else {})
            return parsed, shards
        return read_shard_dir(args.dir)

    try:
        parsed, shards = _read()
    except (OSError, ValueError) as e:
        print(f"cannot read exposition: {e}", file=sys.stderr)
        return 1
    if args.once:
        print(render(parsed))
        if shards:
            print(render_processes(shards, parsed))
        return 0
    before = parsed
    # Monotonic interval timing (the exposition may come from another
    # host; never trust wall clock for rates).
    last = time.monotonic()
    try:
        # Refresh loop, not a retry: a top-style tool runs until ^C, and
        # a transient exposition-read failure skips one frame rather
        # than re-attempting an operation: rsdl-lint: disable=unbounded-retry
        while True:
            time.sleep(args.interval)
            try:
                parsed, shards = _read()
            except (OSError, ValueError) as e:
                print(f"read failed: {e}", file=sys.stderr)
                continue
            now = time.monotonic()
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(render(parsed, before=before, interval_s=now - last))
            if shards:
                print(render_processes(shards, parsed))
            sys.stdout.flush()
            before, last = parsed, now
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
