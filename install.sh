#!/usr/bin/env bash
# Editable install + native kernel prebuild (reference: install.sh).
# On a TPU-VM image, jax/flax/optax and pyarrow are preinstalled; this only
# registers the package and warms the C++ kernel cache.
set -euo pipefail
cd "$(dirname "$0")"

pip install -e .
python -c "from ray_shuffling_data_loader_tpu import native; \
print('native kernels:', 'loaded' if native.available() else 'NumPy fallback')"
