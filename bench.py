"""Headline benchmark: rows/sec/chip ingested through the full pipeline.

ONE invocation runs three timed phases and prints ONE JSON line
(``{"metric", "value", "unit", "vs_baseline", ...}``):

1. **cached** — rows/s streamed from shuffled Parquet through the
   map/reduce shuffle, re-batching, Arrow->NumPy conversion, and
   ``jax.device_put`` onto the accelerator, with the cross-epoch
   file-table cache on (decode paid once). A tiny jitted reduction per
   batch forces materialization on device. This is the headline
   ``value``.
2. **cold** — the corpus-exceeds-RAM regime: no decoded tables held in
   memory, the reference's 64 GB operating point
   (reference: benchmarks/benchmark_batch.sh:9-18). By default the run
   decodes each Parquet file ONCE inside the timed window and streams
   later epochs from memory-mapped Arrow IPC scratch on local disk
   (``file_cache="disk"`` — RSS stays reclaimable page cache;
   ``cold_cache`` in the JSON says which mode ran, and
   RSDL_BENCH_COLD_CACHE=none forces the reference's
   re-decode-every-epoch regime). ``vs_baseline`` is THIS number over
   the pandas reference algorithm, which pays full decode every pass —
   the decode-once design is the win being measured.
3. **train** — the BASELINE.md contract metric: a REAL DLRM train step
   (models/dlrm.py, Adam updates — not a mock sleep) consumes the
   stream, and the phase reports ``stall_pct_under_train`` (share of
   wall-clock the trainer spent waiting on the input pipeline,
   reference's own metric: examples/horovod/ray_torch_shuffle.py:186-218)
   plus train-gated rows/s. Contract: <= 10% stall.

The pandas baseline runs the reference's algorithm the way the reference
runs it per core — pandas ``read_parquet``, boolean-mask partitioning,
``pd.concat`` + ``sample(frac=1)``, sequential single process
(reference: shuffle.py:199-247) — on the same data and host in the same
run.

Env knobs: RSDL_BENCH_ROWS, RSDL_BENCH_FILES, RSDL_BENCH_EPOCHS,
RSDL_BENCH_BATCH, RSDL_BENCH_PREFETCH (batches in flight, default 4),
RSDL_BENCH_CPU=1 (force CPU backend for smoke runs),
RSDL_BENCH_PHASES (csv subset of
"cached,cold,train,scaling,serve,latency,remote,stream", default all;
the remote phase is the storage-plane cold leg — simulated object
store, tiered cache thrash regime, prefetch ON vs OFF at the same
seed; the stream phase is the streaming leg — synthetic event source,
windowed shuffle, served end-to-end through device transfer),
RSDL_BENCH_COLD=1 (legacy: make the cold phase the headline and skip
cached), RSDL_BENCH_COLD_EPOCHS (default 6),
RSDL_BENCH_COLD_CACHE=disk|none (default disk — see phase 2 above),
RSDL_BENCH_TRAIN_EPOCHS
(default 4), RSDL_BENCH_TRAIN_BATCH (default 131072),
RSDL_BENCH_TRAIN_MODEL=tiny|base|mlperf (DLRM scale for the train phase;
default mlperf — MLPerf-DLRM-v2-like widths; tiny on CPU),
RSDL_BENCH_TRAIN_MICROBATCH (rows per real train step; the loader chunk
is consumed as batch/microbatch on-device-sliced steps, default 2048),
RSDL_BENCH_DATA (data cache dir), RSDL_BENCH_DEVICE_REBATCH=0/1 (force
the per-batch host path / the bulk device-rebatch path; default auto),
RSDL_BENCH_STEP_MS (emulated per-batch step time in the ingest phases),
RSDL_BENCH_REDUCERS (override the reducer count),
RSDL_BENCH_TRAINERS (ingest-phase trainer ranks, default 1; >1 routes one
shuffle to N per-rank streams drained concurrently and clocks
launch-to-done — the reference-scale topology),
RSDL_BENCH_INFLIGHT_BYTES (transient-byte budget for the ingest phases),
RSDL_BENCH_SPILL_DIR (with the budget: spill tier for reducer outputs),
RSDL_BENCH_SCAN_STEPS=1 (train phase: one lax.scan call per chunk
instead of per-micro-step dispatch — see the note in run_train),
RSDL_BENCH_DEVICE_TABLE_BYTES (bulk-path per-chunk transfer cap),
RSDL_BENCH_RUNS (train-phase repeats for the median-of-N contract
fields + congestion marker; default 3 on accelerators, 1 under
RSDL_BENCH_CPU).

Chaos soak mode: ``--chaos[=RATE]`` argv flag (or RSDL_BENCH_CHAOS_RATE)
installs a seeded fault-rate spec over the recoverable sites
(``map_read`` / ``reduce_gather`` / ``device_transfer`` /
``spill_write`` / ``storage_read`` / ``storage_stall``,
runtime/faults.py) for the whole invocation: ~RATE of
each site's task keys fail once and must be recovered (lineage
recompute / in-task retry / spill degrade). The run must still complete
every selected phase — a phase that dies under chaos exits non-zero —
and the JSON gains the fault_stats() delta (``faults_injected``,
``fault_retries``, ``fault_recomputes``, ``fault_quarantines``,
``fault_recoveries_exhausted``, ``chaos_rate``) plus the
chaos/telemetry join evidence (``fault_events``,
``fault_events_joinable`` — fault events matched to stage events by
``(kind, epoch, task)``). An explicit
RSDL_CHAOS_SPEC wins over the rate spec (targeted reproduction:
``RSDL_CHAOS_SPEC="map_read:epoch1:file2"`` fails the same way every
run). The JSON also carries runtime-health evidence
(``watchdog_events``, ``stall_escalations``, ``fallback_engaged``) from
the bulk-path progress watchdog, and the library degradation policy
(runtime/policy.py) now owns the device-rebatch default:
RSDL_DEVICE_REBATCH=0 is the promoted, library-wide form of
RSDL_BENCH_DEVICE_REBATCH=0.

Telemetry spine (runtime/telemetry.py): the whole invocation is
flight-recorded (SIGUSR1 dumps the event ring + named-thread stacks at
any moment), and the JSON carries the bottleneck verdict computed from
recorder events — ``bottleneck_stage``, ``telemetry_stall_pct``,
``stage_latency_ms`` (p50/p95/p99 per stage), ``telemetry_events``,
and ``telemetry_overhead_pct`` (events x SELF-MEASURED full-path
per-record cost over the timed window; contract <= 1%), plus
``telemetry_overhead_off_pct`` — the same event count priced at the
RSDL_TELEMETRY=0 hard-off fast path, the proof the off switch is ~free.
RSDL_METRICS_FILE / RSDL_METRICS_PORT bring up the Prometheus
exposition so ``tools/rsdl_top.py`` can watch the run live; see
examples/observability.md.

Causal trace + profiling (runtime/trace.py, runtime/profiler.py): the
record also carries the critical-path attribution computed from the
recorder's retained events — ``critical_path`` (per-stage critical-path
ms, descending), ``self_time_ms`` (per-stage busy-union), ``whatif``
("2x faster <stage> => -X% epoch time", monotone in the speedup), and
``trace_straggler`` (the (stage, task) with the largest critical-path
share). RSDL_TRACE_DIR makes every process dump its recorder for
``tools/rsdl_trace.py`` to merge; RSDL_PROFILER=1 /
RSDL_PROFILE_FOLDED=<path> engage the stdlib sampling profiler and add
a ``profile`` summary (stage-billed samples, per-thread CPU seconds,
hottest stacks; folded stacks land at the path — flamegraph-ready).

Regression gate: ``--baseline <BENCH_rN.json>`` compares this record
against the chosen committed baseline with tools/rsdl_bench_diff.py's
thresholds and exits non-zero on a breach — the r03 -> r05 ingest
regression class can no longer land silently.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
import timeit


# Public per-chip peak matmul throughput (bf16), keyed by substrings of
# jax's device_kind, for the MFU denominator. CPU / unknown kinds report
# mfu as null rather than inventing a peak.
_TPU_PEAK_FLOPS = {
    "v5 lite": 197e12, "v5litepod": 197e12, "v5e": 197e12,
    "v5p": 459e12, "v4": 275e12, "v3": 123e12, "v2": 45e12,
}


def _device_peak_flops(jax) -> "float | None":
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, peak in _TPU_PEAK_FLOPS.items():
        if key in kind:
            return peak
    return None


def _train_flops_per_row(cfg) -> float:
    """Matmul FLOPs per row of one DLRM train step (the MXU work): the
    pairwise-interaction batched matmul plus the top (and bottom, when
    present) MLP, forward + ~2x for backward. Embedding gathers/scatters
    and the Adam update are memory-bound and excluded, the conventional
    MFU numerator; one-hot-matmul lookups for small tables are likewise
    excluded, so the estimate is a floor."""
    f, d = cfg.num_interacting, cfg.embed_dim
    interact = 2.0 * f * f * d
    dims = (cfg.top_in_dim,) + tuple(cfg.top_hidden) + (1,)
    mlp = sum(2.0 * a * b for a, b in zip(dims[:-1], dims[1:]))
    if cfg.dense_dim > 0:
        bdims = ((cfg.dense_dim,) + tuple(cfg.bottom_hidden)
                 + (cfg.embed_dim,))
        mlp += sum(2.0 * a * b for a, b in zip(bdims[:-1], bdims[1:]))
    return 3.0 * (interact + mlp)


def _pandas_reference_baseline(filenames, num_reducers: int,
                               batch_size: int) -> float:
    """rows/s of the reference's shuffle algorithm, single process."""
    import numpy as np
    import pandas as pd

    start = timeit.default_timer()
    total_rows = 0
    # Map stage: read + uniform partition via boolean masks.
    reducer_parts = [[] for _ in range(num_reducers)]
    for filename in filenames:
        rows = pd.read_parquet(filename)
        total_rows += len(rows)
        # Deliberately the reference's own unseeded draw (its map stage,
        # reference: shuffle.py:213) — this is the baseline being timed,
        # not pipeline code: rsdl-lint: disable=unseeded-random
        assignment = np.random.randint(num_reducers, size=len(rows))
        for r in range(num_reducers):
            reducer_parts[r].append(rows[assignment == r])
    # Reduce stage: concat + permute.
    shuffled = [pd.concat(parts).sample(frac=1) for parts in reducer_parts]
    # Consume: exact-size re-batching with leftover carry.
    buffer = None
    for df in shuffled:
        buffer = df if buffer is None else pd.concat([buffer, df])
        while len(buffer) >= batch_size:
            batch = buffer[:batch_size]
            _ = batch.to_numpy(copy=False)
            buffer = buffer[batch_size:]
    duration = timeit.default_timer() - start
    return total_rows / duration


def _aggregate_train_runs(runs: "list[dict]") -> dict:
    """Median-of-N aggregation for the contract (train) phase, with a
    congestion marker (VERDICT r5 Weak #6: a single congested run used
    to land outside contract silently).

    The quiet-host envelope is the runs' own robust spread: median
    ``step_ms_mean`` with a MAD-derived sigma, floored at 5% of the
    median so a perfectly tight triple doesn't flag scheduler noise. A
    run whose step-time z-score exceeds 3 is marked congested; the
    MEDIAN run (not the mean, not the outlier) carries the contract
    fields, so one noisy-neighbor episode cannot sink or inflate the
    artifact.
    """
    import statistics
    step_ms = [r["step_ms_mean"] for r in runs]
    med = statistics.median(step_ms)
    mad = statistics.median([abs(s - med) for s in step_ms])
    sigma = max(1.4826 * mad, 0.05 * med, 1e-9)
    zs = [(s - med) / sigma for s in step_ms]
    congested = [i for i, z in enumerate(zs) if z > 3.0]
    order = sorted(range(len(runs)), key=lambda i: step_ms[i])
    median_i = order[len(runs) // 2]
    return {
        "runs": len(runs),
        "median_run_index": median_i,
        "train_step_ms_median": round(step_ms[median_i], 3),
        "train_rows_per_sec_median": round(runs[median_i]["rows_per_s"], 1),
        "train_stall_pct_median": round(runs[median_i]["stall_pct"], 3),
        "train_step_ms_runs": [round(s, 3) for s in step_ms],
        "train_step_ms_z_max": round(max(zs), 2),
        "congested_runs": len(congested),
        "congested": bool(congested),
    }


def _cold_cache_mode() -> "str | None":
    """Cold-regime cache: "disk" (default — decode parquet once per run,
    stream later epochs from mmap'd Arrow IPC scratch; RSS stays page-cache
    bounded, the honest corpus-exceeds-RAM answer) or "none"
    (RSDL_BENCH_COLD_CACHE=none: re-decode every epoch, the reference's
    regime). Each dataset resolves "disk" to a FRESH scratch dir, so the
    warm-up run can never pre-populate the timed run's cache."""
    mode = os.environ.get("RSDL_BENCH_COLD_CACHE", "disk").strip().lower()
    return None if mode in ("none", "0", "") else "disk"


def _make_dataset(filenames, *, num_epochs, batch_size, num_reducers,
                  prefetch_size, cold, device_rebatch, qname,
                  num_trainers=1, rank=0, max_inflight_bytes=None,
                  spill_dir=None):
    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
    from ray_shuffling_data_loader_tpu.workloads.dlrm_criteo import dlrm_spec
    # Per-chunk transfer cap for the bulk device-rebatch path
    # (RSDL_BENCH_DEVICE_TABLE_BYTES): smaller chunks bound the tunnel's
    # in-flight transfer backlog on tunneled devices.
    table_bytes = os.environ.get("RSDL_BENCH_DEVICE_TABLE_BYTES")
    return JaxShufflingDataset(
        filenames, num_epochs=num_epochs, num_trainers=num_trainers,
        batch_size=batch_size, rank=rank,
        num_reducers=num_reducers, max_concurrent_epochs=2, seed=0,
        queue_name=qname, drop_last=True,
        prefetch_size=prefetch_size,
        file_cache=_cold_cache_mode() if cold else "auto",
        max_inflight_bytes=max_inflight_bytes, spill_dir=spill_dir,
        max_device_table_bytes=int(table_bytes) if table_bytes else None,
        device_rebatch=device_rebatch, **dlrm_spec())


def run_ingest(jax, filenames, *, num_epochs, batch_size, num_reducers,
               prefetch_size, cold, device_rebatch, step_ms, qname,
               max_inflight_bytes=None, spill_dir=None) -> dict:
    """Timed ingest: shuffle -> batches -> device, near-zero consumer.

    Timing protocol (round 4 fix): a separate ONE-epoch warm-up dataset
    pays XLA compiles and OS page-cache fill; then a FRESH dataset runs
    with nothing hidden from the clock. (The previous protocol excluded
    all of epoch 0, which let the producer front-run later epochs into
    the prefetch queue during the excluded epoch; at large batch sizes
    the queue holds multiple epochs of rows, and the "timed" window
    partly measured queue DRAIN — a 4-epoch cold run with identical
    ~4.3s total wall reported 13M or 35M "rows/s" depending only on
    batch size, both artifacts.)

    The clock differs by mode, because the work differs:

    - **cached**: clock starts at FIRST BATCH DELIVERY. By then the
      dataset's file cache is fully warm (the first reducer output
      needs every file mapped), so the window is pure steady state —
      exactly what "decode amortized" means — and only the first chunk
      (produced pre-window, its remaining batches ~1% of the window) is
      credited for free. Launch-to-first-batch is reported as
      ``fill_s``; the reference's trainers never see it because the
      driver starts the shuffle before they attach
      (reference: ray_torch_shuffle.py:316-322).
    - **cold**: clock starts at SHUFFLE LAUNCH and covers everything.
      Cold means decode recurs every epoch, so the pre-first-batch work
      (the whole epoch-0 map stage) is exactly the work being measured
      — excluding it would hand epoch 0 a free decode.
    """
    import jax.numpy as jnp

    # Tiny jitted reduction per batch: forces the batch to land on device;
    # negligible compute (sparse-feature columns arrive as one pytree
    # transfer and are consumed per-column, the DLRM access pattern).
    touch = jax.jit(
        lambda fs, y: sum(f.sum(dtype=jnp.int32) for f in fs)
        + y.sum(dtype=jnp.float32))

    # try/finally on both dataset lifetimes: a phase that raises must not
    # leave its producers/queues running to contaminate later phases (the
    # caller treats phase failures as non-fatal).
    warm = _make_dataset(filenames, num_epochs=1, batch_size=batch_size,
                         num_reducers=num_reducers,
                         prefetch_size=prefetch_size, cold=cold,
                         device_rebatch=device_rebatch,
                         qname=f"{qname}-warm",
                         max_inflight_bytes=max_inflight_bytes,
                         spill_dir=spill_dir)
    try:
        warm.set_epoch(0)
        last = None
        for features, label in warm:
            last = touch(features, label)
        jax.block_until_ready(last)
    finally:
        warm.close()

    launch = timeit.default_timer()
    ds = _make_dataset(filenames, num_epochs=num_epochs,
                       batch_size=batch_size, num_reducers=num_reducers,
                       prefetch_size=prefetch_size, cold=cold,
                       device_rebatch=device_rebatch, qname=qname,
                       max_inflight_bytes=max_inflight_bytes,
                       spill_dir=spill_dir)
    rows_consumed = 0
    start = launch if cold else None  # cold: launch-to-last-batch
    fill_s = None
    try:
        for epoch in range(num_epochs):
            ds.set_epoch(epoch)
            for features, label in ds:
                if fill_s is None:
                    fill_s = timeit.default_timer() - launch
                    if start is None:
                        # Cached: the first batch (produced pre-window) is
                        # consumed BEFORE the clock starts, so neither its
                        # production nor its consumption leaks into the
                        # window; stall stats start with batch 2's wait.
                        last = touch(features, label)
                        jax.block_until_ready(last)
                        ds.batch_wait_stats.reset()
                        start = timeit.default_timer()
                        continue
                last = touch(features, label)
                if step_ms:
                    time.sleep(step_ms / 1e3)
                rows_consumed += label.shape[0]
        jax.block_until_ready(last)
        duration = max(timeit.default_timer() - (start or launch), 1e-9)
    finally:
        ds.close()
    wait = ds.batch_wait_stats.summary()
    return {
        "rows_per_s": rows_consumed / duration,
        "stall_s": wait["total"],
        "stall_pct": 100.0 * wait["total"] / duration,
        "wait_mean_ms": wait["mean"] * 1e3,
        "batches": wait["count"],
        "timed_epochs": num_epochs,
        "duration_s": duration,
        "fill_s": fill_s if fill_s is not None else 0.0,
    }


def _run_worker_scaling(filenames, *, num_reducers, seed=0) -> dict:
    """Worker-count scaling leg: the SAME shuffle (direct driver, null
    consumer — no queue/device machinery, so the executor is the only
    variable) at pool width 1 and at the full configured width, over a
    quarter of the files x 2 epochs (one cold, one cached). The record
    carries the measured rates plus the derived parallel efficiency, so
    "near-linear scaling" is an artifact of the run, not a claim.
    """
    import importlib
    shmod = importlib.import_module("ray_shuffling_data_loader_tpu.shuffle")
    from ray_shuffling_data_loader_tpu import spill as rsdl_spill
    from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy

    files = filenames[:max(1, len(filenames) // 4)]
    full = (rt_policy.resolve("executor", "executor_workers")
            or os.cpu_count() or 1)
    legs = {}
    for workers in sorted({1, full}):
        rows = [0]

        def consumer(trainer, epoch, refs):
            if refs is None:
                return
            for ref in refs:
                rows[0] += rsdl_spill.unwrap(ref.result()).num_rows

        start = timeit.default_timer()
        shmod.shuffle(files, consumer, num_epochs=2,
                      num_reducers=num_reducers, num_trainers=1,
                      seed=seed, num_workers=workers, collect_stats=False)
        duration = max(timeit.default_timer() - start, 1e-9)
        legs[str(workers)] = round(rows[0] / duration, 1)
    result = {
        "rows_per_s_by_workers": legs,
        "max_workers": full,
        "files_fraction": round(len(files) / len(filenames), 3),
    }
    if full > 1 and str(full) in legs and legs["1"]:
        result["parallel_efficiency"] = round(
            legs[str(full)] / (full * legs["1"]), 3)
    return result


def run_ingest_multi(jax, filenames, *, num_epochs, batch_size,
                     num_reducers, prefetch_size, cold, device_rebatch,
                     step_ms, qname, num_trainers,
                     max_inflight_bytes=None, spill_dir=None) -> dict:
    """Multi-trainer ingest: ONE shuffle routes batches to ``num_trainers``
    per-rank streams, each drained by its own consumer thread — the
    reference's trainers-per-node topology (reference:
    benchmark.py:championship trainer sweep, multiqueue.py:127-154) on one
    host. Rank 0 owns the queue + shuffle; ranks 1+ attach to the named
    queue, the reference's consumer-only pattern.

    The clock runs LAUNCH to last-rank-done for every mode (unlike the
    single-trainer cached protocol): with T concurrent streams there is no
    single "first delivery" that marks steady state, and this entry point
    exists for scale evidence where fill is part of the story. rows/s sums
    all ranks; stall stats aggregate across ranks (stall_pct is the mean
    per-rank batch-wait share of the run)."""
    import threading

    import jax.numpy as jnp

    touch = jax.jit(
        lambda fs, y: sum(f.sum(dtype=jnp.int32) for f in fs)
        + y.sum(dtype=jnp.float32))

    warm = _make_dataset(filenames, num_epochs=1, batch_size=batch_size,
                         num_reducers=num_reducers,
                         prefetch_size=prefetch_size, cold=cold,
                         device_rebatch=device_rebatch,
                         qname=f"{qname}-warm",
                         max_inflight_bytes=max_inflight_bytes,
                         spill_dir=spill_dir)
    try:
        warm.set_epoch(0)
        last = None
        for features, label in warm:
            last = touch(features, label)
        jax.block_until_ready(last)
    finally:
        warm.close()

    launch = timeit.default_timer()
    make = lambda rank: _make_dataset(
        filenames, num_epochs=num_epochs, batch_size=batch_size,
        num_reducers=num_reducers, prefetch_size=prefetch_size, cold=cold,
        device_rebatch=device_rebatch, qname=qname,
        num_trainers=num_trainers, rank=rank,
        max_inflight_bytes=max_inflight_bytes, spill_dir=spill_dir)
    rows = [0] * num_trainers
    fills = [None] * num_trainers
    errors = []
    # One jitted touch per rank keeps device work trivial but real; the
    # touch results are tiny scalars, safe to race on one chip.
    lasts = [None] * num_trainers

    def consume(rank: int, ds) -> None:
        try:
            for epoch in range(num_epochs):
                ds.set_epoch(epoch)
                for features, label in ds:
                    if fills[rank] is None:
                        fills[rank] = timeit.default_timer() - launch
                    lasts[rank] = touch(features, label)
                    if step_ms:
                        time.sleep(step_ms / 1e3)
                    rows[rank] += label.shape[0]
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append((rank, e))

    datasets = []
    threads = []
    try:
        # Rank 0 FIRST: it registers the named queue and launches the
        # shuffle the other ranks attach to. Built inside try/finally so a
        # failing later construction cannot leak rank 0's running producer
        # into later phases.
        for rank in range(num_trainers):
            datasets.append(make(rank))
        threads = [threading.Thread(target=consume, args=(r, datasets[r]),
                                    daemon=True,
                                    name=f"rsdl-bench-consume-{r}")
                   for r in range(num_trainers)]
        for t in threads:
            t.start()
        # Poll-join: one failed rank must tear the run down (via the
        # finally's closes, which unblock the surviving consumers), not
        # leave the producer back-pressured and the bench hung forever.
        while any(t.is_alive() for t in threads) and not errors:
            for t in threads:
                t.join(timeout=0.5)
        if not errors:
            for last in lasts:
                if last is not None:
                    jax.block_until_ready(last)
        duration = max(timeit.default_timer() - launch, 1e-9)
    finally:
        for ds in datasets:
            try:
                ds.close()
            # Teardown must not mask the rank error raised right below;
            # nothing is blocked on these closed datasets:
            # rsdl-lint: disable=swallowed-exception
            except Exception:  # noqa: BLE001
                pass
        for t in threads:
            t.join(timeout=60)
    if errors:
        raise RuntimeError(
            f"trainer rank {errors[0][0]} failed") from errors[0][1]
    waits = [ds.batch_wait_stats.summary() for ds in datasets]
    total_stall = sum(w["total"] for w in waits)
    total_batches = sum(w["count"] for w in waits)
    return {
        "rows_per_s": sum(rows) / duration,
        "stall_s": total_stall,
        # Mean per-rank share of the run spent waiting (T ranks each have
        # `duration` of wall to spend).
        "stall_pct": 100.0 * total_stall / (num_trainers * duration),
        "wait_mean_ms": (total_stall / total_batches * 1e3
                         if total_batches else 0.0),
        "batches": total_batches,
        "timed_epochs": num_epochs,
        "duration_s": duration,
        "fill_s": min((f for f in fills if f is not None), default=0.0),
        "num_trainers": num_trainers,
        "clock": "launch",
    }


def _make_chunk_stepper(jax, dlrm, cfg, opt, mb: int,
                        steps_per_chunk: int):
    """One jitted call per loader chunk that runs ``steps_per_chunk``
    REAL micro-steps (fwd+bwd+Adam per ``mb``-row on-device slice) via
    ``lax.scan`` — identical math to dispatching each micro-step from
    Python, minus ``steps_per_chunk - 1`` host->device dispatches per
    chunk. On a tunneled device each dispatch costs milliseconds, which
    previously polluted step_ms_mean with host-link latency; the scanned
    form measures the model, and is the idiomatic TPU shape anyway (one
    traced loop, static trip count, donated carry). Returns
    ``(params, opt_state, last_loss)``."""
    import functools

    import jax.numpy as jnp
    import optax
    from jax import lax

    steps_idx = jnp.arange(steps_per_chunk, dtype=jnp.int32)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def chunk_steps(params, opt_state, cols, labels):
        def body(carry, i):
            p, o = carry
            mcols = [lax.dynamic_slice_in_dim(c, i * mb, mb, axis=0)
                     for c in cols]
            mlab = lax.dynamic_slice_in_dim(labels, i * mb, mb, axis=0)
            loss, grads = jax.value_and_grad(
                lambda pp: dlrm.loss_fn(cfg, pp, None, mcols, mlab))(p)
            updates, o = opt.update(grads, o)
            return (optax.apply_updates(p, updates), o), loss

        (params, opt_state), losses = lax.scan(
            body, (params, opt_state), steps_idx)
        return params, opt_state, losses[-1]

    return chunk_steps


def run_train(jax, filenames, *, num_epochs, batch_size, num_reducers,
              prefetch_size, device_rebatch, model_size, microbatch,
              qname) -> dict:
    """The contract phase: real jitted DLRM train steps consume the
    stream; reports stall% (batch-wait share of wall-clock) and
    train-gated rows/s. Compiles are paid by a separate warm-up dataset;
    the clock starts at the timed dataset's first chunk delivery.

    The trainer is MICRO-BATCHED, the standard large-batch recommender
    setup: the loader delivers ``batch_size``-row device chunks (bulk
    transfers at the granularity the wire likes), and the trainer runs
    one real train step (fwd+bwd+Adam update, models/dlrm.py — not a
    mock sleep) per ``microbatch``-row slice, carved on-device inside
    the jitted step. Rows/s is gated by real training work; stall% is
    the fraction of wall-clock the trainer spent blocked on the input
    pipeline — the reference's own metric, measured around its
    synchronous per-step loop (reference:
    ray_torch_shuffle.py:186-219)."""
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax

    from ray_shuffling_data_loader_tpu.models import dlrm

    if model_size == "tiny":
        # CPU smoke path: full-cardinality embedding grads are dense
        # host-side and would swamp a tiny run.
        cfg = dlrm.DLRMConfig(
            vocab_sizes=tuple(min(v, 1000)
                              for v in dlrm.DATA_SPEC_VOCAB_SIZES),
            embed_dim=8, top_hidden=(64, 32),
            compute_dtype=jnp.float32)
    elif model_size == "base":
        cfg = dlrm.DLRMConfig()  # embed 32, top (512, 256)
    else:
        # Production-representative scale (MLPerf DLRM-v2-like MLP widths
        # on the reference's own 17-table schema): this is what a real
        # recommender train step costs per row, and the scale BASELINE's
        # >=90%-utilization contract is about.
        cfg = dlrm.DLRMConfig(embed_dim=128,
                              top_hidden=(1024, 1024, 512, 256))
    params = dlrm.init(cfg, jax.random.key(0))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    mb = min(microbatch, batch_size)
    if batch_size % mb:
        # Round DOWN to the largest divisor so the step granularity (and
        # hence the contract stall metric) stays close to what was asked
        # for, and say so — silently training one giant step per chunk
        # would change the number being measured.
        mb = next(d for d in range(mb, 0, -1) if batch_size % d == 0)
        print(f"# train microbatch {microbatch} does not divide chunk "
              f"{batch_size}; using {mb}", file=sys.stderr)
    steps_per_chunk = batch_size // mb

    # Two step-loop forms, same math (pinned by
    # test_scanned_chunk_stepper_matches_sequential_micro_steps):
    # per-micro-step jit dispatch (default), or one lax.scan call per
    # chunk (RSDL_BENCH_SCAN_STEPS=1). The scanned form is the idiomatic
    # TPU shape and removes steps_per_chunk-1 host dispatches per chunk —
    # but MEASURED 40x slower on this environment's tunneled v5e
    # (19.3 ms vs 0.47 ms per 2048-row step, identical loss): the
    # backend fails to alias the multi-GB params carry inside the scan
    # and copies it every iteration. Until that aliasing works here, the
    # dispatch-per-step form is what the contract runs on.
    if os.environ.get("RSDL_BENCH_SCAN_STEPS"):
        chunk_steps = _make_chunk_stepper(jax, dlrm, cfg, opt, mb,
                                          steps_per_chunk)
    else:
        @jax.jit
        def micro_step(params, opt_state, cols, labels, i):
            mcols = [lax.dynamic_slice_in_dim(c, i * mb, mb, axis=0)
                     for c in cols]
            mlab = lax.dynamic_slice_in_dim(labels, i * mb, mb, axis=0)
            loss, grads = jax.value_and_grad(
                lambda p: dlrm.loss_fn(cfg, p, None, mcols, mlab))(params)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        def chunk_steps(params, opt_state, cols, labels):
            loss = None
            for i in range(steps_per_chunk):
                params, opt_state, loss = micro_step(
                    params, opt_state, cols, labels, np.int32(i))
            return params, opt_state, loss

    # Same protocol as run_ingest: a one-epoch warm-up dataset pays the
    # model/step compiles; the timed dataset's clock and stall stats
    # start at its FIRST chunk delivery (the reference's trainers attach
    # to an already-running shuffle, so they never observe launch fill —
    # reported separately as fill_s).
    # try/finally on both dataset lifetimes — see run_ingest.
    warm = _make_dataset(filenames, num_epochs=1, batch_size=batch_size,
                         num_reducers=num_reducers,
                         prefetch_size=prefetch_size, cold=False,
                         device_rebatch=device_rebatch,
                         qname=f"{qname}-warm")
    last_chunk = None
    try:
        warm.set_epoch(0)
        loss = None
        for features, label in warm:
            params, opt_state, loss = chunk_steps(
                params, opt_state, features, label)
            last_chunk = (features, label)
        jax.block_until_ready(loss)
    finally:
        warm.close()

    # Measured model peak: pure-compute rows/s of the SAME jitted step
    # loop on one already-device-resident warm chunk — no pipeline, no
    # transfer, no batch wait. When the device has no public peak-FLOPs
    # entry (CPU hosts, unlisted accelerators), train_mfu reports
    # achieved/compute-bound instead of going silently null: 100% means
    # the input pipeline kept the step loop fully fed. Params advance on
    # a throwaway copy so the timed run starts from the same state as
    # before this measurement existed.
    compute_rows_per_s = None
    if last_chunk is not None:
        warm_f, warm_l = last_chunk
        pm, om, lm = chunk_steps(params, opt_state, warm_f, warm_l)
        jax.block_until_ready(lm)
        best_s = None
        for _ in range(3):
            peak_t0 = timeit.default_timer()
            pm, om, lm = chunk_steps(pm, om, warm_f, warm_l)
            jax.block_until_ready(lm)
            rep_s = timeit.default_timer() - peak_t0
            best_s = rep_s if best_s is None else min(best_s, rep_s)
        # Fastest rep, not the mean: the peak is a CAPACITY estimate, and
        # any jitter in the reps only ever makes it look lower.
        compute_rows_per_s = batch_size / max(best_s, 1e-9)

    launch = timeit.default_timer()
    ds = _make_dataset(filenames, num_epochs=num_epochs,
                       batch_size=batch_size, num_reducers=num_reducers,
                       prefetch_size=prefetch_size, cold=False,
                       device_rebatch=device_rebatch, qname=qname)
    rows_consumed = 0
    steps = 0
    start = fill_s = None
    try:
        for epoch in range(num_epochs):
            ds.set_epoch(epoch)
            for features, label in ds:
                if start is None:
                    fill_s = timeit.default_timer() - launch
                    # The first chunk (produced pre-window) trains BEFORE
                    # the clock starts: params advance, but neither its
                    # production nor its compute is inside the window.
                    params, opt_state, loss = chunk_steps(
                        params, opt_state, features, label)
                    jax.block_until_ready(loss)
                    ds.batch_wait_stats.reset()
                    start = timeit.default_timer()
                    continue
                params, opt_state, loss = chunk_steps(
                    params, opt_state, features, label)
                rows_consumed += batch_size
                steps += steps_per_chunk
        jax.block_until_ready(loss)
        duration = max(timeit.default_timer() - (start or launch), 1e-9)
    finally:
        ds.close()
    wait = ds.batch_wait_stats.summary()
    stall_s = wait["total"]
    # Compute-utilization context (VERDICT r4 item 5): dev_util_pct is
    # the non-wait share of the timed wall — an upper bound on device
    # duty cycle (it still contains host-side Python step overhead);
    # mfu_pct divides achieved matmul FLOPs by the chip's public bf16
    # peak (null off-TPU). DLRM MFU is intrinsically low: the model is
    # embedding/memory-bound, the MLP widths just bound the MXU share.
    peak = _device_peak_flops(jax)
    flops_per_row = _train_flops_per_row(cfg)
    if peak:
        mfu_pct = 100.0 * flops_per_row * rows_consumed / (duration * peak)
        mfu_basis, mfu_null_reason = "public_peak", None
    elif compute_rows_per_s:
        # No public peak for this device kind: report achieved rows/s
        # against the measured compute-bound ceiling of the identical
        # step loop. Not comparable to a public-peak MFU — the basis
        # field says which denominator produced the number.
        mfu_pct = 100.0 * (rows_consumed / duration) / compute_rows_per_s
        mfu_basis, mfu_null_reason = "measured_model_peak", None
    else:
        mfu_pct, mfu_basis = None, None
        mfu_null_reason = ("device kind has no public peak-FLOPs entry "
                           "and the warm-up delivered no chunk to measure "
                           "a model peak against")
    return {
        "rows_per_s": rows_consumed / duration,
        "stall_s": stall_s,
        "stall_pct": 100.0 * stall_s / duration,
        "dev_util_pct": 100.0 * (duration - stall_s) / duration,
        "mfu_pct": mfu_pct,
        "mfu_basis": mfu_basis,
        "mfu_null_reason": mfu_null_reason,
        "compute_rows_per_s": compute_rows_per_s,
        "flops_per_row": flops_per_row,
        "wait_mean_ms": wait["mean"] * 1e3,
        # Mean train-step time the pipeline had to beat: everything that
        # wasn't batch-wait, per micro-step.
        "step_ms_mean": ((duration - stall_s) / max(1, steps)) * 1e3,
        "batches": steps,
        "batch_size": batch_size,
        "microbatch": mb,
        # A non-finite loss means the model diverged; null the field (bare
        # NaN is not valid JSON) and flag it so the failure stays loud.
        "final_loss": (float(loss) if loss is not None
                       and math.isfinite(float(loss)) else None),
        "diverged": loss is not None and not math.isfinite(float(loss)),
        "timed_epochs": num_epochs,
        "duration_s": duration,
        "fill_s": fill_s if fill_s is not None else 0.0,
        "model_size": model_size,
    }


def _baseline_from_invocation() -> "str | None":
    """``--baseline PATH`` / ``--baseline=PATH`` argv flag (or
    RSDL_BENCH_BASELINE): the committed bench record this run must not
    regress from."""
    argv = sys.argv[1:]
    for i, arg in enumerate(argv):
        if arg == "--baseline" and i + 1 < len(argv):
            return argv[i + 1]
        if arg.startswith("--baseline="):
            return arg.split("=", 1)[1]
    return os.environ.get("RSDL_BENCH_BASELINE") or None


def _load_bench_diff():
    """tools/rsdl_bench_diff.py as a module (tools/ is not a package)."""
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "rsdl_bench_diff.py")
    spec = importlib.util.spec_from_file_location("_rsdl_bench_diff", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


#: Record keys that are deliberately informational — context the record
#: carries for forensics, not measurements a two-record gate could
#: meaningfully threshold. rsdl-lint's `ungated-bench-metric` rule
#: accepts a numeric record key only when it is covered by a
#: tools/rsdl_bench_diff.py DEFAULT_RULES prefix or listed here; a new
#: numeric emission must pick a side explicitly.
BENCH_INFORMATIONAL_KEYS = frozenset({
    # Invocation shape (identity, not measurement).
    "host_cpus", "num_workers", "num_reducers", "num_trainers",
    "batch_size", "prefetch_size", "rows", "epochs", "step_ms",
    "max_inflight_bytes", "telemetry_events", "fault_events",
    "fault_events_joinable", "chaos_rate",
    # Diagnostic refinements of quantities gated through another rule:
    # train_rows_per_sec gates the step-time/throughput family,
    # stall_pct_under_train gates the stall contract, train_diverged
    # gates convergence, telemetry_overhead_pct (ceiling) gates the ON
    # cost — the OFF cost is the proof the kill switch is free.
    "train_step_ms_mean", "train_compute_rows_per_sec",
    "train_wait_mean_ms", "train_stall_s", "train_dev_util_pct",
    "train_final_loss", "telemetry_overhead_off_pct",
    # Cold ingest is producer-bound BY CONSTRUCTION (near-zero-work
    # consumer): its stall share carries no contract.
    "cold_stall_pct",
    # Ratio against the in-run pandas reference: the reference's own
    # timing noise dominates; cold_rows_per_sec gates the regime.
    "vs_baseline_cached",
    # Rebalance leg context: the configured breach threshold and the
    # saturator's appetite are invocation shape; the raw contended /
    # recovered p99s are diagnostic refinements of the gated
    # rebalance_p99_recovery_x ratio (and the move count is pinned to 1
    # by rebalance_ok, not a threshold).
    "rebalance_slo_ms", "rebalance_sat_rows", "rebalance_moves",
    "rebalance_p99_ms_contended", "rebalance_p99_ms_recovered",
})


def _bench_provenance() -> dict:
    """Measurement provenance stamped into every record: WHAT code ran
    (git rev + dirty flag) on WHAT machine (host + CPU fingerprint).
    The r09->r10 'regression' was a slower bench host that nothing in
    the records could falsify — rsdl_regress/rsdl_bench_diff warn on
    cross-host or dirty-tree comparisons using exactly these fields.
    Every probe is fail-soft: a record without git is still a record."""
    import platform
    import socket
    import subprocess
    repo = os.path.dirname(os.path.abspath(__file__))
    prov: dict = {
        "git_rev": None,
        "tree_dirty": None,
        "host": socket.gethostname(),
        "host_cpus": os.cpu_count(),
        "cpu_model": None,
        "python": platform.python_version(),
        "platform": platform.platform(),
    }
    try:
        prov["git_rev"] = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=repo, capture_output=True,
            text=True, timeout=10, check=True).stdout.strip()
        status = subprocess.run(
            ["git", "status", "--porcelain"], cwd=repo,
            capture_output=True, text=True, timeout=10, check=True)
        prov["tree_dirty"] = bool(status.stdout.strip())
    except (OSError, subprocess.SubprocessError):
        pass
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    prov["cpu_model"] = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return prov


def _capture_round_capsule(record: dict) -> "str | None":
    """Per-round flight capsule (same layout as the runtime/health.py
    incident capsules, consumed by runtime/regress.py): the merged
    trace dumps, federated metrics, history slice, and resolved
    policy+env behind THIS record, written beside it and referenced
    from ``record["capsule"]``. Runs after every phase has finished —
    outside all timed windows, so telemetry_overhead_pct is untouched.
    Fail-soft: a capsule failure costs the forensics, never the record."""
    from ray_shuffling_data_loader_tpu.runtime import health as rt_health
    from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
    base_dir = rt_policy.resolve("bench", "bench_capsule_dir") or "."
    stem = f"bench-{os.getpid()}-r.capsule"
    # Pin a scratch trace dir ONLY for the capture window (all timed
    # phases are over): capture_incident signals the driver to dump its
    # flight-recorder ring and collects whatever lands in the resolved
    # trace dir. A run-long pin would also catch worker exit dumps, but
    # costs measured-phase CPU/IO — the workers' events already fold
    # into the driver-side attribution summary, so the trade is bad.
    pinned = None
    if not rt_policy.resolve("telemetry", "trace_dir"):
        import tempfile
        pinned = tempfile.mkdtemp(prefix="rsdl-bench-trace-")
        os.environ["RSDL_TRACE_DIR"] = pinned
    try:
        capsule = rt_health.capture_incident(
            reason="bench-round", base_dir=base_dir, profile_s=0.0,
            cooldown_s=0.0, stem=stem)
    except Exception as e:  # noqa: BLE001 - forensics must not fail the run
        print(f"# bench capsule capture FAILED: {type(e).__name__}: {e}",
              file=sys.stderr)
        return None
    finally:
        if pinned is not None:
            os.environ.pop("RSDL_TRACE_DIR", None)
            import shutil
            shutil.rmtree(pinned, ignore_errors=True)
    if capsule is None:
        return None
    try:
        record["capsule"] = os.path.relpath(capsule)
    except ValueError:
        record["capsule"] = capsule
    try:
        # The record itself rides in the capsule (self-contained when
        # the directory travels without its BENCH_r*.json), and the
        # manifest's file list is refreshed to include it.
        with open(os.path.join(capsule, "record.json"), "w",
                  encoding="utf-8") as f:
            json.dump(record, f, indent=2, sort_keys=True)
        manifest_path = os.path.join(capsule, "capsule.json")
        with open(manifest_path, encoding="utf-8") as f:
            manifest = json.load(f)
        manifest["files"] = sorted(os.listdir(capsule))
        with open(manifest_path, "w", encoding="utf-8") as f:
            json.dump(manifest, f, indent=2)
    except (OSError, ValueError) as e:
        print(f"# bench capsule record embed FAILED: {e}",
              file=sys.stderr)
    return capsule


def _chaos_rate_from_invocation() -> "float | None":
    """``--chaos`` / ``--chaos=RATE`` argv flag or RSDL_BENCH_CHAOS_RATE."""
    rate = None
    for arg in sys.argv[1:]:
        if arg == "--chaos":
            rate = float(os.environ.get("RSDL_BENCH_CHAOS_RATE", "0.05"))
        elif arg.startswith("--chaos="):
            rate = float(arg.split("=", 1)[1])
    if rate is None and os.environ.get("RSDL_BENCH_CHAOS_RATE"):
        rate = float(os.environ["RSDL_BENCH_CHAOS_RATE"])
    return rate


def _install_chaos(rate: "float | None") -> "float | None":
    """Activate the soak spec unless a targeted RSDL_CHAOS_SPEC is set
    (the env spec was already honored at library import)."""
    from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
    if os.environ.get("RSDL_CHAOS_SPEC", "").strip() \
            or os.environ.get("RSDL_FAULTS_SPEC", "").strip():
        print("# chaos: honoring RSDL_CHAOS_SPEC over --chaos rate",
              file=sys.stderr)
        return rate
    if rate is None:
        return None
    seed = int(os.environ.get("RSDL_CHAOS_SEED", "0"))
    spec = ",".join(f"{site}@{rate}" for site in
                    ("map_read", "reduce_gather", "device_transfer",
                     "spill_write", "storage_read", "storage_stall"))
    rt_faults.install(spec, seed=seed)
    print(f"# chaos soak: rate={rate} seed={seed} over recoverable sites",
          file=sys.stderr)
    return rate


def _run_process_recovery_soak(seed: int) -> dict:
    """Process-level chaos soak: a REAL ``kill -9`` of the queue-server
    subprocess mid-epoch, wire chaos (connection reset mid-frame, frame
    corruption, lost acks) on the client side, and a dead-consumer lease
    expiry — recovered end to end, with the consumed stream asserted
    bit-identical to a fault-free in-process run. Runs on a small
    synthetic corpus so the soak costs seconds, not the bench budget.
    """
    import signal
    import tempfile

    from ray_shuffling_data_loader_tpu import data_generation as datagen
    from ray_shuffling_data_loader_tpu import multiqueue as mq
    from ray_shuffling_data_loader_tpu import multiqueue_service as svc
    from ray_shuffling_data_loader_tpu.dataset import ShufflingDataset
    from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
    from ray_shuffling_data_loader_tpu.runtime import supervisor as rt_sup
    from ray_shuffling_data_loader_tpu.shuffle import shuffle as run_shuffle

    epochs, reducers, soak_seed = 2, 3, 11
    tmpdir = tempfile.mkdtemp(prefix="rsdl-proc-soak-")
    filenames, _ = datagen.generate_data_local(8_000, 2, 1, 0.0, tmpdir)

    streams: dict = {}

    def reference_consumer(trainer_idx, epoch, refs):
        if refs is not None:
            streams.setdefault(epoch, []).extend(refs)

    run_shuffle(filenames, reference_consumer, epochs,
                num_reducers=reducers, num_trainers=1,
                max_concurrent_epochs=1, seed=soak_seed,
                collect_stats=False, file_cache=None)
    expected = {epoch: [tuple(r.result().column("key").to_pylist())
                        for r in refs]
                for epoch, refs in streams.items()}

    def consume_all(address_or_server, max_batch=2):
        address = (address_or_server.address
                   if hasattr(address_or_server, "address")
                   else address_or_server)
        # Deep redial budget: the restarted server re-imports the stack
        # before it listens, which can outlast the default schedule.
        remote = svc.RemoteQueue(address, retries=12, max_batch=max_batch)
        ds = ShufflingDataset(filenames, epochs, num_trainers=1,
                              batch_size=1_000, rank=0, batch_queue=remote,
                              shuffle_result=None, seed=soak_seed)
        got: dict = {}
        try:
            for epoch in range(epochs):
                ds.set_epoch(epoch)
                tables = []
                for table in ds.iter_tables():
                    tables.append(tuple(table.column("key").to_pylist()))
                    yield epoch, len(tables)
                got[epoch] = tables
        finally:
            remote.close()
        yield "done", got

    # Leg A — a REAL kill -9 of the queue-server subprocess mid-epoch:
    # the supervisor restarts it, the journal + shuffle lineage
    # regenerate the undelivered remainder, the consumer reconnects.
    # ack_lost fires client-side to prove lost acks are harmless.
    rt_faults.install("ack_lost:task0", seed=seed)
    supervisor, address = rt_sup.launch_supervised_queue_server(dict(
        filenames=filenames, num_epochs=epochs, num_trainers=1,
        num_reducers=reducers, seed=soak_seed, max_concurrent_epochs=1,
        journal_path=os.path.join(tmpdir, "watermarks.wal"),
        file_cache=None))
    result = {"ok": False, "server_restarts": 0}
    try:
        if not rt_sup.wait_for_server(address, timeout_s=60):
            raise RuntimeError("supervised queue server never came up")
        killed = False
        got_a = None
        for progress in consume_all(address):
            if progress[0] == "done":
                got_a = progress[1]
            elif not killed and progress == (0, 2):
                os.kill(supervisor.pid, signal.SIGKILL)
                killed = True
        kill_ok = killed and got_a == expected
        result["server_restarts"] = supervisor.restarts
    finally:
        rt_faults.clear()
        supervisor.stop()

    # Leg B — wire chaos against an in-process server (so the replay /
    # NACK counters land in THIS process's registry): a connection
    # reset mid-frame and a corrupted frame, both recovered.
    rt_faults.install(
        "conn_reset_midframe:task0:after1,frame_corrupt:task0:after4",
        seed=seed)
    try:
        def wire_consumer(trainer_idx, epoch, refs):
            queue_idx = epoch * 1 + trainer_idx
            if refs is None:
                wire_queue.put(queue_idx, None)
            else:
                wire_queue.put_batch(queue_idx, list(refs))

        wire_queue = mq.MultiQueue(epochs)
        run_shuffle(filenames, wire_consumer, epochs,
                    num_reducers=reducers, num_trainers=1,
                    max_concurrent_epochs=1, seed=soak_seed,
                    collect_stats=False, file_cache=None)
        with svc.serve_queue(wire_queue, num_trainers=1) as server:
            got_b = None
            for progress in consume_all(server, max_batch=2):
                if progress[0] == "done":
                    got_b = progress[1]
        wire_ok = got_b == expected
        wire_queue.shutdown()
    finally:
        rt_faults.clear()
    result["ok"] = kill_ok and wire_ok

    # Dead-consumer leg, in-process (the lease counters must land in
    # THIS process's registry for the JSON record): a consumer connects,
    # then vanishes without a goodbye; the lease expires under the
    # drain policy and its queue is freed.
    os.environ["RSDL_QUEUE_LEASE_TIMEOUT_S"] = "0.5"
    os.environ["RSDL_QUEUE_ON_DEAD_CONSUMER"] = "drain"
    try:
        import pyarrow as pa
        lease_queue = mq.MultiQueue(1)
        for i in range(4):
            lease_queue.put(0, pa.table({"x": [i]}))
        with svc.serve_queue(lease_queue) as server:
            dead = svc.RemoteQueue(server.address, max_batch=1)
            dead.get(0)
            dead.close()  # heartbeats stop; the lease must expire
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if lease_queue.size(0) == 0:
                    break
                time.sleep(0.1)
            result["lease_drained"] = lease_queue.size(0) == 0
        lease_queue.shutdown()
    finally:
        os.environ.pop("RSDL_QUEUE_LEASE_TIMEOUT_S", None)
        os.environ.pop("RSDL_QUEUE_ON_DEAD_CONSUMER", None)
    return result


def _run_speculation_leg(seed: int) -> dict:
    """Straggler leg of ``--chaos``: an injected ``delayN`` straggler on
    one reduce task (targeted FROM the epoch plan via
    ``faults.spec_for_node`` — the chaos key and the task's lineage key
    are equal by construction), raced with speculation ON vs OFF at the
    same seed over several rounds. The contract the record carries:
    p99 epoch time improves with speculation on, the consumed stream is
    bit-identical either way, and at least one backup actually won.

    Runs on the THREAD backend deliberately: chaos key state is
    per-process, so a process-pool backup in a sibling worker would
    re-fire the injected delay and prove nothing (the process-backend
    first-wins contract is pinned in tests/test_plan.py instead — the
    bench host has 1 CPU).
    """
    import statistics
    import tempfile

    from ray_shuffling_data_loader_tpu import data_generation as datagen
    from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
    from ray_shuffling_data_loader_tpu.plan import scheduler as plan_sched
    from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
    from ray_shuffling_data_loader_tpu.shuffle import shuffle as run_shuffle

    reducers, trainers, rounds, delay_ms = 3, 1, 5, 500
    tmpdir = tempfile.mkdtemp(prefix="rsdl-spec-leg-")
    filenames, _ = datagen.generate_data_local(6_000, 2, 1, 0.0, tmpdir)
    plan = plan_ir.build_epoch_plan(filenames, reducers, trainers,
                                    seed, epoch=0)
    straggler = plan.reduces()[0]
    rule = rt_faults.spec_for_node("reduce_gather", straggler,
                                   delay_ms=delay_ms)

    spec_env = {"RSDL_PLAN_SPECULATION": "1",
                "RSDL_PLAN_SPECULATION_MIN_S": "0.15",
                "RSDL_PLAN_SPECULATION_MULTIPLIER": "2.0",
                "RSDL_PLAN_SPECULATION_CHECK_S": "0.02"}

    def run_rounds(speculate: bool):
        """Per round: epoch time = start -> the consumer holds every
        reducer table of the epoch (the p99 the contract is about — a
        losing backup's discarded sleep drains during pool shutdown and
        is deliberately NOT part of epoch time)."""
        durations, streams = [], []
        for round_i in range(rounds):
            # Fresh injector per round: the delay rule fires once per
            # (site, epoch, task) key per injector, and every round must
            # see the same straggler.
            rt_faults.install(rule, seed=seed)
            try:
                stream: list = []
                done = {"t": None}
                start = time.monotonic()

                def consumer(rank, epoch, refs):
                    if refs is None:
                        return
                    for ref in refs:
                        stream.extend(
                            ref.result().column("key").to_pylist())
                    done["t"] = time.monotonic() - start

                run_shuffle(filenames, consumer, 1,
                            num_reducers=reducers, num_trainers=trainers,
                            max_concurrent_epochs=1, seed=seed,
                            collect_stats=False, file_cache=None,
                            num_workers=4, executor_backend="thread")
                durations.append(done["t"])
                streams.append(tuple(stream))
            finally:
                rt_faults.clear()
        return durations, streams

    totals_before = plan_sched.speculation_totals()
    for key, value in spec_env.items():
        os.environ[key] = value
    try:
        on_durations, on_streams = run_rounds(True)
    finally:
        for key in spec_env:
            os.environ.pop(key, None)
    totals_after = plan_sched.speculation_totals()
    off_durations, off_streams = run_rounds(False)

    def p99(values):
        ordered = sorted(values)
        return ordered[min(len(ordered) - 1,
                           int(0.99 * (len(ordered) - 1) + 0.999))]

    identical = len(set(on_streams + off_streams)) == 1
    won = (totals_after["speculative_won"]
           - totals_before["speculative_won"])
    result = {
        "rounds": rounds,
        "straggler": {"site": "reduce_gather", "rule": rule,
                      "node": straggler.id, "delay_ms": delay_ms},
        "p99_epoch_s_speculation_on": round(p99(on_durations), 4),
        "p99_epoch_s_speculation_off": round(p99(off_durations), 4),
        "median_epoch_s_speculation_on": round(
            statistics.median(on_durations), 4),
        "median_epoch_s_speculation_off": round(
            statistics.median(off_durations), 4),
        "p99_improvement_pct": round(
            100.0 * (1.0 - p99(on_durations) / p99(off_durations)), 2)
        if p99(off_durations) > 0 else 0.0,
        "speculative_launched": (totals_after["speculative_launched"]
                                 - totals_before["speculative_launched"]),
        "speculative_won": won,
        "speculative_wasted": (totals_after["speculative_wasted"]
                               - totals_before["speculative_wasted"]),
        "output_bit_identical": identical,
    }
    result["ok"] = bool(identical and won >= 1
                        and p99(on_durations) < p99(off_durations))
    return result


def _run_remote_leg(seed: int) -> dict:
    """Cold ingest against a simulated remote object store (storage/):
    plan-driven prefetch ON vs OFF at the same seed, same simulated
    latency/bandwidth draws, fresh tiered cache each side.

    The regime is deliberately a thrash shape — the tiered cache is
    budgeted below the working set, so a sequential scan under plain
    LRU misses every file every epoch. The prefetcher's idle lanes
    (the reduce tail leaves ``workers - reducers`` lanes free) re-warm
    the next epoch's head-of-plan files, which is exactly the win the
    record must carry: prefetch-on rows/s measurably above prefetch-off
    at the same seed, with the delivered stream bit-identical.

    Hermetic: generates its own small dataset, runs on the thread
    backend (a programmatic ``storage.set_source`` does not cross
    process boundaries — same caveat as programmatic chaos)."""
    import tempfile

    from ray_shuffling_data_loader_tpu import data_generation as datagen
    from ray_shuffling_data_loader_tpu import storage as rt_storage
    from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
    from ray_shuffling_data_loader_tpu.shuffle import shuffle as run_shuffle
    from ray_shuffling_data_loader_tpu.storage.cache import (DiskTier,
                                                             TieredStore)
    from ray_shuffling_data_loader_tpu.storage.source import (
        LocalSource, SimulatedObjectStore)

    num_files, workers, reducers, epochs = 8, 4, 2, 3
    tmpdir = tempfile.mkdtemp(prefix="rsdl-remote-leg-")
    filenames, _ = datagen.generate_data_local(
        16_000, num_files, 1, 0.0, tmpdir)
    # Budget the tiers below the working set (thrash regime): one
    # decoded file's bytes, measured through the local source. Probing
    # EVERY file also warms the OS page cache, so the first-measured
    # side doesn't additionally pay cold-disk decode the second skips.
    file_bytes = max(LocalSource().read_table(f).nbytes
                     for f in filenames)
    hot_bytes = int(2.5 * file_bytes)
    disk_bytes = int(3.5 * file_bytes)

    def _storage_counts() -> dict:
        c = rt_metrics.counter
        return {
            "hot_hits": c("rsdl_storage_hits_total", tier="hot").value,
            "hot_misses": c("rsdl_storage_misses_total", tier="hot").value,
            "disk_hits": c("rsdl_storage_hits_total", tier="disk").value,
            "remote_misses": c("rsdl_storage_misses_total",
                               tier="remote").value,
            "remote_bytes": c("rsdl_storage_remote_bytes_read_total").value,
            "prefetch_issued": c("rsdl_storage_prefetch_issued_total").value,
            "prefetch_hits": c("rsdl_storage_prefetch_hits_total").value,
        }

    def run_side(prefetch: bool) -> "tuple[float, tuple, dict]":
        """(rows_per_sec, delivered key stream, storage counter delta)
        for one A/B side: fresh simulated source (same seed, so the
        same per-path latency draws), fresh tiered cache."""
        sim = SimulatedObjectStore(
            inner=LocalSource(), first_byte_ms=20.0, mb_per_s=200.0,
            jitter_pct=0.0, error_rate=0.0, seed=seed)
        store = TieredStore(hot_bytes,
                            disk=DiskTier(max_bytes=disk_bytes),
                            source=sim)
        prev_source = rt_storage.set_source(sim)
        os.environ["RSDL_STORAGE_PREFETCH"] = "1" if prefetch else "0"
        before = _storage_counts()
        stream: list = []
        rows = {"n": 0}

        def consumer(rank, epoch, refs):
            if refs is None:
                return
            for ref in refs:
                keys = ref.result().column("key").to_pylist()
                rows["n"] += len(keys)
                stream.extend(keys)

        start = time.monotonic()
        try:
            run_shuffle(filenames, consumer, epochs,
                        num_reducers=reducers, num_trainers=1,
                        max_concurrent_epochs=1, seed=seed,
                        collect_stats=False, file_cache=store,
                        num_workers=workers, executor_backend="thread")
        finally:
            duration = time.monotonic() - start
            os.environ.pop("RSDL_STORAGE_PREFETCH", None)
            rt_storage.set_source(prev_source)
            store.close()
        after = _storage_counts()
        delta = {key: after[key] - before[key] for key in after}
        return rows["n"] / duration, tuple(stream), delta

    off_rate, off_stream, _off_delta = run_side(prefetch=False)
    on_rate, on_stream, on_delta = run_side(prefetch=True)

    hot_total = on_delta["hot_hits"] + on_delta["hot_misses"]
    disk_probes = on_delta["disk_hits"] + on_delta["remote_misses"]
    issued = on_delta["prefetch_issued"]
    result = {
        "remote_rows_per_sec": round(on_rate, 1),
        "remote_prefetch_off_rows_per_sec": round(off_rate, 1),
        "remote_prefetch_speedup_x": round(on_rate / off_rate, 3)
        if off_rate > 0 else 0.0,
        "remote_cache_hit_rate_hot": round(
            on_delta["hot_hits"] / hot_total, 4) if hot_total else 0.0,
        "remote_cache_hit_rate_disk": round(
            on_delta["disk_hits"] / disk_probes, 4) if disk_probes else 0.0,
        "remote_prefetch_efficiency": round(
            on_delta["prefetch_hits"] / issued, 4) if issued else 0.0,
        "remote_prefetch_issued": int(issued),
        "remote_bytes_read": int(on_delta["remote_bytes"]),
        "remote_output_bit_identical": off_stream == on_stream,
        "remote_files": num_files,
        "remote_epochs": epochs,
    }
    result["remote_ok"] = bool(result["remote_output_bit_identical"]
                               and on_rate > off_rate and issued > 0)
    return result


def _run_serve_leg(filenames, seed: int = 0,
                   trainer_streams: int = 8,
                   shards: int = 4) -> dict:
    """Serving-plane leg: ``trainer_streams`` concurrent remote trainer
    streams draining one pre-shuffled epoch through the sharded queue
    fabric, measured three ways on the same seed —

    1. single shard, shm-handle delivery (the pre-PR-10 topology's
       process count with the new wire),
    2. ``shards`` shards, shm-handle delivery (the headline
       ``serve_rows_per_sec``; the ratio vs leg 1 is the shard-scaling
       evidence), and
    3. ``shards`` shards, streamed v2 delivery (same table flow, so the
       wire-byte ratio vs leg 2 attributes the handle win per layer,
       not by inference).

    The shuffle runs to completion BEFORE each clock starts: the leg
    times the serving plane, not the producer. Byte/handle/compression
    counters are attributed per leg by delta
    (``stats.queue_serve_totals``).
    """
    import threading

    from ray_shuffling_data_loader_tpu import multiqueue as mq
    from ray_shuffling_data_loader_tpu import multiqueue_service as svc
    from ray_shuffling_data_loader_tpu import stats as rsdl_stats
    from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
    from ray_shuffling_data_loader_tpu.shuffle import shuffle as run_shuffle

    # A small sub-corpus: the leg measures the serving plane's relative
    # scaling/wire behavior, not corpus throughput.
    leg_files = filenames[:2]
    num_reducers = trainer_streams

    def _fill_queue():
        queue = mq.MultiQueue(trainer_streams)

        def consumer(rank, epoch, refs):
            queue_idx = plan_ir.queue_index(epoch, rank, trainer_streams)
            if refs is None:
                queue.put(queue_idx, None)
            else:
                queue.put_batch(queue_idx, list(refs))

        run_shuffle(leg_files, consumer, 1, num_reducers=num_reducers,
                    num_trainers=trainer_streams, max_concurrent_epochs=1,
                    seed=seed, collect_stats=False, file_cache=None)
        return queue

    def _drain(num_shards: int, delivery: str) -> "tuple[float, dict]":
        queue = _fill_queue()
        counts = [0] * trainer_streams
        errors: list = []
        before = rsdl_stats.queue_serve_totals()
        with svc.serve_queue_sharded(queue, num_shards=num_shards,
                                     num_trainers=trainer_streams
                                     ) as sharded:

            def consume(rank: int) -> None:
                try:
                    with svc.ShardedRemoteQueue(
                            sharded.shard_map, max_batch=4,
                            delivery=delivery) as remote:
                        queue_idx = plan_ir.queue_index(
                            0, rank, trainer_streams)
                        while True:
                            table = remote.get(queue_idx)
                            if table is None:
                                return
                            counts[rank] += table.num_rows
                except BaseException as e:  # noqa: BLE001 - re-raised
                    errors.append(e)

            threads = [threading.Thread(target=consume, args=(r,),
                                        daemon=True,
                                        name=f"bench-serve-{r}")
                       for r in range(trainer_streams)]
            start = timeit.default_timer()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            duration = max(timeit.default_timer() - start, 1e-9)
        queue.shutdown()
        if errors:
            raise errors[0]
        after = rsdl_stats.queue_serve_totals()
        delta = {key: after[key] - before[key]
                 for key in ("queue_payload_bytes", "queue_bytes_on_wire",
                             "queue_handle_hits", "queue_handle_misses",
                             "queue_compression_saved_bytes")}
        return sum(counts) / duration, delta

    single_rate, _single = _drain(1, "auto")
    sharded_rate, handle_delta = _drain(shards, "auto")
    _stream_rate, stream_delta = _drain(shards, "stream")

    wire_handle = max(1, handle_delta["queue_bytes_on_wire"])
    wire_stream = stream_delta["queue_bytes_on_wire"]
    saved = stream_delta["queue_compression_saved_bytes"]
    compression_ratio = (
        (wire_stream + saved) / wire_stream if wire_stream else 1.0)
    return {
        "serve_shards": shards,
        "serve_trainer_streams": trainer_streams,
        "serve_rows_per_sec": round(sharded_rate, 1),
        "serve_rows_per_sec_single_shard": round(single_rate, 1),
        "serve_speedup_vs_single_shard": round(
            sharded_rate / single_rate, 3) if single_rate else None,
        "queue_bytes_on_wire": handle_delta["queue_bytes_on_wire"],
        "queue_bytes_on_wire_stream": wire_stream,
        "serve_handle_wire_reduction_x": round(
            wire_stream / wire_handle, 1),
        "queue_handle_hits": handle_delta["queue_handle_hits"],
        "queue_handle_misses": handle_delta["queue_handle_misses"],
        "queue_compression_ratio": round(compression_ratio, 4),
    }


def _run_latency_leg(filenames, seed: int = 0,
                     trainer_streams: int = 2,
                     shards: int = 2) -> dict:
    """Delivery-latency leg (runtime/latency.py): ``trainer_streams``
    remote trainers drain one pre-shuffled epoch over the SHARDED
    serving plane, each closing the loop through a real
    ``JaxShufflingDataset`` (convert + device transfer), on BOTH
    delivery paths — shm-handle first, then streamed v2 bytes. The
    sketch's centroid deltas between snapshots attribute the quantiles
    per path exactly (cumulative counts subtract), and the headline
    ``delivery_p99_ms`` / ``freshness_p99_ms`` are gated by
    ``--baseline`` like any other metric.
    """
    import threading

    from ray_shuffling_data_loader_tpu import multiqueue as mq
    from ray_shuffling_data_loader_tpu import multiqueue_service as svc
    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
    from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
    from ray_shuffling_data_loader_tpu.runtime import latency as rt_lat
    from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
    from ray_shuffling_data_loader_tpu.shuffle import shuffle as run_shuffle
    from ray_shuffling_data_loader_tpu.workloads.dlrm_criteo import dlrm_spec

    leg_files = filenames[:2]
    series = "rsdl_delivery_latency_seconds_centroid"

    def _snapshot() -> dict:
        return dict(rt_metrics.parse_exposition(
            rt_metrics.render()).get(series, {}))

    def _delta(now: dict, base: dict) -> dict:
        return {labels: value - base.get(labels, 0.0)
                for labels, value in now.items()
                if value - base.get(labels, 0.0) > 0}

    def _hop_stats(delta: dict, hop: str):
        counts: dict = {}
        for labels, value in delta.items():
            d = dict(labels)
            if d.get("hop") != hop or "c" not in d:
                continue
            centroid = float(d["c"])
            counts[centroid] = counts.get(centroid, 0.0) + value
        total = int(sum(counts.values()))
        if not total:
            return None
        return {
            "count": total,
            "p50": rt_metrics._centroid_quantile(counts, total, 0.5),
            "p95": rt_metrics._centroid_quantile(counts, total, 0.95),
            "p99": rt_metrics._centroid_quantile(counts, total, 0.99),
        }

    def _drain(delivery: str) -> None:
        queue = mq.MultiQueue(trainer_streams)

        def consumer(rank, epoch, refs):
            queue_idx = plan_ir.queue_index(epoch, rank, trainer_streams)
            if refs is None:
                queue.put(queue_idx, None)
            else:
                queue.put_batch(queue_idx, list(refs))

        run_shuffle(leg_files, consumer, 1,
                    num_reducers=trainer_streams,
                    num_trainers=trainer_streams, max_concurrent_epochs=1,
                    seed=seed, collect_stats=False, file_cache=None)
        errors: list = []
        with svc.serve_queue_sharded(queue, num_shards=shards,
                                     num_trainers=trainer_streams
                                     ) as sharded:

            def consume(rank: int) -> None:
                try:
                    remote = svc.ShardedRemoteQueue(
                        sharded.shard_map, max_batch=2, delivery=delivery)
                    # Small batches + drop_last=False: the leg measures
                    # latency, not throughput, and must convert/transfer
                    # even a smoke-sized corpus so the device hops have
                    # samples.
                    ds = JaxShufflingDataset(
                        leg_files, num_epochs=1,
                        num_trainers=trainer_streams, batch_size=8_192,
                        rank=rank, batch_queue=remote,
                        shuffle_result=None, seed=seed, prefetch_size=2,
                        drop_last=False, **dlrm_spec())
                    try:
                        ds.set_epoch(0)
                        for _features, _label in ds:
                            pass
                    finally:
                        ds.close()
                        remote.close()
                except BaseException as e:  # noqa: BLE001 - re-raised
                    errors.append(e)

            threads = [threading.Thread(target=consume, args=(rank,),
                                        daemon=True,
                                        name=f"bench-latency-{rank}")
                       for rank in range(trainer_streams)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
        queue.shutdown()
        if errors:
            raise errors[0]

    before = _snapshot()
    _drain("auto")       # shm-handle path (loopback)
    after_handle = _snapshot()
    _drain("stream")     # streamed v2 bytes, same table flow
    after_stream = _snapshot()

    handle_delta = _delta(after_handle, before)
    stream_delta = _delta(after_stream, after_handle)
    whole_delta = _delta(after_stream, before)
    delivered = _hop_stats(handle_delta, rt_lat.HOP_BIRTH_TO_DELIVERED)
    delivered_stream = _hop_stats(stream_delta,
                                  rt_lat.HOP_BIRTH_TO_DELIVERED)
    device = _hop_stats(whole_delta, rt_lat.HOP_BIRTH_TO_DEVICE)
    queued = _hop_stats(whole_delta, rt_lat.HOP_BIRTH_TO_QUEUED)
    if delivered is None or delivered_stream is None:
        raise RuntimeError(
            "latency leg observed no birth_to_delivered samples on one "
            "of the delivery paths (handle "
            f"{delivered}, stream {delivered_stream})")
    per_queue = {
        dict(labels).get("queue", "?"): round(entry["p99"] * 1e3, 3)
        for labels, entry in rt_metrics.sketch_quantiles(
            {series: whole_delta}, "rsdl_delivery_latency_seconds",
            qs=(0.99,), hop=rt_lat.HOP_BIRTH_TO_DELIVERED).items()}
    result = {
        "latency_trainer_streams": trainer_streams,
        "latency_shards": shards,
        "delivery_p50_ms": round(delivered["p50"] * 1e3, 3),
        "delivery_p95_ms": round(delivered["p95"] * 1e3, 3),
        "delivery_p99_ms": round(delivered["p99"] * 1e3, 3),
        "delivery_p99_ms_stream": round(
            delivered_stream["p99"] * 1e3, 3),
        "delivery_frames": delivered["count"] + delivered_stream["count"],
        "latency_per_queue_p99_ms": per_queue,
    }
    if queued is not None:
        result["queued_p99_ms"] = round(queued["p99"] * 1e3, 3)
    if device is not None:
        result["freshness_p99_ms"] = round(device["p99"] * 1e3, 3)
    return result


def _run_stream_leg(seed: int = 0, windows: int = 3,
                    files_per_window: int = 2,
                    rows_per_file: int = 4_096) -> dict:
    """Streaming leg (streaming/): a seeded ``SyntheticEventSource``
    drives ``windows`` count-bounded windows through the
    :class:`StreamingShuffleRunner` while one remote trainer drains the
    served stream through a real ``JaxShufflingDataset`` (convert +
    device transfer) — window N+1 assembles and shuffles UNDER window
    N's serve (``max_concurrent_epochs=2``), so the per-window watermark
    lag samples measure the real pipelining gap, in stream seconds.
    Freshness is the PR 11 birth->device sketch measured on LIVE
    windows (the same plane the latency leg gates on static epochs),
    reported as ``stream_freshness_p99_ms`` so the two legs never
    collide in one record. Hermetic: the drifting click stream is
    generated into a fresh tempdir and every arrival is a pure function
    of ``(seed, event_index)``.
    """
    import tempfile
    import threading

    from ray_shuffling_data_loader_tpu import multiqueue as mq
    from ray_shuffling_data_loader_tpu import multiqueue_service as svc
    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
    from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
    from ray_shuffling_data_loader_tpu.runtime import latency as rt_lat
    from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
    from ray_shuffling_data_loader_tpu.streaming import (
        StreamingShuffleRunner, SyntheticEventSource)
    from ray_shuffling_data_loader_tpu.streaming import window as st_window
    from ray_shuffling_data_loader_tpu.workloads import dlrm_criteo

    num_files = windows * files_per_window
    total_rows = num_files * rows_per_file
    series = "rsdl_delivery_latency_seconds_centroid"
    close_hist = rt_metrics.histogram(
        "rsdl_stream_window_close_seconds",
        "wall time from a window's first event to its seal")

    def _snapshot() -> dict:
        return dict(rt_metrics.parse_exposition(
            rt_metrics.render()).get(series, {}))

    def _device_p99_ms(now: dict, base: dict):
        counts: dict = {}
        for labels, value in now.items():
            delta = value - base.get(labels, 0.0)
            d = dict(labels)
            if (delta <= 0 or d.get("hop") != rt_lat.HOP_BIRTH_TO_DEVICE
                    or "c" not in d):
                continue
            centroid = float(d["c"])
            counts[centroid] = counts.get(centroid, 0.0) + delta
        total = int(sum(counts.values()))
        if not total:
            return None
        return round(
            rt_metrics._centroid_quantile(counts, total, 0.99) * 1e3, 3)

    with tempfile.TemporaryDirectory(prefix="rsdl-bench-stream-") as td:
        files = dlrm_criteo.generate_drifting_stream(
            num_files, rows_per_file, td, seed=seed)
        source = SyntheticEventSource(files, seed=seed,
                                      total_events=num_files)
        queue = mq.MultiQueue(windows)
        lag_samples: list = []
        holder: dict = {}

        def consumer(rank, epoch, refs):
            queue_idx = plan_ir.queue_index(epoch, rank, 1)
            if refs is None:
                queue.put(queue_idx, None)
            else:
                queue.put_batch(queue_idx, list(refs))

        def on_window_served(_window_index: int) -> None:
            runner = holder["runner"]
            ingest = runner.assembler.ingest_watermark
            serve = runner.serve_watermark
            if ingest != float("-inf") and serve != float("-inf"):
                lag_samples.append(max(0.0, ingest - serve))

        runner = StreamingShuffleRunner(
            source, consumer, num_reducers=max(2, files_per_window),
            num_trainers=1, seed=seed, max_concurrent_epochs=2,
            policy=st_window.WindowPolicy(max_files=files_per_window),
            on_window_served=on_window_served)
        holder["runner"] = runner

        close_before = (close_hist.sum, close_hist.count)
        lat_before = _snapshot()
        rows_holder = {"rows": 0}
        errors: list = []
        start = timeit.default_timer()
        with svc.serve_queue_sharded(queue, num_shards=1,
                                     num_trainers=1) as sharded:

            def drain() -> None:
                try:
                    remote = svc.ShardedRemoteQueue(sharded.shard_map,
                                                    max_batch=2)
                    ds = JaxShufflingDataset(
                        files, num_epochs=windows, num_trainers=1,
                        batch_size=8_192, rank=0, batch_queue=remote,
                        shuffle_result=None, seed=seed, prefetch_size=2,
                        drop_last=False, **dlrm_criteo.dlrm_spec())
                    try:
                        for epoch in plan_ir.epoch_range(0, windows):
                            ds.set_epoch(epoch)
                            for _features, label in ds:
                                rows_holder["rows"] += int(label.shape[0])
                    finally:
                        ds.close()
                        remote.close()
                except BaseException as e:  # noqa: BLE001 - re-raised
                    errors.append(e)

            trainer = threading.Thread(target=drain, daemon=True,
                                       name="bench-stream-trainer")
            trainer.start()
            summary = runner.run()
            trainer.join(timeout=300)
        duration_s = timeit.default_timer() - start
        queue.shutdown()
        runner.close()
        if errors:
            raise errors[0]
        rows_delivered = rows_holder["rows"]
        if rows_delivered != total_rows:
            raise RuntimeError(
                f"stream leg delivered {rows_delivered} rows, expected "
                f"{total_rows} — the windowed stream lost or duplicated "
                "rows")

    lag_p99 = 0.0
    if lag_samples:
        ordered = sorted(lag_samples)
        lag_p99 = ordered[min(len(ordered) - 1,
                              int(0.99 * len(ordered)))]
    close_sum = close_hist.sum - close_before[0]
    close_count = close_hist.count - close_before[1]
    result = {
        "stream_windows": summary["windows_served"],
        "stream_events": summary["events_sealed"],
        "stream_rows_per_sec": round(total_rows / duration_s, 1),
        "stream_duration_s": round(duration_s, 3),
        "watermark_lag_p99_s": round(lag_p99, 6),
        "late_events": summary["late_events"],
        "window_close_ms": round(1e3 * close_sum / close_count, 3)
        if close_count else 0.0,
    }
    fresh = _device_p99_ms(_snapshot(), lat_before)
    if fresh is not None:
        result["stream_freshness_p99_ms"] = fresh
    return result


def _run_tenancy_leg(filenames, seed: int = 0, hot_weight: float = 3.0,
                     cold_weight: float = 1.0) -> dict:
    """Multi-tenant contention leg (tenancy/): a hot streaming tenant
    (weight ``hot_weight``, rank 0) and a cold batch-replay tenant
    (weight ``cold_weight``, rank 1) share ONE serving shard's
    replay-byte budget under the deficit-round-robin scheduler.

    Two phases over identical fills: the hot tenant first drains its
    rank ALONE (the solo latency baseline), then both tenants drain
    concurrently. The fairness ratio is hot rows over cold rows at the
    instant the hot tenant finishes — under equal demand and sustained
    contention the DRR should split delivery ~``hot_weight/cold_weight``
    (the one-frame-per-GET liveness floor dilutes it a few percent
    toward 1). Per-tenant p99s come from the
    ``rsdl_tenant_delivery_latency_seconds`` sketch the wire clients
    feed (both announce their identity via OP_TENANT, so the leg
    exercises the wire binding, not just the server-side rank table),
    and ``tenancy_latency_ratio_x`` is the ISSUE contract number: the
    hot tenant's contended p99 over its solo p99 (target <= 1.5x).
    Admission evidence rides along: both working sets register against
    a journaled controller sized to hold them, plus one deliberately
    oversized ask that must be rejected.
    """
    import tempfile
    import threading

    from ray_shuffling_data_loader_tpu import dataset as rsdl_dataset
    from ray_shuffling_data_loader_tpu import multiqueue as mq
    from ray_shuffling_data_loader_tpu import multiqueue_service as svc
    from ray_shuffling_data_loader_tpu import tenancy as rt_tenancy
    from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
    from ray_shuffling_data_loader_tpu.runtime import latency as rt_lat
    from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
    from ray_shuffling_data_loader_tpu.shuffle import shuffle as run_shuffle
    from ray_shuffling_data_loader_tpu.tenancy import admission as rt_adm

    leg_files = filenames[:2]
    streams = 2  # rank 0 = hot, rank 1 = cold
    # Many small frames: the DRR meters bytes per pop, and every GET
    # delivers one frame unconditionally (the liveness floor) — so the
    # weighted split is only observable when a rank's epoch spans far
    # more frames than its consumer issues GETs. 128 reducers give each
    # rank ~128 frames; with a deep max_batch most frames then move as
    # DRR grants, not floors.
    reducers = int(os.environ.get("RSDL_BENCH_TENANCY_REDUCERS", 128))
    # Sustained demand: several pre-filled epochs per rank, so the
    # contended drain spans many DRR replenish cycles instead of
    # finishing inside the first one.
    epochs = int(os.environ.get("RSDL_BENCH_TENANCY_EPOCHS", 3))
    series = "rsdl_tenant_delivery_latency_seconds_centroid"
    hot_slo_p99_ms = float(os.environ.get("RSDL_BENCH_TENANCY_SLO_MS",
                                          50.0))
    hot_ctx = rt_tenancy.TenantContext("hot", priority="interactive",
                                       weight=hot_weight,
                                       slo_p99_ms=hot_slo_p99_ms)
    cold_ctx = rt_tenancy.TenantContext("cold", priority="batch",
                                        weight=cold_weight)
    tenants_cfg = {"hot": {"weight": hot_weight, "ranks": [0]},
                   "cold": {"weight": cold_weight, "ranks": [1]}}

    def _snapshot() -> dict:
        return dict(rt_metrics.parse_exposition(
            rt_metrics.render()).get(series, {}))

    def _tenant_p99(now: dict, base: dict, tenant: str):
        counts: dict = {}
        for labels, value in now.items():
            delta = value - base.get(labels, 0.0)
            d = dict(labels)
            if (delta <= 0 or d.get("tenant") != tenant
                    or d.get("hop") != rt_lat.HOP_QUEUED_TO_DELIVERED
                    or "c" not in d):
                continue
            centroid = float(d["c"])
            counts[centroid] = counts.get(centroid, 0.0) + delta
        total = int(sum(counts.values()))
        if not total:
            return None
        return rt_metrics._centroid_quantile(counts, total, 0.99)

    hot_queues = [plan_ir.queue_index(e, 0, streams)
                  for e in plan_ir.epoch_range(0, epochs)]

    def _shuffle_refs() -> dict:
        """One shuffled corpus as {queue_idx: [ref..., None sentinel]}
        — held outside the MultiQueue so a phase can preload a rank
        (batch replay) or feed it live (a stream)."""
        refs_by_queue: dict = {}

        def consumer(rank, epoch, refs):
            queue_idx = plan_ir.queue_index(epoch, rank, streams)
            items = refs_by_queue.setdefault(queue_idx, [])
            if refs is None:
                items.append(None)
            else:
                items.extend(refs)

        run_shuffle(leg_files, consumer, epochs, num_reducers=reducers,
                    num_trainers=streams, max_concurrent_epochs=epochs,
                    seed=seed, collect_stats=False, file_cache=None)
        return refs_by_queue

    def _drain(rank: int, ctx, counts: dict, started: threading.Event,
               finished: threading.Event, errors: list, server) -> None:
        """One tenant's trainer: announce identity over the wire
        (OP_TENANT), drain the rank's epoch, count rows as they land."""
        try:
            started.wait(timeout=60)
            # max_batch deep enough that granted frames dominate the
            # one-frame liveness floor (the floor is what dilutes the
            # measured split below the configured weights).
            remote = svc.RemoteQueue(server.address, max_batch=128,
                                     num_trainers=streams, tenant=ctx)
            try:
                for epoch in plan_ir.epoch_range(0, epochs):
                    queue_idx = plan_ir.queue_index(epoch, rank, streams)
                    while True:
                        item = remote.get(queue_idx)
                        if item is None:
                            break
                        if isinstance(item, rsdl_dataset.ShuffleFailure):
                            raise RuntimeError(
                                f"tenancy leg rank {rank}: {item}")
                        counts[ctx.tenant_id] += item.num_rows
            finally:
                remote.close()
        except BaseException as e:  # noqa: BLE001 - re-raised by caller
            errors.append(e)
        finally:
            finished.set()

    def _run_phase(contended: bool, feed_dt=None) -> dict:
        """One serve-and-drain round. ``feed_dt=None``: the hot rank is
        preloaded like the cold one and drains greedily (the fairness
        measurement — equal backlog, equal appetite, the DRR decides).
        With ``feed_dt`` the hot rank is a LIVE stream: a feeder thread
        puts one frame every ``feed_dt`` seconds, so the hot tenant's
        queued->delivered dwell measures scheduling delay, not backlog
        depth (the p99 SLO measurement)."""
        refs_by_queue = _shuffle_refs()
        queue = mq.MultiQueue(epochs * streams)
        for queue_idx, items in refs_by_queue.items():
            if feed_dt is not None and queue_idx in hot_queues:
                continue  # fed live below
            for item in items:
                queue.put(queue_idx, item)
        counts = {"hot": 0, "cold": 0}
        errors: list = []
        start_gate = threading.Event()
        hot_done = threading.Event()
        cold_done = threading.Event()
        before = _snapshot()

        def _feed() -> None:
            try:
                start_gate.wait(timeout=60)
                for queue_idx in hot_queues:
                    for item in refs_by_queue.get(queue_idx, []):
                        time.sleep(feed_dt)
                        queue.put(queue_idx, item)
            except BaseException as e:  # noqa: BLE001 - re-raised
                errors.append(e)

        with svc.serve_queue(queue, num_trainers=streams,
                             tenants=tenants_cfg) as server:
            threads = [threading.Thread(
                target=_drain, args=(0, hot_ctx, counts, start_gate,
                                     hot_done, errors, server),
                daemon=True, name="bench-tenancy-hot")]
            if contended:
                threads.append(threading.Thread(
                    target=_drain, args=(1, cold_ctx, counts, start_gate,
                                         cold_done, errors, server),
                    daemon=True, name="bench-tenancy-cold"))
            if feed_dt is not None:
                threads.append(threading.Thread(
                    target=_feed, daemon=True,
                    name="bench-tenancy-feeder"))
            for t in threads:
                t.start()
            t0 = timeit.default_timer()
            start_gate.set()
            hot_done.wait(timeout=300)
            hot_elapsed = timeit.default_timer() - t0
            # The fairness sample: cold's delivery the instant hot's
            # equal-demand drain completes.
            cold_at_hot_finish = counts["cold"]
            for t in threads:
                t.join(timeout=300)
        queue.shutdown()
        if errors:
            raise errors[0]
        return {
            "hot_rows": counts["hot"],
            "hot_frames": sum(
                1 for queue_idx in hot_queues
                for item in refs_by_queue.get(queue_idx, [])
                if item is not None),
            "cold_rows_at_hot_finish": cold_at_hot_finish,
            "hot_elapsed_s": max(hot_elapsed, 1e-9),
            "hot_p99_s": _tenant_p99(_snapshot(), before, "hot"),
        }

    # Admission evidence: both tenants' working sets are journaled
    # accepts; a 64x-oversized ask must journal a reject.
    ask = sum(os.path.getsize(f) for f in leg_files) // streams + 1
    with tempfile.TemporaryDirectory(prefix="rsdl-bench-adm-") as td:
        controller = rt_adm.AdmissionController(
            capacity_bytes=4 * streams * ask,
            journal_path=os.path.join(td, "admission.jsonl"))
        accepted = sum(
            controller.register(ctx, "dataset", f"bench-{ctx.tenant_id}",
                                ask).action == "accept"
            for ctx in (hot_ctx, cold_ctx))
        rejected = int(controller.register(
            rt_tenancy.TenantContext("greedy"), "dataset", "bench-greedy",
            64 * streams * ask).action == "reject")
        replayed = rt_adm.replay(os.path.join(td, "admission.jsonl"),
                                 capacity_bytes=4 * streams * ask)
        admission_replay_ok = (replayed.journal_bytes()
                               == controller.journal_bytes())
        controller.close()

    # Pin the DRR quantum to a few frame-sizes of THIS corpus: with the
    # default 1 MiB quantum a small corpus gets more credit per
    # replenish than a whole rank queue holds, every pop is granted,
    # and the measured split collapses to the demand ratio (1:1)
    # instead of the weights. ~6 frames of credit per replenish keeps
    # granted frames dominant over the one-frame liveness floor.
    frame_est = max(1, int(2.5 * sum(os.path.getsize(f)
                                     for f in leg_files))
                    // (streams * reducers))
    quantum_key = "RSDL_QUEUE_TENANT_DRR_QUANTUM_BYTES"
    prior_quantum = os.environ.get(quantum_key)
    os.environ[quantum_key] = str(16 * frame_est)
    try:
        solo = _run_phase(contended=False)
        contended = _run_phase(contended=True)
        # Live-stream p99 phases: feed the hot rank one frame at a time
        # at half its measured solo greedy drain rate (a stream the
        # serving plane can comfortably keep up with), first alone and
        # then against the cold tenant's full greedy backlog replay.
        feed_dt = min(0.02, max(5e-4,
                                2.0 * solo["hot_elapsed_s"]
                                / max(1, solo["hot_frames"])))
        lat_solo = _run_phase(contended=False, feed_dt=feed_dt)
        lat_cont = _run_phase(contended=True, feed_dt=feed_dt)
    finally:
        if prior_quantum is None:
            os.environ.pop(quantum_key, None)
        else:
            os.environ[quantum_key] = prior_quantum

    weight_ratio = hot_weight / cold_weight
    fairness = (contended["hot_rows"]
                / max(1, contended["cold_rows_at_hot_finish"]))
    p99_solo = lat_solo["hot_p99_s"]
    p99_cont = lat_cont["hot_p99_s"]
    latency_ratio = (round(p99_cont / p99_solo, 3)
                     if p99_solo and p99_cont else None)
    # The contract checks (loosened from the deterministic +-15% the
    # fairshare unit test proves, to absorb the liveness floor and
    # loopback scheduling noise of a live multi-thread drain). The p99
    # contract accepts EITHER bound: contended <= 1.5x solo, or the
    # hot tenant's absolute slo_p99_ms — at millisecond solo baselines
    # the ratio measures thread-wakeup jitter under CPU load more than
    # queueing, and the absolute SLO is the bound a tenant actually
    # signed up for.
    fairness_ok = abs(fairness / weight_ratio - 1.0) <= 0.35
    latency_ok = (latency_ratio is None or latency_ratio <= 1.5
                  or (p99_cont is not None
                      and p99_cont * 1e3 <= hot_slo_p99_ms))
    result = {
        "tenancy_weight_ratio": round(weight_ratio, 3),
        "tenancy_fairness_ratio": round(fairness, 3),
        "tenancy_hot_rows": contended["hot_rows"],
        "tenancy_cold_rows_at_hot_finish":
            contended["cold_rows_at_hot_finish"],
        "tenancy_hot_rows_per_sec": round(
            contended["hot_rows"] / contended["hot_elapsed_s"], 1),
        "tenancy_cold_rows_per_sec": round(
            contended["cold_rows_at_hot_finish"]
            / contended["hot_elapsed_s"], 1),
        "tenancy_solo_rows_per_sec": round(
            solo["hot_rows"] / solo["hot_elapsed_s"], 1),
        "tenancy_hot_slo_p99_ms": hot_slo_p99_ms,
        "tenancy_admitted": accepted,
        "tenancy_rejected": rejected,
        "tenancy_admission_replay_ok": admission_replay_ok,
        "tenancy_ok": bool(fairness_ok and latency_ok
                           and admission_replay_ok),
    }
    if p99_solo is not None:
        result["tenancy_hot_p99_ms_solo"] = round(p99_solo * 1e3, 3)
    if p99_cont is not None:
        result["tenancy_hot_p99_ms_contended"] = round(p99_cont * 1e3, 3)
    if latency_ratio is not None:
        result["tenancy_latency_ratio_x"] = latency_ratio
    return result


def _run_elastic_leg(seed: int = 0, num_files: int = 4,
                     rows_per_file: int = 4_096,
                     num_reducers: int = 8) -> dict:
    """Elastic membership leg (membership/): a mid-run rank kill plus a
    boundary rejoin, measured end to end.

    Phase 1 — failure detection: two live local transports, a
    ``HeartbeatProber`` on host 0 watching host 1; host 1's process is
    killed (its transport closed cold, no goodbye) and the leg measures
    the wall time from the kill to the detector's DOWN verdict —
    ``member_down_detect_ms``, the real latency a production shrink
    pays before the plan rewrite can start.

    Phase 2 — resize correctness: a fixed-world
    :class:`membership.elastic.ElasticShuffleRunner` run is the
    reference; the elastic run kills rank 1 mid-epoch 0 via the
    ``member_crash`` chaos site (survivors recompute its undelivered
    reducers from lineage) and rejoins it — plus a NEW rank, growing
    the world uneven — at the epoch boundary. ``rows_lost`` MUST be 0
    and the merged stream bit-identical (reducer outputs are pure in
    ``(seed, epoch, reducer)``); ``resize_stall_ms`` is the recompute
    tax from the first death to epoch completion. Hermetic: synthetic
    parquet in a fresh tempdir, chaos installed and cleared locally.
    """
    import tempfile
    import threading

    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_shuffling_data_loader_tpu import membership as mem
    from ray_shuffling_data_loader_tpu.membership import detector as md
    from ray_shuffling_data_loader_tpu.membership import elastic as me
    from ray_shuffling_data_loader_tpu.parallel import transport as tp
    from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults

    # Phase 1: detection latency over real sockets. Host 0 probes, so
    # host 1 observes its heartbeat frames; host 1's detector tracks
    # rank 0 and the leg measures kill -> DOWN wall time.
    transports = tp.create_local_transports(2, recv_timeout_s=30.0)
    down = threading.Event()
    beats = threading.Semaphore(0)
    det0 = md.FailureDetector([0], heartbeat_s=0.05, suspect_s=0.4,
                              on_down=lambda rank: down.set())

    def _observe(src, inc, view, hb):
        det0.beat(src)
        beats.release()

    transports[1].set_frame_observer(_observe)
    prober = md.HeartbeatProber(transports[0], det0, interval_s=0.05)
    prober.start()
    # Warm up until a handful of real heartbeats have landed, so the
    # detector's smoothed inter-arrival window reflects the live link
    # before the kill (phi is trivially 0.0 at arm time).
    for _ in range(5):
        beats.acquire(timeout=2.0)
    kill_at = timeit.default_timer()
    prober.stop()          # host 0 goes silent: the kill
    detect_deadline = timeit.default_timer() + 5.0
    while not down.is_set() and timeit.default_timer() < detect_deadline:
        det0.poll()
        time.sleep(0.01)
    detect_ms = ((timeit.default_timer() - kill_at) * 1e3
                 if down.is_set() else None)
    for t in transports:
        t.close()

    # Phase 2: shrink + grow correctness and the resize stall.
    with tempfile.TemporaryDirectory(prefix="rsdl_elastic_") as tmpdir:
        filenames = []
        for i in range(num_files):
            start = i * rows_per_file
            table = pa.table({"key": pa.array(
                range(start, start + rows_per_file), type=pa.int64())})
            path = os.path.join(tmpdir, f"elastic_{i}.parquet")
            pq.write_table(table, path)
            filenames.append(path)

        rt_faults.clear()
        fixed = me.ElasticShuffleRunner(
            filenames, num_reducers, seed=seed,
            manager=mem.MembershipManager([0, 1, 2, 3])).run(2)

        rt_faults.install("member_crash:rank1:epoch0", seed=seed)
        manager = mem.MembershipManager([0, 1, 2, 3])
        runner = me.ElasticShuffleRunner(filenames, num_reducers,
                                         seed=seed, manager=manager)
        start_t = timeit.default_timer()
        epoch0 = runner.run_epoch(0)
        stall_ms = float(runner.last_stats.get("resize_stall_ms", 0.0))
        recomputed = int(runner.last_stats.get("recomputed", 0))
        shrunk_view = manager.current_view()
        # Boundary grow: the killed rank rejoins with a bumped
        # incarnation AND a brand-new rank joins — 5 ranks, uneven.
        manager.member_join(1, reason="bench rejoin")
        manager.member_join(4, reason="bench grow")
        epoch1 = runner.run_epoch(1)
        elapsed = timeit.default_timer() - start_t
        rt_faults.clear()

        expected = sum(t.num_rows for epoch in fixed for t in epoch)
        delivered = me.total_rows(epoch0) + me.total_rows(epoch1)
        rows_lost = expected - delivered
        identical = (all(a.equals(b) for a, b in zip(fixed[0], epoch0))
                     and all(a.equals(b)
                             for a, b in zip(fixed[1], epoch1)))

    result = {
        "elastic_rows_per_sec": round(delivered / elapsed, 1),
        "resize_stall_ms": round(stall_ms, 3),
        "rows_lost": int(rows_lost),
        "elastic_shrunk_to": len(shrunk_view.ranks),
        "elastic_grew_to": len(manager.current_view().ranks),
        "elastic_recomputed": recomputed,
        "elastic_ok": bool(rows_lost == 0 and identical
                           and detect_ms is not None),
    }
    if detect_ms is not None:
        result["member_down_detect_ms"] = round(detect_ms, 1)
    return result


def _run_rebalance_leg(seed: int = 0) -> dict:
    """Self-healing serving-plane leg (rebalance/): a hot tenant
    saturates shard 0, the co-located SLO tenant's delivery p99
    breaches, and the journaled controller live-migrates the breaching
    rank to the idle shard — measuring the whole loop end to end.

    Topology: 4 trainer ranks over 2 in-process shards (static
    placement: ranks 0/2 on shard 0, ranks 1/3 on shard 1). Rank 0 is
    the saturator — a feeder pumps frames continuously and a greedy
    deep-batch drain keeps shard 0's serve path busy for the whole leg.
    Rank 2 is the SLO tenant: fed live at a fixed cadence, so its
    queued->delivered dwell measures scheduling delay, not backlog
    depth (the tenancy leg's live-feed protocol). Phase 1 measures the
    contended p99; the breach (against ``rebalance_slo_p99_s``) drives
    a journaled decision and ``rebalance.migrate`` moves rank 2 to
    shard 1 mid-stream — the consumer follows the MOVED redirect, the
    handoff manifest carries the seq cursors — and phase 2 re-measures
    on the now-private shard. ``rebalance_p99_recovery_x`` is
    contended-over-recovered (> 1 is the contract);
    ``rebalance_stall_ms`` is the migrate() wall time (the seal
    window); ``rows_lost`` MUST be 0 with every row offset delivered
    exactly once, in order, across the live move.
    """
    import tempfile
    import threading

    import numpy as np
    import pyarrow as pa

    from ray_shuffling_data_loader_tpu import multiqueue as mq
    from ray_shuffling_data_loader_tpu import multiqueue_service as svc
    from ray_shuffling_data_loader_tpu import rebalance as rb
    from ray_shuffling_data_loader_tpu import tenancy as rt_tenancy
    from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
    from ray_shuffling_data_loader_tpu.runtime import latency as rt_lat
    from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics

    trainers, hot_rank, slo_rank = 4, 0, 2
    rows_per_frame = 512
    slo_frames = int(os.environ.get("RSDL_BENCH_REBALANCE_FRAMES", 60))
    warmup_frames = 8
    feed_dt = 0.004
    # The breach threshold: comfortably above an idle shard's dwell
    # (sub-millisecond on loopback), comfortably below a saturated one.
    slo_s = float(os.environ.get("RSDL_BENCH_REBALANCE_SLO_MS", 2.0)) / 1e3
    series = "rsdl_tenant_delivery_latency_seconds_centroid"
    sat_ctx = rt_tenancy.TenantContext("sat", priority="batch")
    slo_ctx = rt_tenancy.TenantContext("slo", priority="interactive",
                                       slo_p99_ms=slo_s * 1e3)

    q_hot = plan_ir.queue_index(0, hot_rank, trainers)
    q_slo = plan_ir.queue_index(0, slo_rank, trainers)
    frame = pa.table({"key": pa.array(range(rows_per_frame),
                                      type=pa.int64())})
    # The saturator's frames are large and incompressible: each one
    # costs the serving shard's (per-server, single-threaded) codec
    # pool tens of milliseconds of zlib, so shard 0's pool stays
    # backlogged and the co-located SLO tenant's small frames queue
    # behind the saturator's jobs — contention that is genuinely
    # SHARD-LOCAL (the sibling shard's pool is idle), which is exactly
    # what the migration escapes. zlib releases the GIL on large
    # buffers, so this load does not blur the measurement with
    # interpreter noise the way a pure-Python spin loop would.
    sat_rows_per_frame = 1 << 16
    sat_frame = pa.table({"key": pa.array(
        np.random.default_rng(seed).integers(
            0, 1 << 62, size=sat_rows_per_frame, dtype=np.int64))})

    def _snapshot() -> dict:
        return dict(rt_metrics.parse_exposition(
            rt_metrics.render()).get(series, {}))

    def _slo_p99(now: dict, base: dict):
        counts: dict = {}
        for labels, value in now.items():
            delta = value - base.get(labels, 0.0)
            d = dict(labels)
            if (delta <= 0 or d.get("tenant") != "slo"
                    or d.get("hop") != rt_lat.HOP_QUEUED_TO_DELIVERED
                    or "c" not in d):
                continue
            centroid = float(d["c"])
            counts[centroid] = counts.get(centroid, 0.0) + delta
        total = int(sum(counts.values()))
        if not total:
            return None
        return rt_metrics._centroid_quantile(counts, total, 0.99)

    queue = mq.MultiQueue(trainers)
    stop = threading.Event()
    errors: list = []
    sat_rows = [0]

    def _feed_hot() -> None:
        # Depth-capped: the drain rate is codec-pool-bound (each frame
        # is a ~30ms compress), so an unpaced feeder would grow the
        # backlog without bound. A modest cap keeps the pool saturated
        # without hoarding memory — and keeps this thread asleep most
        # of the time, off the interpreter lock.
        try:
            while not stop.is_set():
                if queue.sizes([q_hot])[0] < 16:
                    queue.put(q_hot, sat_frame)
                else:
                    time.sleep(0.005)
        except BaseException as e:  # noqa: BLE001 - re-raised by caller
            errors.append(e)
        finally:
            queue.put(q_hot, None)

    def _drain_hot(remote) -> None:
        try:
            while True:
                item = remote.get(q_hot)
                if item is None:
                    break
                sat_rows[0] += item.num_rows
        except BaseException as e:  # noqa: BLE001 - re-raised by caller
            errors.append(e)

    def _slo_phase(remote, offsets: list) -> "float | None":
        """Feed warmup + ``slo_frames`` frames live, drain them as they
        land, and return the p99 of the measured span from the tenant
        sketch. The warmup frames are delivered (and position-checked)
        but excluded from the p99: they absorb one-time costs — the
        first dial, and after a migration the MOVED redirect — so the
        phase measures steady-state scheduling delay on its shard."""
        before = None
        fed = threading.Event()
        total = warmup_frames + slo_frames

        def _feed_slo() -> None:
            try:
                for _ in range(total):
                    time.sleep(feed_dt)
                    queue.put(q_slo, frame)
            except BaseException as e:  # noqa: BLE001 - re-raised
                errors.append(e)
            finally:
                fed.set()

        feeder = threading.Thread(target=_feed_slo, daemon=True,
                                  name="bench-rebalance-slo-feeder")
        feeder.start()
        drained = 0
        while drained < total:
            item, row_offset = remote.get_positioned(q_slo)
            if item is None:
                break
            offsets.append(row_offset)
            drained += 1
            if drained == warmup_frames:
                before = _snapshot()
        feeder.join(timeout=120)
        if not fed.is_set() or before is None:
            return None
        return _slo_p99(_snapshot(), before)

    with tempfile.TemporaryDirectory(prefix="rsdl_rebalance_") as tmpdir:
        journal_path = os.path.join(tmpdir, "rebalance.journal")
        # Frame compression ON and delivery pinned to streamed for this
        # leg (zlib is stdlib, always present): the per-server codec
        # pool is the shard-local resource the saturator exhausts, and
        # shm-handle delivery would bypass it entirely on loopback.
        # Scoped to server construction — policy is read in __init__.
        comp_env = {"RSDL_QUEUE_COMPRESSION": "zlib",
                    "RSDL_QUEUE_CODEC_THREADS": "1",
                    "RSDL_QUEUE_DELIVERY": "stream"}
        saved_env = {k: os.environ.get(k) for k in comp_env}
        os.environ.update(comp_env)
        try:
            sss_cm = svc.ShardedQueueServer(queue, 2,
                                            num_trainers=trainers)
        finally:
            for k, v in saved_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        with sss_cm as sss:
            controller = rb.RebalanceController(
                sss.shard_map, journal_path=journal_path,
                rebalance_slo_p99_s=slo_s)
            sat_remote = svc.RemoteQueue(sss.servers[0].address,
                                         num_trainers=trainers,
                                         max_batch=4, tenant=sat_ctx)
            slo_remote = svc.ShardedRemoteQueue(sss.shard_map,
                                                max_batch=4,
                                                tenant=slo_ctx)
            offsets: list = []
            threads = [threading.Thread(target=_feed_hot, daemon=True,
                                        name="bench-rebalance-hot-feeder"),
                       threading.Thread(target=_drain_hot,
                                        args=(sat_remote,), daemon=True,
                                        name="bench-rebalance-hot-drain")]
            try:
                for t in threads:
                    t.start()
                t0 = timeit.default_timer()
                p99_contended = _slo_phase(slo_remote, offsets)
                # The breach drives the journaled decision: the measured
                # p99 over the threshold is exactly what the
                # tenant_delivery_slo detector judges in a live server.
                breached = (p99_contended is not None
                            and p99_contended > slo_s)
                move_t0 = timeit.default_timer()
                state = rb.migrate(
                    controller, slo_rank,
                    target=controller.pick_target(slo_rank),
                    reason=f"slo p99 {p99_contended or -1:.4f}s over "
                           f"{slo_s:.4f}s")
                stall_ms = (timeit.default_timer() - move_t0) * 1e3
                p99_recovered = _slo_phase(slo_remote, offsets)
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=120)
                sat_remote.close()
                slo_remote.close()
                controller.close()
            elapsed = timeit.default_timer() - t0
        queue.shutdown()
        # The decision journal must re-derive the committed placement
        # byte-identically (the crash-recovery contract, checked live).
        # Replayed here, while the tmpdir still holds the journal.
        replay_ok = (state is not None
                     and rb.replay(journal_path) == state)
    if errors:
        raise errors[0]

    # Exactly-once across the live move: every offset delivered, in
    # order, no gap and no duplicate — offsets are cumulative row
    # counts, so the contiguity check IS the loss/dup check.
    expected = [i * rows_per_frame
                for i in range(2 * (warmup_frames + slo_frames))]
    delivered_rows = len(offsets) * rows_per_frame
    rows_lost = abs(len(expected) - len(offsets)) * rows_per_frame
    exactly_once = offsets == expected
    recovery_x = (round(p99_contended / p99_recovered, 3)
                  if p99_contended and p99_recovered else None)
    result = {
        "rebalance_moves": int(controller.moves_total),
        "rebalance_stall_ms": round(stall_ms, 3),
        "rows_lost": int(rows_lost if exactly_once else
                         max(rows_lost, rows_per_frame)),
        "rebalance_slo_ms": round(slo_s * 1e3, 3),
        "rebalance_slo_rows_per_sec": round(delivered_rows
                                            / max(elapsed, 1e-9), 1),
        "rebalance_sat_rows": int(sat_rows[0]),
        "rebalance_ok": bool(exactly_once and breached and replay_ok
                             and state is not None
                             and controller.moves_total == 1
                             and recovery_x is not None
                             and recovery_x > 1.0),
    }
    if p99_contended is not None:
        result["rebalance_p99_ms_contended"] = round(p99_contended * 1e3,
                                                     3)
    if p99_recovered is not None:
        result["rebalance_p99_ms_recovered"] = round(p99_recovered * 1e3,
                                                     3)
    if recovery_x is not None:
        result["rebalance_p99_recovery_x"] = recovery_x
    return result


def main() -> None:
    if os.environ.get("RSDL_BENCH_CPU"):
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=1")
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    from ray_shuffling_data_loader_tpu import data_generation as datagen
    from ray_shuffling_data_loader_tpu.utils.config import default_num_reducers

    num_rows = int(os.environ.get("RSDL_BENCH_ROWS", 2_000_000))
    num_files = int(os.environ.get("RSDL_BENCH_FILES", 8))
    # 8 timed epochs (every one in the window; compiles are paid by a
    # separate warm-up dataset — see run_ingest's protocol docstring).
    num_epochs = int(os.environ.get("RSDL_BENCH_EPOCHS", 8))
    # 131072-row batches measured fastest under the END-TO-END protocol
    # (round 4 sweep: 131k -> 4.6M rows/s, 262k -> 4.3M, 524k -> 3.8M on
    # the 1-core bench host). Larger batches only "won" under the old
    # excluded-warm-up window, by letting the prefetch queue pre-produce
    # into the untimed epoch — an artifact, not throughput.
    batch_size = int(os.environ.get("RSDL_BENCH_BATCH", 131_072))
    data_dir = os.environ.get("RSDL_BENCH_DATA", "/tmp/rsdl_bench_data")

    marker = os.path.join(data_dir, f".rows_{num_rows}_files_{num_files}")
    if not os.path.exists(marker):
        import shutil
        if os.path.isdir(data_dir):
            shutil.rmtree(data_dir)
        filenames, _ = datagen.generate_data(
            num_rows, num_files, num_row_groups_per_file=4,
            max_row_group_skew=0.0, data_dir=data_dir, seed=0)
        with open(marker, "w") as f:
            f.write("\n".join(filenames))
    with open(marker) as f:
        filenames = f.read().splitlines()

    # The tunneled TPU plugin occasionally fails its FIRST initialization
    # if the chip is momentarily held by a dying process. An in-process
    # retry cannot recover (jax caches the backend set after the first
    # attempt and would silently hand back CPU — a CPU number labeled as
    # a per-chip metric is worse than rc=1), so re-exec the whole process
    # once: a fresh interpreter re-runs the plugin from scratch.
    try:
        device = jax.devices()[0]
    except RuntimeError as e:
        device = None
        print(f"# device init failed: {e}", file=sys.stderr)
    if (not os.environ.get("RSDL_BENCH_CPU")
            and (device is None or device.platform == "cpu")):
        if os.environ.get("RSDL_BENCH_REEXEC"):
            raise RuntimeError(
                "accelerator backend unavailable after re-exec; set "
                "RSDL_BENCH_CPU=1 to benchmark on CPU deliberately")
        print("# accelerator unavailable; re-executing once in 10s",
              file=sys.stderr)
        time.sleep(10)
        os.environ["RSDL_BENCH_REEXEC"] = "1"
        os.execv(sys.executable, [sys.executable] + sys.argv)
    if device is None:
        device = jax.devices()[0]
    print(f"# bench device: {device}", file=sys.stderr)

    # At least 4 reducers (even on small hosts, finer reducer granularity
    # pipelines read/partition/permute against consumption) — but not so
    # many that reducer outputs shrink below ~2 batches: device re-batching
    # moves batch-aligned spans of whole reducer outputs in bulk, and
    # gather threads (not reducer count) now carry many-core parallelism.
    # The cap wins over the floor of 4: a smoke config whose rows fit in a
    # couple of batches gets fewer reducers rather than sub-batch outputs
    # that would silently disable the bulk path being measured.
    # Multi-trainer ingest (reference-scale evidence): one shuffle routing
    # to N per-rank streams, each drained by its own consumer thread.
    num_trainers = max(1, int(os.environ.get("RSDL_BENCH_TRAINERS", 1)))
    # Byte budget + spill tier, so the scale runs exercise the reference's
    # bounded-memory operating point (cluster.yaml object-store sizing).
    max_inflight_bytes = (int(os.environ["RSDL_BENCH_INFLIGHT_BYTES"])
                          if os.environ.get("RSDL_BENCH_INFLIGHT_BYTES")
                          else None)
    spill_dir = os.environ.get("RSDL_BENCH_SPILL_DIR") or None

    reducer_cap = max(1, num_rows // (2 * batch_size))
    num_reducers = int(os.environ.get(
        "RSDL_BENCH_REDUCERS",
        min(max(4, default_num_reducers(num_trainers=num_trainers)),
            reducer_cap)))

    # Deeper prefetch keeps more host->device transfers in flight — on a
    # tunneled/high-latency device link this hides most of the copy time.
    prefetch_size = int(os.environ.get("RSDL_BENCH_PREFETCH", 4))

    # RSDL_BENCH_DEVICE_REBATCH=0 forces the per-batch host path for
    # apples-to-apples comparisons of the bulk-chunk transfer design.
    # Unset, the choice defers to the LIBRARY degradation policy
    # (runtime/policy.py): RSDL_DEVICE_REBATCH=0 — the promoted form of
    # this old bench-only mitigation — now turns the per-batch path on
    # for the library and the bench together.
    from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
    rebatch_env = os.environ.get("RSDL_BENCH_DEVICE_REBATCH", "").strip()
    device_rebatch = rt_policy.resolve("bench", "device_rebatch") \
        if rebatch_env == "" \
        else rebatch_env not in ("0", "false", "False")

    # Optional per-batch train-step emulation in the ingest phases (the
    # train phase uses the real model instead).
    step_ms = float(os.environ.get("RSDL_BENCH_STEP_MS", 0))

    phases = [p.strip() for p in os.environ.get(
        "RSDL_BENCH_PHASES",
        "cached,cold,train,scaling,serve,latency,remote,stream,tenancy,"
        "elastic,rebalance"
        ).split(",")
        if p.strip()]
    if os.environ.get("RSDL_BENCH_COLD"):
        # Legacy knob: the cold regime IS the headline; skip cached.
        phases = [p for p in phases if p != "cached"]
        if "cold" not in phases:
            phases.insert(0, "cold")

    from ray_shuffling_data_loader_tpu import executor as rsdl_ex
    from ray_shuffling_data_loader_tpu import stats as rsdl_stats
    from ray_shuffling_data_loader_tpu.runtime import health as rt_health
    from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
    from ray_shuffling_data_loader_tpu.runtime import profiler as rt_profiler
    from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_tel
    from ray_shuffling_data_loader_tpu.runtime import trace as rt_trace
    from ray_shuffling_data_loader_tpu.utils.tracing import maybe_profile

    # Telemetry spine: the whole invocation is flight-recorded (SIGUSR1
    # dumps the ring + named-thread stacks at any point; SIGUSR2 captures
    # a full incident capsule on demand), and the exposition exporter
    # comes up when RSDL_METRICS_FILE / RSDL_METRICS_PORT are set so
    # `tools/rsdl_top.py` can watch live.
    rt_tel.install_signal_dump()
    rt_health.install_incident_signal()
    rt_metrics.maybe_start_shard_writer()
    # Per-round flight capsule (runtime/regress.py), captured after the
    # last phase. No trace dir is pinned here: arming RSDL_TRACE_DIR for
    # the whole run makes every pool worker write an exit dump and
    # routes mid-run incident dumps to disk — measurable perturbation of
    # the serve/remote legs on the 1-core host. The capture itself pins
    # a scratch dir only for the duration of the dump (see
    # _capture_round_capsule). RSDL_BENCH_CAPSULE=0 skips capture,
    # restoring the pre-capsule bench byte for byte.
    bench_capsule = rt_policy.resolve("bench", "bench_capsule")
    if (rt_policy.resolve("metrics", "metrics_file")
            or rt_policy.resolve("metrics", "metrics_port")):
        rt_metrics.start_exporter()
    telemetry_per_event_s = rt_tel.measure_record_overhead()
    events_before = rt_tel.recorder().total_recorded

    # Watchdog/stall totals are monotonic process counters; the JSON
    # reports this invocation's delta.
    wd_before = rsdl_stats.watchdog_stats().snapshot()
    chaos_rate = _install_chaos(_chaos_rate_from_invocation())
    fs_before = rsdl_stats.fault_stats().snapshot()
    recovery_before = rsdl_stats.process_recovery_totals()

    cached = cold = train = train_agg = scaling = serve = latency = None
    remote = stream = tenancy = elastic = rebalance = None

    def _phase(name, fn):
        """Run one phase; a failed phase is reported and OMITTED from the
        JSON instead of killing the whole artifact (the headline fallback
        below already handles missing phases). If every phase fails, the
        no-phase exit path fires."""
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - the artifact must survive
            print(f"# {name} phase FAILED: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return None

    def _ingest(qname, *, cold, epochs):
        if num_trainers > 1:
            return run_ingest_multi(
                jax, filenames, num_epochs=epochs, batch_size=batch_size,
                num_reducers=num_reducers, prefetch_size=prefetch_size,
                cold=cold, device_rebatch=device_rebatch, step_ms=step_ms,
                qname=qname, num_trainers=num_trainers,
                max_inflight_bytes=max_inflight_bytes, spill_dir=spill_dir)
        # Same budget/spill plumbing as the multi-trainer path: the JSON
        # record claims these knobs were engaged whenever they are set,
        # so the single-trainer phases must actually apply them too.
        return run_ingest(
            jax, filenames, num_epochs=epochs, batch_size=batch_size,
            num_reducers=num_reducers, prefetch_size=prefetch_size,
            cold=cold, device_rebatch=device_rebatch, step_ms=step_ms,
            qname=qname, max_inflight_bytes=max_inflight_bytes,
            spill_dir=spill_dir)

    # Ops plane (runtime/health.py): SLO detectors armed fresh per timed
    # phase — the phases have deliberately different rate regimes, so a
    # droop baseline must never span a phase boundary. The ingest phases
    # run a near-zero-work consumer whose stall is ~100% of wall BY
    # CONSTRUCTION, so stall_breach arms only under train (the phase the
    # <=10% contract governs). A detector fire auto-captures an incident
    # capsule, lands in the record's `health` section, and — with
    # --baseline — fails the invocation like any other regression.
    health_by_phase = {}

    # delivery_latency_breach / freshness_stall are deliberately NOT
    # armed here: the ingest/train phases pre-produce whole epochs
    # (max_concurrent_epochs=2), so tables dwell in the queue for tens
    # of seconds BY DESIGN — birth->delivered there measures buffer
    # depth, not serving health. The delivery SLOs are judged where
    # they mean something (the serving plane); the bench's latency leg
    # reports the p99s and --baseline gates them.
    def _armed_phase(name, fn, with_stall=False):
        detectors = [d for d in ("throughput_droop", "stall_breach",
                                 "ledger_creep", "queue_saturation",
                                 "lease_churn", "straggler_drift")
                     if with_stall or d != "stall_breach"]
        rt_health.arm(component="bench", detectors=detectors)
        try:
            return _phase(name, fn)
        finally:
            finished = rt_health.disarm()
            if finished is not None:
                finished.wait_captures(timeout_s=15.0)
                health_by_phase[name] = finished.summary()
                if finished.total_fires:
                    print(f"# health: {finished.total_fires} detector "
                          f"fire(s) during {name} "
                          f"({sorted(d for d, s in finished.summary()['detectors'].items() if s['fires'])})",
                          file=sys.stderr)

    # Host-side sampling profiler next to the JAX device profiler: one
    # window, two views (RSDL_PROFILER=1 / RSDL_PROFILE_FOLDED=<path>).
    with maybe_profile(), rt_profiler.maybe_sample() as sampling_prof:
        if "cached" in phases:
            cached = _armed_phase("cached", lambda: _ingest(
                "bench-cached", cold=False, epochs=num_epochs))
            if cached is not None:
                print(f"# cached: {cached['rows_per_s']:,.0f} rows/s, stall "
                      f"{cached['stall_pct']:.2f}% over {cached['batches']} "
                      "batches", file=sys.stderr)
        if "cold" in phases:
            # 6 epochs: enough steady-state mmap epochs that the one-time
            # in-window decode+IPC-write doesn't dominate the average.
            cold_epochs = int(os.environ.get("RSDL_BENCH_COLD_EPOCHS",
                                             min(6, num_epochs)))
            cold = _armed_phase("cold", lambda: _ingest(
                "bench-cold", cold=True, epochs=cold_epochs))
            if cold is not None:
                print(f"# cold: {cold['rows_per_s']:,.0f} rows/s, stall "
                      f"{cold['stall_pct']:.2f}% over {cold['batches']} "
                      "batches", file=sys.stderr)
        if "scaling" in phases:
            scaling = _phase("worker-scaling", lambda: _run_worker_scaling(
                filenames, num_reducers=num_reducers))
            if scaling is not None:
                print("# worker scaling: "
                      + ", ".join(f"{w}w -> {r:,.0f} rows/s" for w, r in
                                  scaling["rows_per_s_by_workers"].items())
                      + (f" (efficiency "
                         f"{scaling['parallel_efficiency']:.2f})"
                         if "parallel_efficiency" in scaling else ""),
                      file=sys.stderr)
        if "serve" in phases:
            serve = _phase("serve", lambda: _run_serve_leg(filenames))
            if serve is not None:
                print(f"# serve: {serve['serve_rows_per_sec']:,.0f} rows/s "
                      f"aggregate over {serve['serve_trainer_streams']} "
                      f"remote streams on {serve['serve_shards']} shards "
                      f"({serve['serve_speedup_vs_single_shard']}x of 1 "
                      f"shard); handle delivery cut wire bytes "
                      f"{serve['serve_handle_wire_reduction_x']}x",
                      file=sys.stderr)
        if "remote" in phases:
            remote = _phase("remote", lambda: _run_remote_leg(
                int(os.environ.get("RSDL_BENCH_SEED", "0"))))
            if remote is not None:
                print(f"# remote: "
                      f"{remote['remote_rows_per_sec']:,.0f} rows/s "
                      f"prefetch-on vs "
                      f"{remote['remote_prefetch_off_rows_per_sec']:,.0f} "
                      f"off ({remote['remote_prefetch_speedup_x']}x); "
                      f"hot hit {remote['remote_cache_hit_rate_hot']:.0%} "
                      f"disk hit {remote['remote_cache_hit_rate_disk']:.0%}"
                      f"; prefetch efficiency "
                      f"{remote['remote_prefetch_efficiency']:.0%}; "
                      f"bit_identical="
                      f"{remote['remote_output_bit_identical']}",
                      file=sys.stderr)
        if "latency" in phases:
            latency = _phase("latency", lambda: _run_latency_leg(filenames))
            if latency is not None:
                print(f"# latency: delivery p99 "
                      f"{latency['delivery_p99_ms']}ms (handle) / "
                      f"{latency['delivery_p99_ms_stream']}ms (stream) "
                      f"over {latency['delivery_frames']} frames on "
                      f"{latency['latency_shards']} shards; freshness "
                      f"p99 {latency.get('freshness_p99_ms', 'n/a')}ms",
                      file=sys.stderr)
        if "stream" in phases:
            stream = _phase("stream", lambda: _run_stream_leg(
                int(os.environ.get("RSDL_BENCH_SEED", "0"))))
            if stream is not None:
                print(f"# stream: "
                      f"{stream['stream_rows_per_sec']:,.0f} rows/s "
                      f"end-to-end over {stream['stream_windows']} "
                      f"windows ({stream['stream_events']} events); "
                      f"watermark lag p99 "
                      f"{stream['watermark_lag_p99_s']}s; window close "
                      f"{stream['window_close_ms']}ms; late "
                      f"{stream['late_events']}; freshness p99 "
                      f"{stream.get('stream_freshness_p99_ms', 'n/a')}ms",
                      file=sys.stderr)
        if "tenancy" in phases:
            tenancy = _phase("tenancy", lambda: _run_tenancy_leg(
                filenames, int(os.environ.get("RSDL_BENCH_SEED", "0"))))
            if tenancy is not None:
                print(f"# tenancy: fairness "
                      f"{tenancy['tenancy_fairness_ratio']}x at "
                      f"{tenancy['tenancy_weight_ratio']}x weights; hot "
                      f"{tenancy['tenancy_hot_rows_per_sec']:,.0f} rows/s "
                      f"vs cold "
                      f"{tenancy['tenancy_cold_rows_per_sec']:,.0f}; hot "
                      f"p99 "
                      f"{tenancy.get('tenancy_hot_p99_ms_contended', 'n/a')}"
                      f"ms contended vs "
                      f"{tenancy.get('tenancy_hot_p99_ms_solo', 'n/a')}ms "
                      f"solo "
                      f"({tenancy.get('tenancy_latency_ratio_x', 'n/a')}x)"
                      f"; admitted {tenancy['tenancy_admitted']} rejected "
                      f"{tenancy['tenancy_rejected']}; "
                      f"ok={tenancy['tenancy_ok']}", file=sys.stderr)
        if "elastic" in phases:
            elastic = _phase("elastic", lambda: _run_elastic_leg(
                int(os.environ.get("RSDL_BENCH_SEED", "0"))))
            if elastic is not None:
                print(f"# elastic: down detected in "
                      f"{elastic.get('member_down_detect_ms', 'n/a')}ms; "
                      f"resize stall {elastic['resize_stall_ms']}ms "
                      f"({elastic['elastic_recomputed']} reducers "
                      f"recomputed on survivors); world "
                      f"4 -> {elastic['elastic_shrunk_to']} -> "
                      f"{elastic['elastic_grew_to']}; rows lost "
                      f"{elastic['rows_lost']}; "
                      f"ok={elastic['elastic_ok']}", file=sys.stderr)
        if "rebalance" in phases:
            rebalance = _phase("rebalance", lambda: _run_rebalance_leg(
                int(os.environ.get("RSDL_BENCH_SEED", "0"))))
            if rebalance is not None:
                print(f"# rebalance: slo p99 "
                      f"{rebalance.get('rebalance_p99_ms_contended', 'n/a')}"
                      f"ms contended -> "
                      f"{rebalance.get('rebalance_p99_ms_recovered', 'n/a')}"
                      f"ms after the live move "
                      f"({rebalance.get('rebalance_p99_recovery_x', 'n/a')}x"
                      f" recovery); {rebalance['rebalance_moves']} move(s),"
                      f" stall {rebalance['rebalance_stall_ms']}ms; rows "
                      f"lost {rebalance['rows_lost']}; "
                      f"ok={rebalance['rebalance_ok']}", file=sys.stderr)
        if "train" in phases:
            train_epochs = int(os.environ.get("RSDL_BENCH_TRAIN_EPOCHS", 4))
            train_batch = int(os.environ.get("RSDL_BENCH_TRAIN_BATCH",
                                             131_072))
            model_size = os.environ.get(
                "RSDL_BENCH_TRAIN_MODEL",
                "tiny" if os.environ.get("RSDL_BENCH_CPU") else "mlperf")
            train_mb = int(os.environ.get("RSDL_BENCH_TRAIN_MICROBATCH",
                                          2048))
            # Median-of-N for the CONTRACT phase (default 3 on real
            # accelerators; 1 on the CPU smoke path, where wall-clock per
            # run dominates CI budgets and there is no shared-host chip).
            n_runs = max(1, int(os.environ.get(
                "RSDL_BENCH_RUNS",
                "1" if os.environ.get("RSDL_BENCH_CPU") else "3")))
            train_runs = []

            def _run_train_phase():
                for run_i in range(n_runs):
                    r = _phase(f"train[{run_i}]",
                               lambda run_i=run_i: run_train(
                                   jax, filenames, num_epochs=train_epochs,
                                   batch_size=train_batch,
                                   num_reducers=num_reducers,
                                   prefetch_size=prefetch_size,
                                   device_rebatch=device_rebatch,
                                   model_size=model_size,
                                   microbatch=train_mb,
                                   qname=f"bench-train-r{run_i}"))
                    if r is not None:
                        train_runs.append(r)
                return train_runs or None

            # One armed window across the median-of-N runs: the stall
            # contract phase judges stall_breach too.
            _armed_phase("train", _run_train_phase, with_stall=True)
            train_agg = None
            if train_runs:
                train_agg = _aggregate_train_runs(train_runs)
                train = train_runs[train_agg.pop("median_run_index")]
            if train is not None:
                loss_txt = (f"{train['final_loss']:.4f}"
                            if train["final_loss"] is not None
                            else ("DIVERGED" if train.get("diverged")
                                  else "n/a"))
                congestion_txt = ""
                if train_agg is not None and train_agg["runs"] > 1:
                    congestion_txt = (
                        f" [median of {train_agg['runs']} runs"
                        + (f", {train_agg['congested_runs']} CONGESTED"
                           if train_agg["congested"] else "")
                        + "]")
                print(f"# train: {train['rows_per_s']:,.0f} rows/s over "
                      f"{train['batches']} real DLRM micro-steps "
                      f"({train['microbatch']} rows, "
                      f"{train['step_ms_mean']:.2f}ms each), stall "
                      f"{train['stall_pct']:.2f}% "
                      f"(contract: <=10%), loss={loss_txt}"
                      f"{congestion_txt}",
                      file=sys.stderr)

    # The pandas baseline is a LOADER rate; it only makes sense against an
    # ingest phase. A train-only run (contract metric alone) skips it — a
    # compute-gated-over-decode-bound ratio would mean nothing.
    baseline_files = filenames[:max(1, len(filenames) // 4)]
    baseline_rows_per_s = None
    if cached is not None or cold is not None:
        # Best of two runs: the first warms the page cache, and taking the
        # max is fairest to the reference on a noisy shared host. Failure
        # here must not destroy the already-measured phases: the ratio is
        # then omitted (vs_baseline: null), not the artifact.
        baseline_rows_per_s = _phase(
            "pandas-baseline",
            lambda: max(_pandas_reference_baseline(
                baseline_files, num_reducers=max(2, num_reducers // 4),
                batch_size=batch_size) for _ in range(2)))
        if baseline_rows_per_s is not None:
            print(f"# pandas reference algo: "
                  f"{baseline_rows_per_s:,.0f} rows/s", file=sys.stderr)

    if cached is not None:
        headline, metric = cached, "shuffle_ingest_rows_per_sec_per_chip"
    elif cold is not None:
        headline = cold
        metric = "shuffle_ingest_rows_per_sec_per_chip_cold"
    elif train is not None:
        # Train-only run: the headline is the train-gated rate (the train
        # phase runs with the cache ON, so the cold metric name would lie).
        headline, metric = train, "train_gated_rows_per_sec_per_chip"
    elif serve is not None:
        # Serve-only run (RSDL_BENCH_PHASES=serve): the headline is the
        # serving plane's aggregate remote-stream rate; the ingest-phase
        # stall/fill fields do not exist here and report as zero.
        headline = {"rows_per_s": serve["serve_rows_per_sec"],
                    "stall_pct": 0.0, "stall_s": 0.0,
                    "wait_mean_ms": 0.0, "timed_epochs": 1,
                    "duration_s": 0.0}
        metric = "serve_rows_per_sec_aggregate"
    elif latency is not None:
        # Latency-only run (RSDL_BENCH_PHASES=latency): the headline is
        # the end-to-end delivery p99 itself (note the unit: ms, and
        # LOWER is better — the bench-diff `value` rule judges the
        # metric-specific key `delivery_p99_ms` instead).
        headline = {"rows_per_s": latency["delivery_p99_ms"],
                    "stall_pct": 0.0, "stall_s": 0.0,
                    "wait_mean_ms": 0.0, "timed_epochs": 1,
                    "duration_s": 0.0}
        metric = "delivery_p99_ms"
    elif remote is not None:
        # Remote-only run (RSDL_BENCH_PHASES=remote): the headline is
        # the prefetch-on cold-ingest rate against the simulated object
        # store (the storage plane's tentpole number).
        headline = {"rows_per_s": remote["remote_rows_per_sec"],
                    "stall_pct": 0.0, "stall_s": 0.0,
                    "wait_mean_ms": 0.0, "timed_epochs":
                        remote["remote_epochs"],
                    "duration_s": 0.0}
        metric = "remote_cold_rows_per_sec"
    elif stream is not None:
        # Stream-only run (RSDL_BENCH_PHASES=stream): the headline is
        # the windowed end-to-end rate — assemble -> shuffle -> serve ->
        # device — over the synthetic stream (streaming/).
        headline = {"rows_per_s": stream["stream_rows_per_sec"],
                    "stall_pct": 0.0, "stall_s": 0.0,
                    "wait_mean_ms": 0.0,
                    "timed_epochs": stream["stream_windows"],
                    "duration_s": stream["stream_duration_s"]}
        metric = "stream_rows_per_sec"
    elif tenancy is not None:
        # Tenancy-only run (RSDL_BENCH_PHASES=tenancy): the headline is
        # the hot tenant's contended drain rate — the number the QoS
        # plane exists to protect under a cold co-tenant's pressure.
        headline = {"rows_per_s": tenancy["tenancy_hot_rows_per_sec"],
                    "stall_pct": 0.0, "stall_s": 0.0,
                    "wait_mean_ms": 0.0, "timed_epochs": 1,
                    "duration_s": 0.0}
        metric = "tenancy_hot_rows_per_sec"
    elif elastic is not None:
        # Elastic-only run (RSDL_BENCH_PHASES=elastic): the headline is
        # the delivered-row rate of the shrink+grow run — the rate the
        # elastic plane sustains while paying the resize tax.
        headline = {"rows_per_s": elastic["elastic_rows_per_sec"],
                    "stall_pct": 0.0, "stall_s": 0.0,
                    "wait_mean_ms": 0.0, "timed_epochs": 2,
                    "duration_s": 0.0}
        metric = "elastic_rows_per_sec"
    elif rebalance is not None:
        # Rebalance-only run (RSDL_BENCH_PHASES=rebalance): the headline
        # is the SLO tenant's delivered rate across the live move — the
        # stream the self-healing plane exists to keep whole.
        headline = {"rows_per_s": rebalance["rebalance_slo_rows_per_sec"],
                    "stall_pct": 0.0, "stall_s": 0.0,
                    "wait_mean_ms": 0.0, "timed_epochs": 1,
                    "duration_s": 0.0}
        metric = "rebalance_slo_rows_per_sec"
    else:
        print(f"no phase produced a result (selected: {phases!r}; a "
              "'# <name> phase FAILED' line above means the phase ran "
              "and died; otherwise the selection matched nothing)",
              file=sys.stderr)
        sys.exit(2)
    headline_cold = headline is cold
    # vs_baseline is the HONEST ratio: the cold pipeline (decode every
    # epoch) against the pandas reference algorithm, which also pays full
    # decode. The cached ratio is reported separately.
    if baseline_rows_per_s is None:
        vs_baseline = None
    elif cold is not None:
        vs_baseline = cold["rows_per_s"] / baseline_rows_per_s
    else:
        vs_baseline = headline["rows_per_s"] / baseline_rows_per_s

    record = {
        "metric": metric,
        "value": round(headline["rows_per_s"], 1),
        "unit": "ms" if metric == "delivery_p99_ms" else "rows/s",
        "vs_baseline": (round(vs_baseline, 3)
                        if vs_baseline is not None else None),
        # Headline-phase stall stats (near-zero consumer: stall% ~= 100%
        # is expected there; the contract number is the train phase's).
        "stall_pct": round(headline["stall_pct"], 3),
        # The ingest phases run a deliberately near-zero-work consumer, so
        # nearly all wall time is batch-wait BY CONSTRUCTION — stall_pct
        # there measures producer throughput, not a pipeline failure. The
        # <=10% contract applies only to stall_pct_under_train.
        "producer_bound": headline is not train,
        "stall_s": round(headline["stall_s"], 3),
        "batch_wait_mean_ms": round(headline["wait_mean_ms"], 3),
        "step_ms": step_ms,
        "cache_mode": "cold" if headline_cold else "cached",
        # Fairness note: the pandas baseline is a rate over a quarter of
        # the files (it is single-process and O(minutes) on the full set).
        "baseline_files_fraction": round(len(baseline_files) /
                                         len(filenames), 3),
        # Hardware context: the shuffle is host-CPU work, so rows/s scales
        # with cores; cross-round comparisons need this. (Round-1's 17.2M
        # was a many-core host; a 1-core host sustains ~4M.)
        "host_cpus": os.cpu_count(),
        "timed_epochs": headline["timed_epochs"],
        # Launch-to-first-delivery latency of the headline phase (outside
        # the timed window for cached/train, inside it for cold).
        "fill_s": round(headline.get("fill_s", 0.0), 3),
    }
    # Executor honesty (ISSUE 7 satellite): report the EFFECTIVE data
    # plane — backend, pool width, worker pids — and normalize per-core
    # by the pool width that actually ran, not os.cpu_count() (the old
    # field claimed full-host normalization even when the pool was 1
    # worker wide, or when the process pool ran fewer workers than
    # cores).
    pool_info = rsdl_ex.last_worker_pool()
    executor_workers = pool_info["workers"] or (os.cpu_count() or 1)
    record["executor_backend"] = pool_info["backend"] or "thread"
    record["executor_workers"] = executor_workers
    record["executor_worker_pids"] = pool_info["pids"]
    record["rows_per_s_per_core"] = round(
        headline["rows_per_s"] / max(1, executor_workers), 1)
    if scaling is not None:
        # Worker-count scaling leg (1 -> N): near-linear scaling must be
        # an artifact in the record, not a claim in prose.
        record["worker_scaling"] = scaling
    if latency is not None:
        # Delivery-latency leg (runtime/latency.py): flat keys so the
        # bench-diff gate reads delivery_p99_ms / freshness_p99_ms like
        # any other metric — the observability prerequisite ROADMAP
        # items 2 and 5 consume ("bounded p99 delivery latency").
        record.update(latency)
    if serve is not None:
        # Serving-plane leg (multiqueue_service v3): flat keys so the
        # bench-diff gate and the trial CSV read them like any other
        # metric — shard-scaling ratio, per-layer wire bytes
        # (handle vs stream on the SAME table flow), and the
        # compression ratio. A serve_rows_per_sec drop fails --baseline
        # like any other regression.
        record.update(serve)
    if remote is not None:
        # Storage-plane cold leg (storage/): flat keys so the bench-diff
        # gate reads remote_rows_per_sec / remote_prefetch_speedup_x
        # like any other metric — the prefetch-on-beats-off contract is
        # an artifact in the record, not a claim in prose.
        record.update(remote)
    if stream is not None:
        # Streaming leg (streaming/): flat keys so the bench-diff gate
        # reads stream_rows_per_sec / watermark_lag_p99_s /
        # window_close_ms like any other metric — the rules skip
        # cleanly against pre-streaming baselines that lack them.
        record.update(stream)
    if tenancy is not None:
        # Tenancy contention leg (tenancy/): flat keys so the bench-diff
        # gate reads tenancy_fairness_ratio / tenancy_latency_ratio_x
        # like any other metric — the weighted-fair split and the hot
        # tenant's contended-over-solo p99 are artifacts in the record,
        # not claims in prose.
        record.update(tenancy)
    if elastic is not None:
        # Elastic-membership leg (membership/): flat keys so the
        # bench-diff gate reads member_down_detect_ms / resize_stall_ms
        # / rows_lost / elastic_ok like any other metric — the rules
        # skip cleanly against pre-elastic baselines that lack them.
        record.update(elastic)
    if rebalance is not None:
        # Self-healing serving-plane leg (rebalance/): flat keys so the
        # bench-diff gate reads rebalance_p99_recovery_x /
        # rebalance_stall_ms / rebalance_ok like any other metric — the
        # rules skip cleanly against pre-rebalance baselines. rows_lost
        # is shared with the elastic leg under one ceiling-0 rule:
        # max-merge so neither leg can launder the other's loss.
        if "rows_lost" in record:
            rebalance = dict(rebalance, rows_lost=max(
                record["rows_lost"], rebalance["rows_lost"]))
        record.update(rebalance)
    # Runtime-health evidence (runtime/watchdog.py): deadline misses on
    # the supervised bulk transfer/carve path, escalations (a stall
    # persisting past further deadline multiples), and whether the
    # automatic per-batch fallback engaged during this invocation.
    wd_after = rsdl_stats.watchdog_stats().snapshot()
    record["watchdog_events"] = (wd_after["watchdog_events"]
                                 - wd_before["watchdog_events"])
    record["stall_escalations"] = (wd_after["stall_escalations"]
                                   - wd_before["stall_escalations"])
    record["fallback_engaged"] = (wd_after["fallbacks_engaged"]
                                  > wd_before["fallbacks_engaged"])
    # Fault/recovery evidence (runtime/faults.py + runtime/retry.py):
    # this invocation's delta of the process fault counters. Reported
    # whenever anything fired (an env chaos spec counts), always under
    # --chaos soak.
    fs_after = rsdl_stats.fault_stats().snapshot()
    fs_delta = {key: fs_after[key] - fs_before[key] for key in
                ("injected", "retries", "recomputes", "quarantines",
                 "exhausted")}
    if chaos_rate is not None or any(fs_delta.values()):
        record["faults_injected"] = fs_delta["injected"]
        record["fault_retries"] = fs_delta["retries"]
        record["fault_recomputes"] = fs_delta["recomputes"]
        record["fault_quarantines"] = fs_delta["quarantines"]
        record["fault_recoveries_exhausted"] = fs_delta["exhausted"]
    if chaos_rate is not None:
        record["chaos_rate"] = chaos_rate
    # Process-level crash soak (PR 5): under --chaos, a real kill -9 of
    # the queue-server subprocess plus wire chaos and a lease expiry —
    # the record carries the recovery evidence the acceptance gate reads.
    process_soak = None
    if chaos_rate is not None:
        process_soak = _phase("process-recovery-soak",
                              lambda: _run_process_recovery_soak(
                                  int(os.environ.get("RSDL_CHAOS_SEED",
                                                     "0"))))
        recovery_after = rsdl_stats.process_recovery_totals()
        record["replayed_frames"] = (
            recovery_after["queue_frames_replayed"]
            - recovery_before["queue_frames_replayed"])
        record["server_restarts"] = (
            recovery_after["queue_server_restarts"]
            - recovery_before["queue_server_restarts"])
        record["lease_expiries"] = (
            recovery_after["queue_lease_expiries"]
            - recovery_before["queue_lease_expiries"])
        record["process_soak_ok"] = bool(process_soak
                                         and process_soak.get("ok"))
        if process_soak:
            print(f"# process soak: stream bit-identical={process_soak['ok']}"
                  f" server_restarts={process_soak['server_restarts']}"
                  f" lease_drained={process_soak.get('lease_drained')}",
                  file=sys.stderr)
    # Speculation evidence (plan/scheduler.py): always report the plan
    # scheduler's process-wide race/steal totals; under --chaos, run the
    # injected-straggler leg and fold its p99 on-vs-off verdict in.
    from ray_shuffling_data_loader_tpu.plan import (
        scheduler as plan_scheduler)
    record["speculation"] = {
        "enabled": bool(rt_policy.resolve("plan", "plan_speculation")),
        "stealing": bool(rt_policy.resolve("plan", "plan_stealing")),
        **plan_scheduler.speculation_totals(),
    }
    speculation_leg = None
    if chaos_rate is not None:
        speculation_leg = _phase(
            "speculation-straggler-leg",
            lambda: _run_speculation_leg(
                int(os.environ.get("RSDL_CHAOS_SEED", "0")) + 17))
        if speculation_leg:
            record["speculation"]["straggler_leg"] = speculation_leg
            # The leg's races land in the process-wide totals; refresh
            # so the block's counters cover the whole invocation.
            record["speculation"].update(
                plan_scheduler.speculation_totals())
            print("# speculation leg: p99 "
                  f"{speculation_leg['p99_epoch_s_speculation_off']}s off"
                  f" -> {speculation_leg['p99_epoch_s_speculation_on']}s"
                  f" on ({speculation_leg['p99_improvement_pct']}%),"
                  f" won={speculation_leg['speculative_won']}"
                  f" bit_identical="
                  f"{speculation_leg['output_bit_identical']}",
                  file=sys.stderr)
    # Telemetry-spine evidence (runtime/telemetry.py): the bottleneck
    # verdict and per-stage latency decomposition are computed from
    # flight-recorder events — not from log scraping — plus the
    # recorder's own measured share of the timed window.
    verdict = rt_tel.attribution().run_summary() or {}
    record["bottleneck_stage"] = verdict.get("bottleneck_stage")
    record["telemetry_stall_pct"] = verdict.get("stall_pct")
    record["stage_latency_ms"] = {
        stage: {q: s[q] for q in ("p50_ms", "p95_ms", "p99_ms")}
        for stage, s in verdict.get("stages", {}).items()}
    events_delta = rt_tel.recorder().total_recorded - events_before
    timed_s = sum(p["duration_s"] for p in (cached, cold, train) if p)
    record["telemetry_events"] = events_delta
    record["telemetry_enabled"] = rt_tel.enabled()
    record["telemetry_overhead_pct"] = (
        round(100.0 * events_delta * telemetry_per_event_s / timed_s, 4)
        if timed_s else 0.0)
    # The RSDL_TELEMETRY=0 hard-off fast path, priced at THIS run's
    # event volume: the proof the off switch costs ~nothing (with
    # telemetry off, events_delta itself is ~0 and both fields pin to 0).
    record["telemetry_overhead_off_pct"] = (
        round(100.0 * events_delta * rt_tel.measure_disabled_overhead()
              / timed_s, 6) if timed_s else 0.0)
    # Causal critical-path attribution over the recorder's retained
    # events (runtime/trace.py): which stages/tasks the epochs actually
    # waited on, and what a 2x speedup of each would buy.
    record.update(rt_trace.bench_fields(rt_tel.recorder().events()))
    # Ops-plane evidence (runtime/health.py): per-phase detector
    # episodes. `fires` > 0 means an SLO detector saw a sustained breach
    # DURING a timed phase — with --baseline this fails the invocation
    # (below); the auto-captured capsules name the evidence either way.
    health_fires = sum(s.get("fires", 0) for s in health_by_phase.values())
    record["health"] = {
        "armed": bool(health_by_phase),
        "fires": health_fires,
        "by_phase": health_by_phase,
        "capsules": [c for s in health_by_phase.values()
                     for c in s.get("capsules", [])],
    }
    if sampling_prof is not None:
        record["profile"] = sampling_prof.summary()
    if chaos_rate is not None or any(fs_delta.values()):
        # Chaos <-> telemetry correlation: a fault event (kind = the
        # fault-site name) is JOINABLE when a non-fault telemetry event
        # shares its (kind, epoch, task) key.
        events = rt_tel.recorder().events()
        plain_keys = {(e["kind"], e.get("epoch"), e.get("task"))
                      for e in events if "fault" not in e}
        fault_events = [e for e in events if e.get("fault")]
        record["fault_events"] = len(fault_events)
        record["fault_events_joinable"] = sum(
            1 for e in fault_events
            if (e["kind"], e.get("epoch"), e.get("task")) in plain_keys)
    if cold is not None:
        # "disk": parquet decoded ONCE inside the timed window, later
        # epochs stream from mmap'd Arrow IPC scratch (fresh dir per
        # run — nothing pre-warmed). "none": re-decode every epoch. A
        # single-epoch run maps each file once, so resolve_file_cache
        # engages no tier — report what actually ran, not the env mode.
        record["cold_cache"] = (_cold_cache_mode() or "none"
                                if cold["timed_epochs"] > 1 else "none")
    if num_trainers > 1:
        record["num_trainers"] = num_trainers
        # Multi-trainer phases clock launch-to-done (see run_ingest_multi).
        record["clock"] = headline.get("clock", "first-delivery")
    if max_inflight_bytes:
        record["max_inflight_bytes"] = max_inflight_bytes
        record["spill"] = bool(spill_dir)
    if cached is not None:
        # Mirror the vs_baseline handling: a failed (fail-soft) baseline
        # phase leaves baseline_rows_per_s None — omit the ratio, never
        # destroy the already-measured phases with a TypeError.
        record["vs_baseline_cached"] = (
            round(cached["rows_per_s"] / baseline_rows_per_s, 3)
            if baseline_rows_per_s is not None else None)
    if cold is not None and not headline_cold:
        record.update({
            "cold_rows_per_sec": round(cold["rows_per_s"], 1),
            "cold_stall_pct": round(cold["stall_pct"], 3),
            "cold_producer_bound": True,
            "cold_timed_epochs": cold["timed_epochs"],
            "cold_fill_s": round(cold.get("fill_s", 0.0), 3),
        })
    if train is not None:
        record.update({
            # The BASELINE.md contract metric: <= 10% stall under a real
            # train step (>= 90% input-pipeline utilization).
            "stall_pct_under_train": round(train["stall_pct"], 3),
            "train_rows_per_sec": round(train["rows_per_s"], 1),
            "train_step_ms_mean": round(train["step_ms_mean"], 3),
            "train_batch_size": train["batch_size"],
            "train_microbatch": train["microbatch"],
            "train_steps": train["batches"],
            "train_stall_s": round(train["stall_s"], 3),
            "train_dev_util_pct": round(train["dev_util_pct"], 3),
            "train_mfu_pct": (round(train["mfu_pct"], 4)
                              if train["mfu_pct"] is not None else None),
            "train_mfu_basis": train.get("mfu_basis"),
            "train_mfu_null_reason": train.get("mfu_null_reason"),
            "train_compute_rows_per_sec": (
                round(train["compute_rows_per_s"], 1)
                if train.get("compute_rows_per_s") else None),
            "train_flops_per_row": train["flops_per_row"],
            "train_wait_mean_ms": round(train["wait_mean_ms"], 3),
            "train_fill_s": round(train.get("fill_s", 0.0), 3),
            "train_final_loss": (round(train["final_loss"], 5)
                                 if train["final_loss"] is not None
                                 else None),
            "train_diverged": bool(train.get("diverged", False)),
            "train_model": f"dlrm-{train['model_size']}",
        })
        if train_agg is not None:
            # Median-of-N contract fields + congestion marker: the
            # per-run train_* fields above already come from the MEDIAN
            # run; these expose the spread and flag noisy-host episodes.
            record.update(train_agg)

    # Measurement honesty: what code ran on what machine, so two
    # records can be judged comparable BEFORE their deltas are believed
    # (rsdl_regress / rsdl_bench_diff cross-check these).
    record["provenance"] = _bench_provenance()
    if bench_capsule:
        _capture_round_capsule(record)

    print(json.dumps(record))

    if chaos_rate is not None:
        # The soak contract: injected faults are RECOVERED, not survived
        # by luck — every selected phase must still complete, and the
        # process-level soak's stream must come back bit-identical.
        missing = [name for name, result in
                   (("cached", cached), ("cold", cold), ("train", train))
                   if name in phases and result is None]
        if missing:
            print(f"# chaos soak FAILED: phase(s) {missing} did not "
                  f"complete under fault rate {chaos_rate}",
                  file=sys.stderr)
            sys.exit(1)
        if not (process_soak and process_soak.get("ok")):
            print("# chaos soak FAILED: process-recovery soak did not "
                  "recover a bit-identical stream", file=sys.stderr)
            sys.exit(1)
        if not (speculation_leg and speculation_leg.get("ok")):
            print("# chaos soak FAILED: speculation straggler leg did "
                  "not improve p99 with a bit-identical stream "
                  f"({speculation_leg})", file=sys.stderr)
            sys.exit(1)
        print(f"# chaos soak OK: {fs_delta['injected']} injected, "
              f"{fs_delta['recomputes']} recomputed, "
              f"{fs_delta['exhausted']} exhausted, "
              f"{record['server_restarts']} server restarts, "
              f"{record['replayed_frames']} frames replayed, "
              f"{record['lease_expiries']} lease expiries",
              file=sys.stderr)

    # Regression gate (--baseline <BENCH_rN.json>): compare THIS record
    # against the chosen committed baseline; a threshold breach fails
    # the invocation so an r03->r05-class throughput drop is loud.
    baseline_path = _baseline_from_invocation()
    if baseline_path:
        diff_mod = _load_bench_diff()
        findings = diff_mod.compare_records(
            diff_mod.load_record(baseline_path), record)
        for line in diff_mod.render_findings(findings):
            print(f"# bench-diff: {line}", file=sys.stderr)
        regressions = [f for f in findings if not f["ok"]]
        if regressions:
            print(f"# bench-diff FAILED vs {baseline_path}: "
                  f"{len(regressions)} metric(s) regressed",
                  file=sys.stderr)
            sys.exit(1)
        # The gate extends to live health: a sustained SLO breach during
        # a timed phase is a regression even when the aggregate numbers
        # survive (a droop the window averaged away, a leak still
        # climbing at exit). The capsules in record["health"] are the
        # forensic record of exactly what fired.
        if health_fires:
            print(f"# health gate FAILED vs {baseline_path}: "
                  f"{health_fires} detector fire(s) during timed phases "
                  f"(capsules: {record['health']['capsules']})",
                  file=sys.stderr)
            sys.exit(1)
        print(f"# bench-diff OK vs {baseline_path} (health: 0 fires)",
              file=sys.stderr)


if __name__ == "__main__":
    main()
