"""Headline benchmark: rows/sec/chip ingested through the full pipeline.

Measures the BASELINE.md primary metric: rows per second streamed from
shuffled Parquet through the map/reduce shuffle, re-batching, Arrow->NumPy
conversion, and ``jax.device_put`` onto the accelerator (a tiny jitted
reduction per batch forces materialization on device, so transfers are not
imaginary). This is the loader path a real trainer consumes
(reference harness analog: benchmarks/benchmark.py + the batch-wait metric
of examples/horovod/ray_torch_shuffle.py:186-218).

``vs_baseline`` compares against the reference's algorithm run the way the
reference runs it per core — pandas ``read_parquet``, boolean-mask
partitioning, ``pd.concat`` + ``sample(frac=1)``, sequential single process
(reference: shuffle.py:199-247) — measured on the same data and host in the
same run.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env knobs: RSDL_BENCH_ROWS, RSDL_BENCH_FILES, RSDL_BENCH_EPOCHS,
RSDL_BENCH_BATCH, RSDL_BENCH_PREFETCH (batches in flight, default 4),
RSDL_BENCH_CPU=1 (force CPU backend for smoke runs),
RSDL_BENCH_COLD=1 (disable the file-table cache so every epoch re-reads +
re-decodes Parquet — the reference's 64 GB operating regime, where the
corpus does not fit memory), RSDL_BENCH_DATA (data cache dir),
RSDL_BENCH_DEVICE_REBATCH=0/1 (force the per-batch host path / the bulk
device-rebatch path; default auto), RSDL_BENCH_STEP_MS (emulated per-batch
train-step time for the stall%-under-load regime), RSDL_BENCH_REDUCERS
(override the reducer count).
"""

from __future__ import annotations

import json
import os
import sys
import time
import timeit


def _pandas_reference_baseline(filenames, num_reducers: int,
                               batch_size: int) -> float:
    """rows/s of the reference's shuffle algorithm, single process."""
    import numpy as np
    import pandas as pd

    start = timeit.default_timer()
    total_rows = 0
    # Map stage: read + uniform partition via boolean masks.
    reducer_parts = [[] for _ in range(num_reducers)]
    for filename in filenames:
        rows = pd.read_parquet(filename)
        total_rows += len(rows)
        assignment = np.random.randint(num_reducers, size=len(rows))
        for r in range(num_reducers):
            reducer_parts[r].append(rows[assignment == r])
    # Reduce stage: concat + permute.
    shuffled = [pd.concat(parts).sample(frac=1) for parts in reducer_parts]
    # Consume: exact-size re-batching with leftover carry.
    buffer = None
    for df in shuffled:
        buffer = df if buffer is None else pd.concat([buffer, df])
        while len(buffer) >= batch_size:
            batch = buffer[:batch_size]
            _ = batch.to_numpy(copy=False)
            buffer = buffer[batch_size:]
    duration = timeit.default_timer() - start
    return total_rows / duration


def main() -> None:
    if os.environ.get("RSDL_BENCH_CPU"):
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=1")
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    import jax.numpy as jnp
    import numpy as np

    from ray_shuffling_data_loader_tpu import data_generation as datagen
    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
    from ray_shuffling_data_loader_tpu.utils.config import default_num_reducers

    num_rows = int(os.environ.get("RSDL_BENCH_ROWS", 2_000_000))
    num_files = int(os.environ.get("RSDL_BENCH_FILES", 8))
    # 8 epochs, first excluded as warm-up. The warm-up epoch's long compile
    # lets the pipeline legitimately pre-shuffle + pre-transfer up to
    # ~2 epochs of runway (max_concurrent_epochs + prefetch depth); with
    # only a few timed epochs that shading inflates the rate, so the timed
    # window is 7 epochs — long enough that steady-state shuffle work
    # dominates what it measures.
    num_epochs = int(os.environ.get("RSDL_BENCH_EPOCHS", 8))
    # 131072-row batches measured fastest on-chip (round 3 sweep: 65k ->
    # 17.8M rows/s, 131k -> 23.1M, 262k -> 20.7M): fewer per-batch tunnel
    # dispatches without outgrowing the transfer pipeline.
    batch_size = int(os.environ.get("RSDL_BENCH_BATCH", 131_072))
    data_dir = os.environ.get("RSDL_BENCH_DATA", "/tmp/rsdl_bench_data")

    marker = os.path.join(data_dir, f".rows_{num_rows}_files_{num_files}")
    if not os.path.exists(marker):
        import glob
        import shutil
        if os.path.isdir(data_dir):
            shutil.rmtree(data_dir)
        filenames, _ = datagen.generate_data(
            num_rows, num_files, num_row_groups_per_file=4,
            max_row_group_skew=0.0, data_dir=data_dir, seed=0)
        with open(marker, "w") as f:
            f.write("\n".join(filenames))
    with open(marker) as f:
        filenames = f.read().splitlines()

    # The tunneled TPU plugin occasionally fails its FIRST initialization
    # if the chip is momentarily held by a dying process. An in-process
    # retry cannot recover (jax caches the backend set after the first
    # attempt and would silently hand back CPU — a CPU number labeled as
    # a per-chip metric is worse than rc=1), so re-exec the whole process
    # once: a fresh interpreter re-runs the plugin from scratch.
    try:
        device = jax.devices()[0]
    except RuntimeError as e:
        device = None
        print(f"# device init failed: {e}", file=sys.stderr)
    if (not os.environ.get("RSDL_BENCH_CPU")
            and (device is None or device.platform == "cpu")):
        if os.environ.get("RSDL_BENCH_REEXEC"):
            raise RuntimeError(
                "accelerator backend unavailable after re-exec; set "
                "RSDL_BENCH_CPU=1 to benchmark on CPU deliberately")
        print("# accelerator unavailable; re-executing once in 10s",
              file=sys.stderr)
        time.sleep(10)
        os.environ["RSDL_BENCH_REEXEC"] = "1"
        os.execv(sys.executable, [sys.executable] + sys.argv)
    if device is None:
        device = jax.devices()[0]
    print(f"# bench device: {device}", file=sys.stderr)

    # At least 4 reducers (even on small hosts, finer reducer granularity
    # pipelines read/partition/permute against consumption) — but not so
    # many that reducer outputs shrink below ~2 batches: device re-batching
    # moves batch-aligned spans of whole reducer outputs in bulk, and
    # gather threads (not reducer count) now carry many-core parallelism.
    # The cap wins over the floor of 4: a smoke config whose rows fit in a
    # couple of batches gets fewer reducers rather than sub-batch outputs
    # that would silently disable the bulk path being measured.
    reducer_cap = max(1, num_rows // (2 * batch_size))
    num_reducers = int(os.environ.get(
        "RSDL_BENCH_REDUCERS",
        min(max(4, default_num_reducers(num_trainers=1)), reducer_cap)))

    # Narrowest dtype per column that covers its cardinality, cast at the
    # map stage: every downstream byte — partition, permute-gather,
    # re-batch, host->HBM DMA — is 43B/row instead of 76B. Indices widen
    # for free on device (workloads/dlrm_criteo.py).
    from ray_shuffling_data_loader_tpu.workloads.dlrm_criteo import dlrm_spec

    # Deeper prefetch keeps more host->device transfers in flight — on a
    # tunneled/high-latency device link this hides most of the copy time.
    prefetch_size = int(os.environ.get("RSDL_BENCH_PREFETCH", 4))

    # Cold mode: no file-table cache, so the timed epochs pay Parquet read
    # + decode every epoch (the regime of the reference's 64 GB runs,
    # reference: benchmarks/benchmark_batch.sh:9-18). Default (cached) mode
    # measures the steady state where the working set fits host memory.
    cold = bool(os.environ.get("RSDL_BENCH_COLD"))

    # RSDL_BENCH_DEVICE_REBATCH=0 forces the per-batch host path for
    # apples-to-apples comparisons of the bulk-chunk transfer design.
    rebatch_env = os.environ.get("RSDL_BENCH_DEVICE_REBATCH", "").strip()
    device_rebatch = "auto" if rebatch_env == "" \
        else rebatch_env not in ("0", "false", "False")
    ds = JaxShufflingDataset(
        filenames, num_epochs=num_epochs, num_trainers=1,
        batch_size=batch_size, rank=0,
        num_reducers=num_reducers, max_concurrent_epochs=2, seed=0,
        queue_name="bench-queue", drop_last=True,
        prefetch_size=prefetch_size,
        file_cache=None if cold else "auto",
        device_rebatch=device_rebatch, **dlrm_spec())

    # Tiny jitted reduction per batch: forces the batch to land on device;
    # negligible compute (sparse-feature columns arrive as one pytree
    # transfer and are consumed per-column, the DLRM access pattern).
    touch = jax.jit(
        lambda fs, y: sum(f.sum(dtype=jnp.int32) for f in fs)
        + y.sum(dtype=jnp.float32))

    # Warm-up epoch 0 separately to exclude one-time compile cost (with a
    # single epoch there is no warm-up and compile time is included).
    # RSDL_PROFILE_DIR=/tmp/tr captures a JAX profiler trace of the run.
    from ray_shuffling_data_loader_tpu.utils.tracing import maybe_profile
    # Optional per-batch train-step emulation: BASELINE's >=90%-utilization
    # contract is about a TRAINER's stall fraction, and with a near-zero
    # consumer the pipeline is producer-bound by construction (stall% ~=
    # 100% minus nothing). RSDL_BENCH_STEP_MS sleeps per batch to measure
    # stall% at a realistic step time; rows/s is then gated by the step.
    step_ms = float(os.environ.get("RSDL_BENCH_STEP_MS", 0))

    rows_consumed = 0
    start = timeit.default_timer()
    last = None
    with maybe_profile():
        for epoch in range(num_epochs):
            ds.set_epoch(epoch)
            for features, label in ds:
                last = touch(features, label)
                if step_ms:
                    time.sleep(step_ms / 1e3)
                if epoch > 0 or num_epochs == 1:
                    rows_consumed += label.shape[0]
            if epoch == 0 and num_epochs > 1:
                jax.block_until_ready(last)
                # Exclude warm-up/compile waits from the stall metric: the
                # contract number (BASELINE.md: >=90% input-pipeline
                # utilization) is about steady state, not first-compile.
                ds.batch_wait_stats.reset()
                start = timeit.default_timer()
        jax.block_until_ready(last)
    duration = max(timeit.default_timer() - start, 1e-9)
    ds.close()
    pipeline_rows_per_s = rows_consumed / duration
    wait = ds.batch_wait_stats.summary()
    stall_s = wait["total"]
    stall_pct = 100.0 * stall_s / duration

    # Best of two runs: the first warms the page cache, and taking the max
    # is fairest to the reference on a noisy shared host.
    baseline_files = filenames[:max(1, len(filenames) // 4)]
    baseline_rows_per_s = max(
        _pandas_reference_baseline(baseline_files,
                                   num_reducers=max(2, num_reducers // 4),
                                   batch_size=batch_size)
        for _ in range(2))
    print(f"# pipeline: {pipeline_rows_per_s:,.0f} rows/s | "
          f"pandas reference algo: {baseline_rows_per_s:,.0f} rows/s | "
          f"stall {stall_s:.3f}s ({stall_pct:.2f}%) over "
          f"{wait['count']} batches | mode: "
          f"{'cold (decode every epoch)' if cold else 'cached'}",
          file=sys.stderr)

    print(json.dumps({
        "metric": ("shuffle_ingest_rows_per_sec_per_chip_cold" if cold
                   else "shuffle_ingest_rows_per_sec_per_chip"),
        "value": round(pipeline_rows_per_s, 1),
        "unit": "rows/s",
        "vs_baseline": round(pipeline_rows_per_s / baseline_rows_per_s, 3),
        # Contract metric (BASELINE.md): consumer time spent waiting on the
        # input pipeline, warm-up excluded. With step_ms=0 (default) the
        # consumer does ~no work, so stall% ~= 100% is expected and rows/s
        # is the signal; set RSDL_BENCH_STEP_MS to a realistic train-step
        # time to measure the >=90%-utilization regime (<=10% stall).
        "stall_pct": round(stall_pct, 3),
        "stall_s": round(stall_s, 3),
        "batch_wait_mean_ms": round(wait["mean"] * 1e3, 3),
        "step_ms": step_ms,
        "cache_mode": "cold" if cold else "cached",
        # Fairness note: the pandas baseline is a rate over a quarter of
        # the files (it is single-process and O(minutes) on the full set).
        "baseline_files_fraction": round(len(baseline_files) /
                                         len(filenames), 3),
        # Hardware context: the shuffle is host-CPU work, so rows/s scales
        # with cores; cross-round comparisons need this. (Round-1's 17.2M
        # was a many-core host; a 1-core host sustains ~4M.)
        "host_cpus": os.cpu_count(),
        "timed_epochs": num_epochs - 1 if num_epochs > 1 else 1,
    }))


if __name__ == "__main__":
    main()
