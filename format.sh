#!/usr/bin/env bash
# Format/lint gate (reference: format.sh — yapf 0.23.0 + flake8 3.7.7 over
# changed files). Uses yapf/flake8 when installed; always runs a bytecode
# compile check so the gate works on TPU-VM images without lint tools.
set -euo pipefail
cd "$(dirname "$0")"

PY_DIRS=(ray_shuffling_data_loader_tpu tests benchmarks examples)

echo "-- compile check"
python -m compileall -q "${PY_DIRS[@]}" bench.py __graft_entry__.py setup.py

if python -c 'import yapf' 2>/dev/null; then
    echo "-- yapf (diff mode)"
    python -m yapf --style .style.yapf --recursive --diff "${PY_DIRS[@]}"
else
    echo "-- yapf not installed, skipping"
fi

if python -c 'import flake8' 2>/dev/null; then
    echo "-- flake8"
    python -m flake8 "${PY_DIRS[@]}"
else
    echo "-- flake8 not installed, skipping"
fi

# Project-invariant analyzer (analysis/ is stdlib-only, but importing it
# goes through the package __init__, which needs numpy/pyarrow — skip
# gracefully on images without them, same pattern as yapf/flake8 above).
if python -c 'import ray_shuffling_data_loader_tpu.analysis' 2>/dev/null; then
    # --concurrency adds the whole-program pass (interprocedural
    # locksets, lock-order cycle detection). When the archived runtime
    # order graph is present, the run also cross-checks it against the
    # static graph: dynamic acquisition edges the static pass missed
    # are findings, static cycles confirmed at runtime hard-fail.
    if [ -f .rsdl-locksan-graph.json ]; then
        echo "-- rsdl-lint (concurrency + locksan cross-check)"
        python -m ray_shuffling_data_loader_tpu.analysis --concurrency \
            --locksan-graph .rsdl-locksan-graph.json \
            "${PY_DIRS[@]}" bench.py __graft_entry__.py tools
    else
        echo "-- rsdl-lint (concurrency)"
        python -m ray_shuffling_data_loader_tpu.analysis --concurrency \
            "${PY_DIRS[@]}" bench.py __graft_entry__.py tools
    fi
else
    echo "-- rsdl-lint deps not importable, skipping"
fi

# Locksan archival run (RSDL_LOCKSAN_SUITE=1): replay tier-1 with every
# package lock wrapped in the runtime sanitizer and rewrite the
# committed .rsdl-locksan-graph.json artifact that the lint gate above
# cross-checks. Off by default — it costs a full suite run; flip it on
# after changing lock structure in the threaded modules so the archived
# graph's construction-site keys stay in sync with the source.
if [ "${RSDL_LOCKSAN_SUITE:-0}" = "1" ]; then
    echo "-- locksan suite run (rewriting .rsdl-locksan-graph.json)"
    RSDL_LOCKSAN=1 RSDL_LOCKSAN_OUT=.rsdl-locksan-graph.json \
        python -m pytest tests/ -q -m 'not slow' \
        -p no:cacheprovider >/dev/null
    python -m ray_shuffling_data_loader_tpu.analysis --concurrency \
        --locksan-graph .rsdl-locksan-graph.json \
        "${PY_DIRS[@]}" bench.py __graft_entry__.py tools
fi

# Epoch-plan IR self-test (tools/rsdl_plan.py, stdlib-only): builds a
# demo plan, round-trips it through JSON byte-stably, and proves the
# validator rejects a corrupt lineage key — so a schema drift in
# plan/ir.py surfaces here before any consumer trips over it.
echo "-- rsdl-plan (check mode)"
python tools/rsdl_plan.py --check >/dev/null

# Stage microbenchmarks (tools/rsdl_microbench.py): per-kernel numbers
# (parquet decode, partition plan, fused gather, shm IPC handoff) in
# informational mode, so a kernel-level regression surfaces before the
# next full bench round. Always rc 0; the hard gate stays bench.py
# --baseline. RSDL_MICROBENCH=0 skips it (costs a few seconds).
if [ "${RSDL_MICROBENCH:-1}" != "0" ]; then
    if python -c 'import pyarrow, numpy' 2>/dev/null; then
        echo "-- rsdl-microbench (check mode)"
        python tools/rsdl_microbench.py --check >/dev/null
    else
        echo "-- rsdl-microbench deps not importable, skipping"
    fi
fi

# Delivery-latency sketch self-test (tools/rsdl_top.py, stdlib-only):
# observes disjoint values in two registries, merges them through the
# shard-federation path, and requires the merged quantiles to equal a
# directly-merged sketch's — a schema drift in the sketch exposition
# (series suffix, centroid label, merge math) fails here, not in a
# silently-wrong p99 on a dashboard.
echo "-- rsdl-top (check-latency mode)"
python tools/rsdl_top.py --check-latency >/dev/null

# Bench regression check (tools/rsdl_bench_diff.py, stdlib-only): when
# committed bench records are present, compare the two newest and print
# the per-metric verdict. Check mode is informational (rc 0) — the hard
# gates are `bench.py --baseline <record>` at measurement time and the
# two-file CLI form in CI.
if ls BENCH_r*.json >/dev/null 2>&1; then
    echo "-- rsdl-bench-diff (check mode)"
    python tools/rsdl_bench_diff.py --check .
fi

# Regression-forensics self-test (tools/rsdl_regress.py, stdlib-only):
# synthesizes a two-round pair with a planted suspect (one stage 3x
# slower, its latency histogram shifted, one env knob appeared) and
# requires the differential engine to rank the plant #1 — alignment,
# bucket-overlap significance, or suspect-scoring drift fails here,
# not in a forensic report that quietly blames the wrong stage.
echo "-- rsdl-regress (check mode)"
python tools/rsdl_regress.py --check >/dev/null

# Run-report schema smoke (tools/rsdl_report.py, stdlib-only): validates
# that the committed bench records (and any history/capsule artifacts
# handed to it) still parse against the report's schema without writing
# HTML. Informational (rc 0), same contract as the checks above.
echo "-- rsdl-report (check mode)"
python tools/rsdl_report.py --check

echo "OK"
