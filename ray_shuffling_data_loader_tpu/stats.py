"""Stats / observability subsystem.

Capability parity with the reference's stats pipeline (reference:
stats.py:22-60 dataclass model, :68-200 epoch collector, :202-253 trial
collector, :255-574 CSV report writers, :580-648 humanizers + object-store
sampler), re-based on threads instead of Ray actors: collectors are
thread-safe in-process objects (the shuffle's map/reduce/consume tasks are
host threads here, so a lock replaces the actor mailbox).

Report schema: the trial CSV and epoch CSV column sets reproduce the
reference's exactly (reference: stats.py:305-355,468-505) so downstream
tooling reads either; the trial CSV additionally APPENDS the
watchdog/stall columns (``watchdog_events``, ``stall_escalations``,
``fallbacks_engaged``), the fault/recovery columns
(``faults_injected``, ``fault_retries``, ``fault_recomputes``,
``fault_quarantines``, ``fault_recoveries_exhausted``) and the
telemetry bottleneck columns (``bottleneck_stage``,
``telemetry_stall_pct``, per-stage ``p95_<stage>_ms`` — computed by
runtime/telemetry.py from flight-recorder events) — process totals at
write time — which position-indexed reference tooling never sees.
Memory utilization sampling replaces the raylet gRPC store probe
(reference: stats.py:598-632) with host RSS + native buffer-pool bytes
+ optional TPU HBM via ``device.memory_stats()``.

The watchdog/fault recorders below no longer own private integer
counters: their counts ARE typed counters in the runtime metrics
registry (runtime/metrics.py), so the Prometheus exposition, the bench
JSON, and these snapshot dicts read the same cells — ``snapshot()`` is
now a *reader* of the registry, kept for its stable dict schema.
"""

from __future__ import annotations

import csv
import datetime
import os
import threading
import time
import timeit
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils import fileio
from ray_shuffling_data_loader_tpu.utils.humanize import (
    human_readable_big_num, human_readable_size)
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)


# ---------------------------------------------------------------------------
# Stats model (reference: stats.py:22-60)
# ---------------------------------------------------------------------------


@dataclass
class StageStats:
    task_durations: List[float]
    stage_duration: float


@dataclass
class MapStats(StageStats):
    read_durations: List[float]


@dataclass
class ReduceStats(StageStats):
    pass


@dataclass
class ConsumeStats(StageStats):
    consume_times: List[float]


@dataclass
class ThrottleStats:
    wait_duration: float


@dataclass
class EpochStats:
    duration: float
    map_stats: MapStats
    reduce_stats: ReduceStats
    consume_stats: ConsumeStats
    throttle_stats: ThrottleStats


@dataclass
class TrialStats:
    epoch_stats: List[EpochStats]
    duration: float


@dataclass
class MemorySample:
    """One utilization sample (replaces the raylet object-store sample)."""
    timestamp: float
    rss_bytes: int
    pool_bytes: int
    hbm_bytes: int = 0
    # Reclaimable bytes the pool's free list holds for reuse — real RSS,
    # but not in-flight data.
    pool_cached_bytes: int = 0

    @property
    def object_store_bytes_used(self) -> int:
        """Reference-compatible accessor (reference: stats.py:266)."""
        return self.pool_bytes if self.pool_bytes else self.rss_bytes


# ---------------------------------------------------------------------------
# Collectors (reference: stats.py:68-253, actors -> thread-safe objects)
# ---------------------------------------------------------------------------


class EpochStatsCollector:
    """Per-epoch stage-span collector with first-start/last-done edge
    detection (reference: stats.py:68-200)."""

    def __init__(self, num_maps: int, num_reduces: int, num_consumes: int):
        self._num_maps = num_maps
        self._num_reduces = num_reduces
        self._num_consumes = num_consumes
        self._lock = threading.Lock()
        self._epoch_start_time: Optional[float] = None
        self._duration: Optional[float] = None
        self._maps_started = 0
        self._maps_done = 0
        self._map_durations: List[float] = []
        self._read_durations: List[float] = []
        self._reduces_started = 0
        self._reduces_done = 0
        self._reduce_durations: List[float] = []
        self._consumes_started = 0
        self._consumes_done = 0
        self._consume_durations: List[float] = []
        self._consume_times: List[float] = []
        self._throttle_duration = 0.0
        self._stage_start: Dict[str, Optional[float]] = {
            "map": None, "reduce": None, "consume": None}
        self._stage_duration: Dict[str, Optional[float]] = {
            "map": None, "reduce": None, "consume": None}
        self._done_event = threading.Event()
        if num_reduces == 0:
            # A host owning zero reducers (more hosts than reducers in the
            # distributed plan) has nothing to wait for: its epochs are
            # born complete, otherwise get_stats would block forever.
            self._duration = 0.0
            self._done_event.set()

    def epoch_start(self) -> None:
        with self._lock:
            self._epoch_start_time = timeit.default_timer()

    def map_start(self) -> None:
        self._stage_task_start("map")

    def map_done(self, duration: float, read_duration: float) -> None:
        with self._lock:
            self._maps_done += 1
            self._map_durations.append(duration)
            self._read_durations.append(read_duration)
            # ">=": a retried task (Executor task_retries) may re-record a
            # completion; the last-done edge extends to the latest one.
            if self._maps_done >= self._num_maps:
                self._stage_done_locked("map")

    def reduce_start(self) -> None:
        self._stage_task_start("reduce")

    def reduce_done(self, duration: float) -> None:
        with self._lock:
            self._reduces_done += 1
            self._reduce_durations.append(duration)
            if self._reduces_done >= self._num_reduces:
                self._stage_done_locked("reduce")
                # Epoch "shuffle done" edge = last reduce done
                # (reference: stats.py:152-156); a retried reduce extends it.
                assert self._epoch_start_time is not None
                self._duration = (timeit.default_timer()
                                  - self._epoch_start_time)
                self._done_event.set()

    def consume_start(self) -> None:
        self._stage_task_start("consume")

    def consume_done(self, duration: float,
                     trial_time_to_consume: float) -> None:
        with self._lock:
            self._consumes_done += 1
            self._consume_durations.append(duration)
            self._consume_times.append(trial_time_to_consume)
            if self._consumes_done >= self._num_consumes:
                self._stage_done_locked("consume")

    def throttle_done(self, duration: float) -> None:
        with self._lock:
            self._throttle_duration += duration

    def _stage_task_start(self, stage: str) -> None:
        with self._lock:
            counter = {"map": "_maps_started", "reduce": "_reduces_started",
                       "consume": "_consumes_started"}[stage]
            if getattr(self, counter) == 0:
                self._stage_start[stage] = timeit.default_timer()
            setattr(self, counter, getattr(self, counter) + 1)

    def _stage_done_locked(self, stage: str) -> None:
        start = self._stage_start[stage]
        assert start is not None, f"{stage} stage never started"
        self._stage_duration[stage] = timeit.default_timer() - start

    def wait_until_done(self, timeout: Optional[float] = None) -> bool:
        return self._done_event.wait(timeout)

    def get_stats(self) -> EpochStats:
        with self._lock:
            # ">=": task retries may record extra completions.
            assert self._maps_done >= self._num_maps, (
                f"epoch incomplete: {self._maps_done}/{self._num_maps} maps")
            assert self._reduces_done >= self._num_reduces, (
                f"epoch incomplete: {self._reduces_done}/{self._num_reduces}"
                " reduces")
            return EpochStats(
                duration=self._duration or 0.0,
                map_stats=MapStats(list(self._map_durations),
                                   self._stage_duration["map"] or 0.0,
                                   list(self._read_durations)),
                reduce_stats=ReduceStats(list(self._reduce_durations),
                                         self._stage_duration["reduce"] or 0.0),
                consume_stats=ConsumeStats(list(self._consume_durations),
                                           self._stage_duration["consume"]
                                           or 0.0,
                                           list(self._consume_times)),
                throttle_stats=ThrottleStats(self._throttle_duration))


class TrialStatsCollector:
    """Whole-trial collector: one EpochStatsCollector per epoch plus trial
    wall-clock (reference: stats.py:202-253)."""

    def __init__(self, num_epochs: int, num_maps: int, num_reduces: int,
                 num_consumes: int):
        self._num_epochs = num_epochs
        self._epochs = [
            EpochStatsCollector(num_maps, num_reduces, num_consumes)
            # The caller DECLARED this finite count (stats collection is
            # per-bounded-trial by contract; streaming runs pass no
            # collector), so pre-sizing is the static shape, not an
            # assumption: rsdl-lint: disable=static-epoch-assumption
            for _ in range(num_epochs)
        ]
        self._trial_start_time: Optional[float] = None
        self._trial_duration: Optional[float] = None
        self._lock = threading.Lock()

    def trial_start(self) -> None:
        with self._lock:
            self._trial_start_time = timeit.default_timer()

    @property
    def trial_start_time(self) -> float:
        assert self._trial_start_time is not None
        return self._trial_start_time

    def epoch(self, epoch: int) -> EpochStatsCollector:
        return self._epochs[epoch]

    # Per-task hooks mirroring the reference actor's method surface
    # (reference: shuffle.py:204-263 call sites).
    def epoch_start(self, epoch: int) -> None:
        self._epochs[epoch].epoch_start()

    def map_start(self, epoch: int) -> None:
        self._epochs[epoch].map_start()

    def map_done(self, epoch: int, duration: float,
                 read_duration: float) -> None:
        self._epochs[epoch].map_done(duration, read_duration)

    def reduce_start(self, epoch: int) -> None:
        self._epochs[epoch].reduce_start()

    def reduce_done(self, epoch: int, duration: float) -> None:
        self._epochs[epoch].reduce_done(duration)

    def consume_start(self, epoch: int) -> None:
        self._epochs[epoch].consume_start()

    def consume_done(self, epoch: int, duration: float,
                     trial_time_to_consume: float) -> None:
        self._epochs[epoch].consume_done(duration, trial_time_to_consume)

    def throttle_done(self, epoch: int, duration: float) -> None:
        self._epochs[epoch].throttle_done(duration)

    def trial_done(self) -> None:
        with self._lock:
            assert self._trial_start_time is not None
            self._trial_duration = (timeit.default_timer()
                                    - self._trial_start_time)

    def get_stats(self, timeout: Optional[float] = None) -> TrialStats:
        for collector in self._epochs:
            collector.wait_until_done(timeout)
        with self._lock:
            duration = self._trial_duration
        if duration is None:
            assert self._trial_start_time is not None
            duration = timeit.default_timer() - self._trial_start_time
        return TrialStats(
            epoch_stats=[c.get_stats() for c in self._epochs],
            duration=duration)


# ---------------------------------------------------------------------------
# Batch-wait tracking (the north-star stall metric,
# reference: examples/horovod/ray_torch_shuffle.py:186-218)
# ---------------------------------------------------------------------------


@dataclass
class BatchWaitStats:
    wait_times: List[float] = field(default_factory=list)

    def record(self, wait_s: float) -> None:
        self.wait_times.append(wait_s)

    def reset(self) -> None:
        """Drop recorded waits (e.g. to exclude warm-up/compile epochs)."""
        self.wait_times.clear()

    def summary(self) -> Dict[str, float]:
        if not self.wait_times:
            return {"mean": 0.0, "std": 0.0, "max": 0.0, "min": 0.0,
                    "total": 0.0, "count": 0}
        arr = np.asarray(self.wait_times)
        return {
            "mean": float(arr.mean()), "std": float(arr.std()),
            "max": float(arr.max()), "min": float(arr.min()),
            "total": float(arr.sum()), "count": int(len(arr)),
        }


# ---------------------------------------------------------------------------
# Watchdog / stall reporting (runtime/watchdog.py files structured reports
# here; the bench harness and the CSV writers read the process totals)
# ---------------------------------------------------------------------------


class WatchdogStats:
    """Process-wide sink for structured stall reports and degradation
    decisions.

    ``runtime.watchdog`` records every deadline miss (escalation 1 = the
    first miss of a watch, 2+ = the stall persisting across further
    deadline multiples); subsystems record each automatic degradation
    (e.g. the bulk-transfer path dropping to per-batch). Totals are
    monotonic — snapshot before/after a run to measure that run's
    events, the same protocol as ``spill.process_spill_totals``.
    """

    _RECENT = 32  # ring of most recent stalls kept for diagnostics

    def __init__(self):
        self._lock = threading.Lock()
        # Counts live in the metrics registry (one set of cells per
        # process — a second WatchdogStats instance shares them); only
        # the recent-stall diagnostic ring is per-instance.
        self._events = rt_metrics.counter(
            "rsdl_watchdog_events_total", "watchdog deadline misses")
        self._escalations = rt_metrics.counter(
            "rsdl_watchdog_escalations_total",
            "stalls persisting past further deadline multiples")
        self._fallbacks = rt_metrics.counter(
            "rsdl_watchdog_fallbacks_total",
            "automatic degradations engaged")
        self._recent: List[Dict[str, Any]] = []

    def record_stall(self, report) -> None:
        """``report`` is a ``runtime.watchdog.StallReport`` (duck-typed:
        name/waited_s/deadline_s/escalation/detail/timestamp)."""
        entry = {
            "name": report.name,
            "waited_s": float(report.waited_s),
            "deadline_s": float(report.deadline_s),
            "escalation": int(report.escalation),
            "detail": report.detail,
            "timestamp": float(report.timestamp),
        }
        self._events.inc()
        if report.escalation > 1:
            self._escalations.inc()
        rt_metrics.counter("rsdl_watchdog_stalls_total",
                           "deadline misses by watch name",
                           name=report.name).inc()
        rt_telemetry.record("watchdog_stall", name=report.name,
                            escalation=int(report.escalation),
                            waited_s=float(report.waited_s),
                            detail=report.detail)
        with self._lock:
            self._recent.append(entry)
            del self._recent[:-self._RECENT]

    def record_fallback(self, component: str, reason: str) -> None:
        self._fallbacks.inc()
        rt_telemetry.record("fallback", component=component, reason=reason)
        with self._lock:
            self._recent.append({
                "name": f"{component}:fallback",
                "detail": reason,
                "timestamp": time.time(),
            })
            del self._recent[:-self._RECENT]

    def snapshot(self) -> Dict[str, Any]:
        by_name: Dict[str, int] = {}
        family = rt_metrics.get("rsdl_watchdog_stalls_total")
        if family is not None and hasattr(family, "children"):
            for labels, metric in family.children().items():
                by_name[dict(labels).get("name", "?")] = int(metric.value)
        with self._lock:
            recent = list(self._recent)
        return {
            "watchdog_events": int(self._events.value),
            "stall_escalations": int(self._escalations.value),
            "fallbacks_engaged": int(self._fallbacks.value),
            "stalls_by_name": by_name,
            "recent_stalls": recent,
        }


_watchdog_stats = WatchdogStats()


def watchdog_stats() -> WatchdogStats:
    """THE process-wide watchdog/stall recorder."""
    return _watchdog_stats


# ---------------------------------------------------------------------------
# Fault / recovery accounting (runtime/faults.py injects, runtime/retry.py
# and the shuffle's lineage recovery record; bench.py and the trial CSV
# read the process totals)
# ---------------------------------------------------------------------------


class FaultStats:
    """Process-wide sink for fault-injection and recovery events.

    Counters are monotonic — snapshot before/after a run to measure that
    run's activity (the ``watchdog_stats``/``process_spill_totals``
    protocol). ``recomputes`` counts tasks re-executed successfully after
    a failure (lineage map recomputes AND in-task reduce/transfer
    re-runs); ``retries`` counts every RetryPolicy backoff taken;
    ``exhausted`` counts recoveries that ran out of attempts (the only
    failures that reach the ``ShuffleFailure`` poison pill).
    """

    _RECENT = 32

    def __init__(self):
        self._lock = threading.Lock()
        # Counts live in the metrics registry (shared per process, like
        # WatchdogStats); the quarantine report ring is per-instance.
        self._injected = rt_metrics.counter(
            "rsdl_faults_injected_total", "chaos faults fired")
        self._retries = rt_metrics.counter(
            "rsdl_fault_retries_total", "RetryPolicy backoffs taken")
        self._recomputes = rt_metrics.counter(
            "rsdl_fault_recomputes_total",
            "tasks re-executed successfully after a failure")
        self._quarantines = rt_metrics.counter(
            "rsdl_fault_quarantines_total",
            "input files dropped by on_bad_file='skip'")
        self._exhausted = rt_metrics.counter(
            "rsdl_fault_exhausted_total",
            "recoveries that ran out of attempts")
        self._recovery_latency = rt_metrics.histogram(
            "rsdl_fault_recovery_seconds", "recompute/recovery latency")
        self._recovery_latency_max = rt_metrics.gauge(
            "rsdl_fault_recovery_max_seconds",
            "largest single recovery latency")
        self._recent_quarantines: List[Dict[str, Any]] = []

    def record_injected(self, site: str, epoch=None, task=None) -> None:
        self._injected.inc()
        rt_metrics.counter("rsdl_faults_injected_by_site_total",
                           "chaos faults fired by site", site=site).inc()

    def record_retry(self, component: str) -> None:
        self._retries.inc()
        rt_telemetry.record("fault_retry", component=component)

    def record_recompute(self, component: str, latency_s: float) -> None:
        self._recomputes.inc()
        self._recovery_latency.observe(latency_s)
        self._recovery_latency_max.max(latency_s)
        rt_telemetry.record("fault_recompute", component=component,
                            latency_s=latency_s)

    def record_quarantine(self, report) -> None:
        """``report`` is a ``runtime.faults.QuarantinedFile`` (duck-typed:
        ``as_dict()``)."""
        self._quarantines.inc()
        rt_telemetry.record("fault_quarantine",
                            epoch=getattr(report, "epoch", None),
                            task=getattr(report, "file_index", None))
        with self._lock:
            self._recent_quarantines.append(report.as_dict())
            del self._recent_quarantines[:-self._RECENT]

    def record_exhausted(self, component: str) -> None:
        self._exhausted.inc()
        rt_telemetry.record("fault_exhausted", component=component)

    def snapshot(self) -> Dict[str, Any]:
        by_site: Dict[str, int] = {}
        family = rt_metrics.get("rsdl_faults_injected_by_site_total")
        if family is not None and hasattr(family, "children"):
            for labels, metric in family.children().items():
                by_site[dict(labels).get("site", "?")] = int(metric.value)
        with self._lock:
            recent = list(self._recent_quarantines)
        return {
            "injected": int(self._injected.value),
            "retries": int(self._retries.value),
            "recomputes": int(self._recomputes.value),
            "quarantines": int(self._quarantines.value),
            "exhausted": int(self._exhausted.value),
            "recovery_latency_total_s": self._recovery_latency.sum,
            "recovery_latency_max_s": self._recovery_latency_max.value,
            "injected_by_site": by_site,
            "recent_quarantines": recent,
        }

    def __getitem__(self, key: str):
        """Mapping-style access to the current totals
        (``fault_stats()["recomputes"]``)."""
        return self.snapshot()[key]


_fault_stats = FaultStats()


def fault_stats() -> FaultStats:
    """THE process-wide fault/recovery recorder."""
    return _fault_stats


# ---------------------------------------------------------------------------
# Memory utilization sampler (reference: stats.py:598-648, raylet gRPC ->
# host/pool/HBM introspection)
# ---------------------------------------------------------------------------


def _read_rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return 0


def get_memory_stats(sample_hbm: bool = False) -> MemorySample:
    """One utilization sample: process RSS, pipeline pool bytes, optional
    HBM. ``pool_bytes`` is the buffer ledger's total — file-cache tables,
    in-flight reducer outputs, and transport recv buffers (the reference's
    plasma store-utilization columns, reference: stats.py:263-270)."""
    from ray_shuffling_data_loader_tpu import native
    ledger = native.buffer_ledger()
    pool_bytes = ledger.bytes_in_use()
    pool_cached = ledger.freelist_bytes()
    hbm = 0
    if sample_hbm:
        try:
            import jax
            for dev in jax.local_devices():
                stats = dev.memory_stats()
                if stats:
                    hbm += stats.get("bytes_in_use", 0)
        except Exception:  # noqa: BLE001 - sampling must never kill a trial
            hbm = 0
    return MemorySample(timestamp=time.time(), rss_bytes=_read_rss_bytes(),
                        pool_bytes=pool_bytes, hbm_bytes=hbm,
                        pool_cached_bytes=pool_cached)


def collect_store_stats(stats_list: List[Tuple[float, MemorySample]],
                        done_event: threading.Event,
                        sample_period_s: float = 5.0,
                        sample_hbm: bool = False) -> None:
    """Sampler loop body: append (timestamp, sample) until done_event is set
    (reference: stats.py:635-648). Run it in a daemon thread."""
    while not done_event.is_set():
        sample = get_memory_stats(sample_hbm=sample_hbm)
        stats_list.append((sample.timestamp, sample))
        done_event.wait(sample_period_s)


def start_store_stats_sampler(
        stats_list: List[Tuple[float, MemorySample]],
        sample_period_s: float = 5.0,
        sample_hbm: bool = False) -> threading.Event:
    """Spawn the sampler thread; returns the event that stops it
    (reference: shuffle.py:32-37 thread wiring)."""
    done = threading.Event()
    thread = threading.Thread(
        target=collect_store_stats,
        args=(stats_list, done, sample_period_s, sample_hbm),
        daemon=True, name="rsdl-store-stats")
    thread.start()
    return done


# ---------------------------------------------------------------------------
# CSV report writers (reference: stats.py:255-574; identical column sets)
# ---------------------------------------------------------------------------


def _spread(prefix: str, values: List[float]) -> Dict[str, float]:
    arr = np.asarray(values) if values else np.asarray([0.0])
    return {
        f"avg_{prefix}": float(arr.mean()),
        f"std_{prefix}": float(arr.std()),
        f"max_{prefix}": float(arr.max()),
        f"min_{prefix}": float(arr.min()),
    }


TRIAL_FIELDNAMES = [
    "num_files", "num_row_groups_per_file", "num_reducers", "num_trainers",
    "num_epochs", "max_concurrent_epochs", "trial", "duration",
    "row_throughput", "batch_throughput", "batch_throughput_per_trainer",
    "avg_object_store_utilization", "max_object_store_utilization",
    "avg_epoch_duration", "std_epoch_duration", "max_epoch_duration",
    "min_epoch_duration",
    "avg_map_stage_duration", "std_map_stage_duration",
    "max_map_stage_duration", "min_map_stage_duration",
    "avg_reduce_stage_duration", "std_reduce_stage_duration",
    "max_reduce_stage_duration", "min_reduce_stage_duration",
    "avg_consume_stage_duration", "std_consume_stage_duration",
    "max_consume_stage_duration", "min_consume_stage_duration",
    "avg_map_task_duration", "std_map_task_duration",
    "max_map_task_duration", "min_map_task_duration",
    "avg_read_duration", "std_read_duration", "max_read_duration",
    "min_read_duration",
    "avg_reduce_task_duration", "std_reduce_task_duration",
    "max_reduce_task_duration", "min_reduce_task_duration",
    "avg_consume_task_duration", "std_consume_task_duration",
    "max_consume_task_duration", "min_consume_task_duration",
    "avg_time_to_consume", "std_time_to_consume", "max_time_to_consume",
    "min_time_to_consume",
    # Appended past the reference's column set (see module docstring).
    "watchdog_events", "stall_escalations", "fallbacks_engaged",
    # Fault/recovery totals (fault_stats(); process totals at write time).
    "faults_injected", "fault_retries", "fault_recomputes",
    "fault_quarantines", "fault_recoveries_exhausted",
    # Telemetry bottleneck verdict (runtime/telemetry.py run summary at
    # write time: stage with the largest work share when the consumer's
    # batch-wait share exceeds the stall threshold, else train_step).
    "bottleneck_stage", "telemetry_stall_pct",
    "p95_map_read_ms", "p95_reduce_ms", "p95_queue_wait_ms",
    "p95_fetch_ms", "p95_convert_ms", "p95_device_transfer_ms",
    "p95_train_step_ms",
    # Process-crash recovery totals (multiqueue_service v2 +
    # runtime/supervisor.py; process totals at write time): frames
    # re-sent from the server replay buffer, supervised queue-server
    # restarts, and consumer-lease expiries.
    "queue_frames_replayed", "queue_server_restarts",
    "queue_lease_expiries",
    # Serving-plane byte honesty (multiqueue_service v3; process totals
    # at write time): actual socket payload bytes vs shm-handle
    # deliveries, the compression win, and the shard count — so a wire
    # regression is attributable to the serving layer from the CSV
    # alone, not inferred from end-to-end rates.
    "queue_bytes_on_wire", "queue_handle_hits", "queue_handle_misses",
    "queue_compression_ratio", "serve_shards",
]


def _counter_total(name: str) -> int:
    """Process-lifetime total of a registry counter (0 if never made)."""
    family = rt_metrics.get(name)
    if family is None:
        return 0
    if hasattr(family, "children"):
        return int(sum(m.value for m in family.children().values()))
    return int(family.value)


def process_recovery_totals() -> Dict[str, int]:
    """Queue-service crash-recovery counters (monotonic; snapshot
    before/after a run — the ``watchdog_stats`` protocol)."""
    return {
        "queue_frames_replayed": _counter_total(
            "rsdl_queue_frames_replayed_total"),
        "queue_server_restarts": _counter_total(
            "rsdl_queue_server_restarts_total"),
        "queue_lease_expiries": _counter_total(
            "rsdl_queue_lease_expiries_total"),
        "queue_frames_nacked": _counter_total(
            "rsdl_queue_frames_nacked_total"),
        "queue_frames_corrupt": _counter_total(
            "rsdl_queue_frames_corrupt_total"),
        "queue_client_reconnects": _counter_total(
            "rsdl_queue_client_reconnects_total"),
    }


def queue_serve_totals() -> Dict[str, Any]:
    """Serving-plane byte/handle accounting (multiqueue_service v3;
    monotonic process totals — snapshot before/after a run to attribute
    a window). ``queue_compression_ratio`` is logical-over-wire for the
    streamed frames compression actually touched (1.0 = off/no win)."""
    payload = _counter_total("rsdl_queue_payload_bytes_total")
    wire = _counter_total("rsdl_queue_bytes_on_wire_total")
    saved = _counter_total("rsdl_queue_compression_saved_bytes_total")
    compressed_wire = None
    ratio = 1.0
    if saved:
        # saved = logical - wire over exactly the compressed frames, so
        # the compressed share of the wire is recoverable from totals
        # only when every streamed byte was compressed; report the
        # conservative whole-stream ratio instead.
        compressed_wire = max(1, wire)
        ratio = (wire + saved) / compressed_wire
    return {
        "queue_payload_bytes": payload,
        "queue_bytes_on_wire": wire,
        "queue_handle_hits": _counter_total(
            "rsdl_queue_handle_hits_total"),
        "queue_handle_misses": _counter_total(
            "rsdl_queue_handle_misses_total"),
        "queue_compression_saved_bytes": saved,
        "queue_compression_ratio": round(ratio, 4),
        "serve_shards": int(_counter_total("rsdl_queue_serve_shards")),
    }

EPOCH_FIELDNAMES = [
    "num_files", "num_row_groups_per_file", "num_reducers", "num_trainers",
    "num_epochs", "max_concurrent_epochs", "trial", "epoch", "duration",
    "row_throughput", "batch_throughput", "batch_throughput_per_trainer",
    "map_stage_duration", "reduce_stage_duration", "consume_stage_duration",
    "avg_map_task_duration", "std_map_task_duration",
    "max_map_task_duration", "min_map_task_duration",
    "avg_read_duration", "std_read_duration", "max_read_duration",
    "min_read_duration",
    "avg_reduce_task_duration", "std_reduce_task_duration",
    "max_reduce_task_duration", "min_reduce_task_duration",
    "avg_consume_task_duration", "std_consume_task_duration",
    "max_consume_task_duration", "min_consume_task_duration",
    "avg_time_to_consume", "std_time_to_consume", "max_time_to_consume",
    "min_time_to_consume",
]


def process_stats(all_stats: List[Tuple[TrialStats, List[Tuple[float, MemorySample]]]],
                  overwrite_stats: bool,
                  stats_dir: str,
                  no_epoch_stats: bool,
                  unique_stats: bool,
                  num_rows: int,
                  num_files: int,
                  num_row_groups_per_file: int,
                  batch_size: int,
                  num_reducers: int,
                  num_trainers: int,
                  num_epochs: int,
                  max_concurrent_epochs: int) -> None:
    """Write trial + epoch CSVs and print the summary
    (reference: stats.py:255-574; same signature, same columns)."""
    fileio.makedirs(stats_dir)
    stats_list = [s for s, _ in all_stats]
    store_stats_list = [ss for _, ss in all_stats]
    times = [s.duration for s in stats_list]
    mean, std = float(np.mean(times)), float(np.std(times))
    all_samples = [sample.object_store_bytes_used
                   for trial_ss in store_stats_list
                   for _, sample in trial_ss]
    num_samples = len(all_samples)
    max_util = human_readable_size(max(all_samples)) if all_samples else "0 B"
    throughput_std = float(np.std(
        [num_epochs * num_rows / t for t in times]))
    batch_tp_std = float(np.std(
        [(num_epochs * num_rows / batch_size) / t for t in times]))
    print(f"\nMean over {len(times)} trials: {mean:.3f}s +- {std}")
    print(f"Mean throughput over {len(times)} trials: "
          f"{num_epochs * num_rows / mean:.2f} rows/s +- {throughput_std:.2f}")
    print(f"Mean batch throughput over {len(times)} trials: "
          f"{(num_epochs * num_rows / batch_size) / mean:.2f} batches/s +- "
          f"{batch_tp_std:.2f}")
    print(f"Max memory utilization over {num_samples} samples: {max_util}\n")

    write_mode = "w+" if overwrite_stats else "a+"
    hr_rows = human_readable_big_num(num_rows)
    hr_batch = human_readable_big_num(batch_size)
    now = datetime.datetime.now(datetime.timezone.utc).isoformat()

    def _open_report(kind: str):
        filename = f"{kind}_stats_{hr_rows}_rows_{hr_batch}_batch_size"
        filename += f"_{now}.csv" if unique_stats else ".csv"
        path = fileio.join(stats_dir, filename)
        header = (overwrite_stats or fileio.file_size(path) == 0)
        return path, header

    static = {
        "num_files": num_files,
        "num_row_groups_per_file": num_row_groups_per_file,
        "num_reducers": num_reducers,
        "num_trainers": num_trainers,
        "num_epochs": num_epochs,
        "max_concurrent_epochs": max_concurrent_epochs,
    }

    wd = watchdog_stats().snapshot()
    fs = fault_stats().snapshot()
    recovery = process_recovery_totals()
    serve = queue_serve_totals()
    verdict = rt_telemetry.attribution().run_summary() or {}
    verdict_stages = verdict.get("stages", {})

    path, header = _open_report("trial")
    logger.info("Writing trial stats to %s", path)
    with fileio.open_text(path, write_mode) as f:
        writer = csv.DictWriter(f, fieldnames=TRIAL_FIELDNAMES)
        if header:
            writer.writeheader()
        for trial, (stats, trial_ss) in enumerate(all_stats):
            row: Dict[str, Any] = dict(static)
            row["trial"] = trial
            row["watchdog_events"] = wd["watchdog_events"]
            row["stall_escalations"] = wd["stall_escalations"]
            row["fallbacks_engaged"] = wd["fallbacks_engaged"]
            row["faults_injected"] = fs["injected"]
            row["fault_retries"] = fs["retries"]
            row["fault_recomputes"] = fs["recomputes"]
            row["fault_quarantines"] = fs["quarantines"]
            row["fault_recoveries_exhausted"] = fs["exhausted"]
            row["bottleneck_stage"] = verdict.get("bottleneck_stage", "")
            row["telemetry_stall_pct"] = verdict.get("stall_pct", 0.0)
            row["queue_frames_replayed"] = recovery["queue_frames_replayed"]
            row["queue_server_restarts"] = recovery["queue_server_restarts"]
            row["queue_lease_expiries"] = recovery["queue_lease_expiries"]
            row["queue_bytes_on_wire"] = serve["queue_bytes_on_wire"]
            row["queue_handle_hits"] = serve["queue_handle_hits"]
            row["queue_handle_misses"] = serve["queue_handle_misses"]
            row["queue_compression_ratio"] = serve[
                "queue_compression_ratio"]
            row["serve_shards"] = serve["serve_shards"]
            for stage in rt_telemetry.STAGES:
                row[f"p95_{stage}_ms"] = verdict_stages.get(
                    stage, {}).get("p95_ms", 0.0)
            row["duration"] = stats.duration
            row_tp = num_epochs * num_rows / stats.duration
            row["row_throughput"] = row_tp
            row["batch_throughput"] = row_tp / batch_size
            row["batch_throughput_per_trainer"] = (
                row_tp / batch_size / num_trainers)
            samples = [s.object_store_bytes_used for _, s in trial_ss]
            row["avg_object_store_utilization"] = (
                float(np.mean(samples)) if samples else 0.0)
            row["max_object_store_utilization"] = (
                float(np.max(samples)) if samples else 0.0)
            row.update(_spread("epoch_duration",
                               [e.duration for e in stats.epoch_stats]))
            row.update(_spread(
                "map_stage_duration",
                [e.map_stats.stage_duration for e in stats.epoch_stats]))
            row.update(_spread(
                "reduce_stage_duration",
                [e.reduce_stats.stage_duration for e in stats.epoch_stats]))
            row.update(_spread(
                "consume_stage_duration",
                [e.consume_stats.stage_duration for e in stats.epoch_stats]))
            row.update(_spread(
                "map_task_duration",
                [d for e in stats.epoch_stats
                 for d in e.map_stats.task_durations]))
            row.update(_spread(
                "read_duration",
                [d for e in stats.epoch_stats
                 for d in e.map_stats.read_durations]))
            row.update(_spread(
                "reduce_task_duration",
                [d for e in stats.epoch_stats
                 for d in e.reduce_stats.task_durations]))
            row.update(_spread(
                "consume_task_duration",
                [d for e in stats.epoch_stats
                 for d in e.consume_stats.task_durations]))
            row.update(_spread(
                "time_to_consume",
                [d for e in stats.epoch_stats
                 for d in e.consume_stats.consume_times]))
            writer.writerow(row)

    if no_epoch_stats:
        return
    path, header = _open_report("epoch")
    logger.info("Writing epoch stats to %s", path)
    with fileio.open_text(path, write_mode) as f:
        writer = csv.DictWriter(f, fieldnames=EPOCH_FIELDNAMES)
        if header:
            writer.writeheader()
        for trial, (stats, _) in enumerate(all_stats):
            for epoch, e in enumerate(stats.epoch_stats):
                row = dict(static)
                row["trial"] = trial
                row["epoch"] = epoch
                row["duration"] = e.duration
                row_tp = num_rows / e.duration if e.duration else 0.0
                row["row_throughput"] = row_tp
                row["batch_throughput"] = row_tp / batch_size
                row["batch_throughput_per_trainer"] = (
                    row_tp / batch_size / num_trainers)
                row["map_stage_duration"] = e.map_stats.stage_duration
                row["reduce_stage_duration"] = e.reduce_stats.stage_duration
                row["consume_stage_duration"] = (
                    e.consume_stats.stage_duration)
                row.update(_spread("map_task_duration",
                                   e.map_stats.task_durations))
                row.update(_spread("read_duration",
                                   e.map_stats.read_durations))
                row.update(_spread("reduce_task_duration",
                                   e.reduce_stats.task_durations))
                row.update(_spread("consume_task_duration",
                                   e.consume_stats.task_durations))
                row.update(_spread("time_to_consume",
                                   e.consume_stats.consume_times))
                writer.writerow(row)
