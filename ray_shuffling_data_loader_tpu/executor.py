"""Futures-based task executor with ``wait(num_returns)`` semantics.

The reference schedules its map/reduce fan-out on Ray's raylet (C++ external
dependency) and throttles with ``ray.wait`` (reference: shuffle.py:126-131,
148-151). On a TPU-VM there is no cluster scheduler between the loader and
the host: map/reduce tasks are CPU work on the local host (pyarrow releases
the GIL for Parquet decode and take), so the idiomatic equivalent is a
thread-pool executor per host plus an explicit ``wait`` that reproduces
``ray.wait``'s contract — return when ``num_returns`` of the given futures
have completed, preserving submission order in the done list.

Multi-host scaling composes above this: each host of a TPU slice runs its own
executor over its shard of the files (SPMD, see parallel/), so no cross-host
task scheduler is needed. Plasma's role — a shared, accounted buffer plane —
is played by Arrow C++ buffers, with pipeline-wide byte accounting in
``native.NativeBufferPool`` (see native/ and stats.py's pool_bytes column).
"""

from __future__ import annotations

import concurrent.futures as cf
import os
import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)


class TaskRef:
    """A handle to an in-flight task's result.

    Plays the role of a Ray ObjectRef for loader code: created by
    :meth:`Executor.submit`, resolved by :func:`get`, waited on by
    :func:`wait`. Holds a strong reference to the result until dropped,
    which is what gives the shuffle's throttle loop its memory-release
    semantics (dropping refs frees buffers, reference: shuffle.py:131-132).
    """

    __slots__ = ("_future",)

    def __init__(self, future: cf.Future):
        self._future = future

    def result(self, timeout: Optional[float] = None) -> Any:
        return self._future.result(timeout)

    def done(self) -> bool:
        return self._future.done()

    def cancel(self) -> bool:
        return self._future.cancel()

    def add_done_callback(self, fn) -> None:
        """Invoke ``fn(future)`` when the task completes (immediately if
        it already has) — the completion-event hook the plan scheduler's
        dependency-ordered dispatch rides on (plan/scheduler.py)."""
        self._future.add_done_callback(fn)


def get(refs, timeout: Optional[float] = None):
    """Resolve a TaskRef or list of TaskRefs to values (ray.get parity)."""
    if isinstance(refs, TaskRef):
        return refs.result(timeout)
    return [r.result(timeout) for r in refs]


def wait(refs: Sequence[TaskRef],
         num_returns: int = 1,
         timeout: Optional[float] = None) -> Tuple[List[TaskRef], List[TaskRef]]:
    """Block until ``num_returns`` of ``refs`` are done (ray.wait parity).

    Returns ``(done, not_done)`` with ``done`` ordered by completion
    readiness scan order (stable w.r.t. input order, like ray.wait).
    If fewer than ``num_returns`` complete before ``timeout``, returns
    whatever is done — the caller must not assume ``len(done) ==
    num_returns`` (the reference's throttle miscounts exactly this way,
    SURVEY.md §7 "known bugs"; we return the true count).
    """
    if num_returns > len(refs):
        raise ValueError(
            f"num_returns={num_returns} exceeds number of refs={len(refs)}")
    if len({id(r) for r in refs}) != len(refs):
        raise ValueError("wait() does not accept duplicate refs")
    import time
    deadline = None if timeout is None else time.monotonic() + timeout
    pending = {r._future: r for r in refs}
    done_refs: List[TaskRef] = []
    satisfied: set = set()
    while num_returns > 0 and len(satisfied) < num_returns:
        # Satisfied futures were dropped from `pending` below, so every
        # wake scans only the still-live futures — a large fan-out's
        # worst case is one pass per completion over the survivors, not
        # O(n^2) rebuilds of the full list.
        budget = (None if deadline is None
                  else max(0.0, deadline - time.monotonic()))
        finished, _ = cf.wait(
            pending.keys(), timeout=budget,
            return_when=cf.ALL_COMPLETED
            if num_returns - len(satisfied) == len(pending)
            else cf.FIRST_COMPLETED)
        satisfied.update(finished)
        for future in finished:
            pending.pop(future, None)
        if deadline is not None and time.monotonic() >= deadline:
            break
    for ref in refs:  # stable order
        if ref._future in satisfied and len(done_refs) < max(num_returns, 0):
            done_refs.append(ref)
    done_set = set(id(r) for r in done_refs)
    not_done = [r for r in refs if id(r) not in done_set]
    return done_refs, not_done


# Last shuffle-worker pool created in this process (bench honesty
# fields): the bench record must report the EFFECTIVE data-plane width
# and backend, not os.cpu_count() — a 1-wide pool on a 96-core host must
# not claim 96-way normalization (ISSUE 7 satellite). Only real worker
# pools register; single-thread driver/utility pools do not.
_pool_info_lock = threading.Lock()
_last_pool_info = {"backend": None, "workers": None, "pids": []}


def note_worker_pool(backend: str, workers: int, pids: Sequence[int]) -> None:
    """Record the most recent worker pool's effective shape."""
    with _pool_info_lock:
        _last_pool_info.update(backend=backend, workers=workers,
                               pids=list(pids))


def last_worker_pool() -> dict:
    """``{backend, workers, pids}`` of the most recent worker pool (the
    bench record's executor_* fields); ``backend`` None if none yet."""
    with _pool_info_lock:
        return dict(_last_pool_info)


class Executor:
    """Per-host thread-pool task executor.

    Threads (not processes) because the hot work — pyarrow Parquet decode,
    take/concat, NumPy RNG — releases the GIL; threads share the host RAM
    arrow buffers zero-copy, which is the plasma-equivalent data plane.
    """

    def __init__(self, num_workers: Optional[int] = None,
                 thread_name_prefix: str = "rsdl-worker",
                 task_retries: int = 0,
                 retry_policy=None):
        """``task_retries``: re-run a task that raises up to N extra times
        before surfacing the failure — the stand-in for Ray's implicit task
        retry the reference leans on (SURVEY.md §5). Safe for local shuffle
        tasks (every random draw is keyed by (seed, epoch, task), so a
        retried task reproduces its output exactly) and for distributed MAP
        tasks (re-sent chunks are deduplicated by the receiver). NOT safe
        for tasks that consume one-shot inputs — distributed REDUCE tasks
        consume transport messages exactly once, so they are submitted via
        :meth:`submit_once`.

        Retries run under the shared ``runtime.retry.RetryPolicy``
        (exponential backoff with decorrelated jitter — a zero-sleep loop
        hammers exactly the resource that just failed), resolved for the
        ``executor`` component (``RSDL_EXECUTOR_RETRY_*`` env overrides
        the backoff bounds; ``task_retries`` pins the attempt budget).
        Pass ``retry_policy`` to override wholesale."""
        if num_workers is None:
            num_workers = os.cpu_count() or 4
        if task_retries < 0:
            raise ValueError(f"task_retries must be >= 0, got {task_retries}")
        self._num_workers = num_workers
        self._task_retries = task_retries
        # Registry evidence for rsdl_top / exposition: pool width and a
        # per-pool submission counter (labelled by thread-name prefix —
        # the same name SIGUSR1 stack dumps show, so the two join).
        from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
        rt_metrics.gauge("rsdl_executor_workers",
                         "thread-pool width by pool name",
                         pool=thread_name_prefix).set(num_workers)
        self._tasks_submitted = rt_metrics.counter(
            "rsdl_executor_tasks_total", "tasks submitted by pool name",
            pool=thread_name_prefix)
        # The thread backend's "worker process" is this process: publish
        # it under the same per-pid gauge the process pool uses so
        # rsdl_top's per-process view reads identically across backends.
        rt_metrics.gauge("rsdl_executor_worker_up",
                         "1 while the pid is a live pool worker",
                         pool=thread_name_prefix,
                         pid=str(os.getpid())).set(1)
        if retry_policy is None and task_retries:
            from ray_shuffling_data_loader_tpu.runtime import retry as rt
            retry_policy = rt.RetryPolicy.for_component(
                "executor", retry_max_attempts=task_retries + 1)
        self._retry_policy = retry_policy
        self._pool = cf.ThreadPoolExecutor(
            max_workers=num_workers, thread_name_prefix=thread_name_prefix)
        self._shutdown = False
        if thread_name_prefix == "rsdl-worker":
            note_worker_pool("thread", num_workers, [os.getpid()])

    #: Data-plane discriminator (procpool.ProcessPoolExecutor says
    #: "process"); shuffle_epoch and the bench record key off it.
    backend = "thread"

    @property
    def num_workers(self) -> int:
        return self._num_workers

    def worker_pids(self) -> List[int]:
        """PIDs actually executing tasks — for the thread backend that is
        this process alone (the bench record's honesty fields)."""
        return [os.getpid()]

    def submit(self, fn: Callable, *args, **kwargs) -> TaskRef:
        if self._shutdown:
            raise RuntimeError("executor is shut down")
        self._tasks_submitted.inc()
        if self._retry_policy is not None:
            return TaskRef(self._pool.submit(self._run_with_retries, fn,
                                             args, kwargs))
        return TaskRef(self._pool.submit(fn, *args, **kwargs))

    def submit_once(self, fn: Callable, *args, **kwargs) -> TaskRef:
        """Submit WITHOUT the executor's retry policy — for tasks whose
        inputs are consumed on first use (e.g. one-shot transport
        messages), where a retry could only block and then fail with a
        misleading timeout."""
        if self._shutdown:
            raise RuntimeError("executor is shut down")
        self._tasks_submitted.inc()
        return TaskRef(self._pool.submit(fn, *args, **kwargs))

    def _run_with_retries(self, fn: Callable, args, kwargs) -> Any:
        # RetryPolicy owns the loop: jittered backoff between attempts
        # (never a zero-sleep hammer), the teardown-signal exclusions
        # (KeyboardInterrupt/SystemExit are never swallowed and never
        # retried), WARNING per intermediate failure, and the FINAL
        # failure logged at ERROR with the exhausted budget.
        return self._retry_policy.call(
            fn, *args, describe=getattr(fn, "__name__", repr(fn)), **kwargs)

    def map(self, fn: Callable, items: Sequence) -> List[TaskRef]:
        return [self.submit(fn, item) for item in items]

    def shutdown(self, wait_for_tasks: bool = True) -> None:
        self._shutdown = True
        self._pool.shutdown(wait=wait_for_tasks)

    def __enter__(self) -> "Executor":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
