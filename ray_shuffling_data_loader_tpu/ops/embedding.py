"""TPU embedding lookup strategies for DLRM-style sparse features.

The reference never runs a real model (its train step is mocked,
reference: ray_torch_shuffle.py:199-204); our DLRM flagship does 19 table
lookups per step (models/dlrm.py), so the lookup is the model's hot
non-matmul op. Three strategies, dispatched by :func:`lookup`:

- ``take``: ``jnp.take(..., mode="clip")`` — XLA's native gather.
- ``one_hot``: encode indices as a ``(batch, vocab)`` one-hot and matmul
  with the table. Random-access gathers underuse the TPU (they issue from
  the scalar/vector units against 512-byte HBM granules); a one-hot matmul
  rides the MXU's systolic array instead. For small vocabularies the
  (batch x vocab) FLOP waste is far cheaper than the gather's latency —
  the standard TPU trick for small embedding tables. Exact: each output
  row is 1.0 times one table row, so even bf16 results match the gather
  bit-for-bit.
- ``pallas``: a Pallas kernel using ``PrefetchScalarGridSpec`` — indices
  are scalar-prefetched into SMEM so each grid step's BlockSpec index_map
  selects the table row to DMA HBM->VMEM, overlapping row fetches with the
  pipeline. Backward is an XLA scatter-add via ``custom_vjp``.

``auto`` picks ``one_hot`` for vocab <= ONE_HOT_MAX_VOCAB; above it, the
Pallas gather on a real TPU when the embed dim is 128-lane aligned
(kernel-isolated on-chip measurement: ~25-30% faster than XLA gather at
vocab 1M / embed 128 / batch 64k — benchmarks/bench_embedding.py), else
XLA ``take``.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

# Above this vocab size the one-hot matmul's wasted FLOPs and VMEM
# pressure outgrow the gather's latency; 2048 keeps the one-hot tile
# within a few MXU passes at typical batch sizes.
ONE_HOT_MAX_VOCAB = 2048


def take_lookup(table: jax.Array, indices: jax.Array,
                dtype: Any) -> jax.Array:
    """XLA gather. mode="clip" so a stray bad index cannot NaN the step
    (models/dlrm.py validates batches host-side instead)."""
    return jnp.take(table.astype(dtype), indices, axis=0, mode="clip")


def one_hot_lookup(table: jax.Array, indices: jax.Array,
                   dtype: Any) -> jax.Array:
    """(batch, vocab) one-hot @ (vocab, embed) on the MXU."""
    vocab = table.shape[0]
    indices = jnp.clip(indices, 0, vocab - 1)
    one_hot = jax.nn.one_hot(indices, vocab, dtype=dtype)
    return one_hot @ table.astype(dtype)


# Output rows gathered per grid step. 8 = the float32 sublane tile, the
# minimum legal block height; it also bounds in-flight row DMAs per step.
_GATHER_BLOCK = 8


def _pallas_gather_impl(table: jax.Array, indices: jax.Array,
                        interpret: bool) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _, embed_dim = table.shape
    batch = indices.shape[0]
    padded = ((batch + _GATHER_BLOCK - 1) // _GATHER_BLOCK) * _GATHER_BLOCK
    if padded != batch:
        indices = jnp.pad(indices, (0, padded - batch))

    def kernel(idx_ref, table_ref, out_ref, sems):
        i = pl.program_id(0)
        # Issue all row DMAs of this block back-to-back (HBM -> this
        # step's VMEM output block), then wait — the copies overlap.
        dmas = []
        for j in range(_GATHER_BLOCK):
            row = idx_ref[i * _GATHER_BLOCK + j]
            dma = pltpu.make_async_copy(
                table_ref.at[pl.ds(row, 1), :],
                out_ref.at[pl.ds(j, 1), :],
                sems.at[j])
            dma.start()
            dmas.append(dma)
        for dma in dmas:
            dma.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(padded // _GATHER_BLOCK,),
        in_specs=[
            # The table never enters VMEM wholesale; rows are DMA'd on
            # demand straight out of HBM.
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((_GATHER_BLOCK, embed_dim),
                               lambda i, idx_ref: (i, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((_GATHER_BLOCK,))],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((padded, embed_dim), table.dtype),
        interpret=interpret,
    )(indices, table)
    return out[:batch] if padded != batch else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _pallas_gather(table: jax.Array, indices: jax.Array,
                   interpret: bool) -> jax.Array:
    return _pallas_gather_impl(table, indices, interpret)


def _pallas_gather_fwd(table, indices, interpret):
    return _pallas_gather_impl(table, indices, interpret), (
        indices, table.shape[0])


def _pallas_gather_bwd(interpret, residual, cotangent):
    indices, vocab = residual
    d_table = jnp.zeros((vocab, cotangent.shape[-1]),
                        cotangent.dtype).at[indices].add(cotangent)
    return d_table, None


_pallas_gather.defvjp(_pallas_gather_fwd, _pallas_gather_bwd)


def pallas_lookup(table: jax.Array, indices: jax.Array,
                  dtype: Any) -> jax.Array:
    """Pallas scalar-prefetch row gather (interpret mode off-TPU).

    On real TPUs Mosaic requires HBM row-slice DMAs to be 128-lane
    aligned, so tables whose embed dim is not a multiple of 128 fall back
    to the XLA gather (numerically identical).
    """
    vocab, embed_dim = table.shape
    interpret = jax.default_backend() != "tpu"
    if not interpret and embed_dim % 128 != 0:
        return take_lookup(table, indices, dtype)
    indices = jnp.clip(indices.astype(jnp.int32), 0, vocab - 1)
    # Gather in the table's storage dtype and cast afterwards: Mosaic
    # supports single-row HBM DMAs for 4-byte types but not 2-byte ones,
    # and cast-then-gather == gather-then-cast elementwise.
    return _pallas_gather(table, indices, interpret).astype(dtype)


def _auto_mode(vocab: int, embed_dim: int) -> str:
    if vocab <= ONE_HOT_MAX_VOCAB:
        return "one_hot"
    if jax.default_backend() == "tpu" and embed_dim % 128 == 0:
        return "pallas"
    return "take"


def lookup(table: jax.Array,
           indices: jax.Array,
           dtype: Any,
           mode: str = "auto") -> jax.Array:
    """Embedding lookup: ``table (vocab, embed)``, ``indices (batch,)`` ->
    ``(batch, embed)`` in ``dtype``. All modes clip out-of-range indices
    and return bit-identical results; they differ only in which hardware
    unit does the work."""
    if mode == "auto":
        mode = _auto_mode(table.shape[0], table.shape[1])
    if mode == "take":
        return take_lookup(table, indices, dtype)
    if mode == "one_hot":
        return one_hot_lookup(table, indices, dtype)
    if mode == "pallas":
        return pallas_lookup(table, indices, dtype)
    raise ValueError(
        f"unknown lookup mode {mode!r}; expected auto/take/one_hot/pallas")
