"""Seeded row partitioning and permutation primitives.

These are the statistical heart of the shuffle. The reference draws an
unseeded uniform reducer id per row in its map task
(reference: shuffle.py:213) and does an unseeded ``df.sample(frac=1)``
permutation in its reduce task (reference: shuffle.py:240); both are
irreproducible. We keep the same two-stage statistical structure
(uniform multinomial row->reducer assignment, then within-reducer
permutation) but derive every random stream from an explicit
``(seed, epoch, task)`` key via ``np.random.SeedSequence`` so an epoch's
shuffle is exactly replayable — which is what makes loader
checkpoint/resume possible.

The hot paths (``partition_indices``) dispatch to the native C++ kernel in
``ray_shuffling_data_loader_tpu.native`` when it is available and fall back
to NumPy otherwise.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

# Stream-domain tags so map and reduce tasks for the same (seed, epoch)
# never share a random stream.
_MAP_STREAM = 0
_REDUCE_STREAM = 1

# Domain tag for the fused hash-partition stream (plan v2). Distinct from
# the Philox spawn keys above by construction (different generator family),
# but tagged anyway so future streams keyed off the same triple can't
# collide with it.
_PLAN_STREAM = 2

_MASK64 = (1 << 64) - 1


def _mix64(x: int) -> int:
    """splitmix64 finalizer over Python ints (single values only — the
    vectorized form lives in ``native.hash_assign``)."""
    x &= _MASK64
    x = ((x ^ (x >> 30)) * 0xbf58476d1ce4e5b9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94d049bb133111eb) & _MASK64
    return x ^ (x >> 31)


def partition_key(seed: int, epoch: int, file_index: int) -> int:
    """64-bit key for the fused partition plan of one map task.

    Chained splitmix64 mixing of the ``(seed, epoch, file_index)`` lineage
    triple — the same determinism contract as :func:`map_rng`, expressed as
    one integer the native kernel (and its NumPy twin) can stream from.
    """
    key = _mix64((seed & _MASK64) ^ (_PLAN_STREAM << 56))
    key = _mix64(key ^ ((epoch & _MASK64) * 0x9e3779b97f4a7c15))
    return _mix64(key ^ ((file_index & _MASK64) * 0xc2b2ae3d27d4eb4f))


def map_rng(seed: int, epoch: int, file_index: int) -> np.random.Generator:
    """PRNG for the map task of ``file_index`` in ``epoch``."""
    seq = np.random.SeedSequence(entropy=seed,
                                 spawn_key=(_MAP_STREAM, epoch, file_index))
    return np.random.Generator(np.random.Philox(seq))


def reduce_rng(seed: int, epoch: int, reducer_index: int) -> np.random.Generator:
    """PRNG for the reduce task of ``reducer_index`` in ``epoch``."""
    seq = np.random.SeedSequence(entropy=seed,
                                 spawn_key=(_REDUCE_STREAM, epoch, reducer_index))
    return np.random.Generator(np.random.Philox(seq))


def assign_reducers(num_rows: int, num_reducers: int,
                    rng: np.random.Generator) -> np.ndarray:
    """Uniformly assign each of ``num_rows`` rows to a reducer.

    Mirrors ``np.random.randint(num_reducers, size=len(rows))``
    (reference: shuffle.py:213) but seeded.
    """
    return rng.integers(0, num_reducers, size=num_rows, dtype=np.uint32)


def partition_indices(assignments: np.ndarray,
                      num_reducers: int) -> List[np.ndarray]:
    """Stable-partition row indices by reducer assignment.

    Returns ``num_reducers`` int64 index arrays; concatenated they are a
    permutation of ``arange(len(assignments))``. Stability (original row
    order preserved within a partition) keeps the shuffle's statistics
    identical to the reference's boolean-mask partitioning
    (reference: shuffle.py:215-218) at O(n) instead of O(n * num_reducers).
    """
    from ray_shuffling_data_loader_tpu import native
    if native.available():
        return native.partition_indices(assignments, num_reducers)
    return partition_indices_numpy(assignments, num_reducers)


def partition_indices_numpy(assignments: np.ndarray,
                            num_reducers: int) -> List[np.ndarray]:
    """Pure-NumPy fallback for :func:`partition_indices`."""
    if num_reducers < 1:
        raise ValueError(f"num_reducers must be >= 1, got {num_reducers}")
    counts = np.bincount(assignments, minlength=num_reducers)
    if len(counts) > num_reducers:
        raise ValueError(
            f"assignment value out of range for num_reducers={num_reducers}")
    order = np.argsort(assignments, kind="stable").astype(np.int64, copy=False)
    splits = np.cumsum(counts)[:-1]
    return [part for part in np.split(order, splits)]


def plan_partition_flat(num_rows: int, num_reducers: int, seed: int,
                        epoch: int, file_index: int, nthreads: int = 1
                        ) -> "tuple[np.ndarray, np.ndarray]":
    """Fused assign+partition plan: ``(flat_indices, offsets)``.

    One kernel replaces the map task's two-stage assign (Philox draw) ->
    partition (counting sort) pipeline: each row's reducer is a stateless
    splitmix64 hash of ``(partition_key(seed, epoch, file_index), row)``
    and the stable counting sort is fused around it, so the per-row
    assignment array is never materialized on the native path. The NumPy
    fallback vectorizes the identical hash, so the plan is bit-identical
    with and without the native library — which is what lets the thread
    and process executor backends (and recovery recomputes on either)
    reproduce each other's shuffles exactly.
    """
    from ray_shuffling_data_loader_tpu import native
    key = partition_key(seed, epoch, file_index)
    if native.available():
        return native.plan_partition_flat(num_rows, num_reducers, key,
                                          nthreads=nthreads)
    assignments = native.hash_assign(num_rows, num_reducers, key)
    counts = np.bincount(assignments, minlength=num_reducers)
    order = np.argsort(assignments, kind="stable").astype(np.int64,
                                                          copy=False)
    offsets = np.zeros(num_reducers + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    return order, offsets


def partition_counts(num_rows: int, num_reducers: int, seed: int,
                     epoch: int, file_index: int, row0: int = 0,
                     nthreads: int = 1) -> np.ndarray:
    """Per-reducer row counts of the file's partition plan, with NO data
    and NO index array — the assignment stream is counter-based, so the
    counts for rows ``[row0, row0 + num_rows)`` are a pure function of
    ``(seed, epoch, file_index)``. Prefix sums of the full-file counts are
    exactly :func:`plan_partition_flat`'s ``offsets``, which is how the
    streaming map pipeline sizes its per-reducer output regions before the
    first record batch is decoded. Bit-identical native/NumPy paths."""
    from ray_shuffling_data_loader_tpu import native
    key = partition_key(seed, epoch, file_index)
    if native.available():
        return native.partition_counts(num_rows, num_reducers, key,
                                       row0=row0, nthreads=nthreads)
    assignments = native.hash_assign(num_rows, num_reducers, key, row0=row0)
    return np.bincount(assignments,
                       minlength=num_reducers).astype(np.int64, copy=False)


def assign_dest_batch(num_rows: int, num_reducers: int, seed: int,
                      epoch: int, file_index: int, row0: int,
                      cursors: np.ndarray) -> np.ndarray:
    """Destination slots for one record batch of the streaming map
    pipeline: row ``row0 + i`` lands at slot ``cursors[r]`` of its reducer
    ``r``'s region, and ``cursors`` (int64, one per reducer, seeded with
    the region offsets) advance in place. Because batches arrive in
    increasing global row order, each region fills in original row order —
    the same stable order the legacy counting-sort plan guarantees, so
    scattering batches through these slots reproduces the legacy
    plan-then-gather layout bit for bit. int32 slots on the native path
    (callers pre-check total rows < 2**31); the NumPy fallback computes
    identical slot values."""
    key = partition_key(seed, epoch, file_index)
    from ray_shuffling_data_loader_tpu import native
    if native.available() and int(cursors.max(initial=0)) + num_rows < 2**31:
        return native.assign_dest(num_rows, num_reducers, key, row0, cursors)
    assignments = native.hash_assign(num_rows, num_reducers, key, row0=row0)
    counts = np.bincount(assignments, minlength=num_reducers)
    order = np.argsort(assignments, kind="stable")
    starts = np.zeros(num_reducers, dtype=np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    dest = np.empty(num_rows, dtype=np.int64)
    # In stable-sorted order, reducer r's rows occupy one run; its k-th row
    # (original order) lands at cursors[r] + k.
    dest[order] = (np.repeat(cursors[:num_reducers], counts)
                   + np.arange(num_rows) - np.repeat(starts, counts))
    cursors += counts
    return dest


def plan_partition(num_rows: int, num_reducers: int, seed: int, epoch: int,
                   file_index: int, nthreads: int = 1) -> List[np.ndarray]:
    """Per-reducer index arrays from the fused partition plan (the
    list-of-parts view of :func:`plan_partition_flat`, shape-compatible
    with :func:`partition_indices`)."""
    flat, offsets = plan_partition_flat(num_rows, num_reducers, seed,
                                        epoch, file_index, nthreads)
    return [flat[offsets[r]:offsets[r + 1]] for r in range(num_reducers)]


def permutation(num_rows: int, rng: np.random.Generator) -> np.ndarray:
    """Uniform permutation of ``arange(num_rows)``.

    Mirrors ``df.sample(frac=1)`` (reference: shuffle.py:240) but seeded.
    """
    return rng.permutation(num_rows)


def split_sizes(total: int, num_parts: int) -> List[int]:
    """Sizes produced by ``np.array_split(range(total), num_parts)``.

    The reference routes reducer outputs to trainers with ``np.array_split``
    (reference: shuffle.py:188-189); we reproduce its contiguous,
    remainder-first split arithmetic exactly.
    """
    base, rem = divmod(total, num_parts)
    return [base + 1 if i < rem else base for i in range(num_parts)]


def contiguous_splits(items: Sequence, num_parts: int) -> List[list]:
    """Contiguous split of ``items`` into ``num_parts`` groups, array_split-style."""
    out: List[list] = []
    start = 0
    for size in split_sizes(len(items), num_parts):
        out.append(list(items[start:start + size]))
        start += size
    return out
