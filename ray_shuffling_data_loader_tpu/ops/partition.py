"""Seeded row partitioning and permutation primitives.

These are the statistical heart of the shuffle. The reference draws an
unseeded uniform reducer id per row in its map task
(reference: shuffle.py:213) and does an unseeded ``df.sample(frac=1)``
permutation in its reduce task (reference: shuffle.py:240); both are
irreproducible. We keep the same two-stage statistical structure
(uniform multinomial row->reducer assignment, then within-reducer
permutation) but derive every random stream from an explicit
``(seed, epoch, task)`` key via ``np.random.SeedSequence`` so an epoch's
shuffle is exactly replayable — which is what makes loader
checkpoint/resume possible.

The hot paths (``partition_indices``) dispatch to the native C++ kernel in
``ray_shuffling_data_loader_tpu.native`` when it is available and fall back
to NumPy otherwise.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

# Stream-domain tags so map and reduce tasks for the same (seed, epoch)
# never share a random stream.
_MAP_STREAM = 0
_REDUCE_STREAM = 1


def map_rng(seed: int, epoch: int, file_index: int) -> np.random.Generator:
    """PRNG for the map task of ``file_index`` in ``epoch``."""
    seq = np.random.SeedSequence(entropy=seed,
                                 spawn_key=(_MAP_STREAM, epoch, file_index))
    return np.random.Generator(np.random.Philox(seq))


def reduce_rng(seed: int, epoch: int, reducer_index: int) -> np.random.Generator:
    """PRNG for the reduce task of ``reducer_index`` in ``epoch``."""
    seq = np.random.SeedSequence(entropy=seed,
                                 spawn_key=(_REDUCE_STREAM, epoch, reducer_index))
    return np.random.Generator(np.random.Philox(seq))


def assign_reducers(num_rows: int, num_reducers: int,
                    rng: np.random.Generator) -> np.ndarray:
    """Uniformly assign each of ``num_rows`` rows to a reducer.

    Mirrors ``np.random.randint(num_reducers, size=len(rows))``
    (reference: shuffle.py:213) but seeded.
    """
    return rng.integers(0, num_reducers, size=num_rows, dtype=np.uint32)


def partition_indices(assignments: np.ndarray,
                      num_reducers: int) -> List[np.ndarray]:
    """Stable-partition row indices by reducer assignment.

    Returns ``num_reducers`` int64 index arrays; concatenated they are a
    permutation of ``arange(len(assignments))``. Stability (original row
    order preserved within a partition) keeps the shuffle's statistics
    identical to the reference's boolean-mask partitioning
    (reference: shuffle.py:215-218) at O(n) instead of O(n * num_reducers).
    """
    from ray_shuffling_data_loader_tpu import native
    if native.available():
        return native.partition_indices(assignments, num_reducers)
    return partition_indices_numpy(assignments, num_reducers)


def partition_indices_numpy(assignments: np.ndarray,
                            num_reducers: int) -> List[np.ndarray]:
    """Pure-NumPy fallback for :func:`partition_indices`."""
    if num_reducers < 1:
        raise ValueError(f"num_reducers must be >= 1, got {num_reducers}")
    counts = np.bincount(assignments, minlength=num_reducers)
    if len(counts) > num_reducers:
        raise ValueError(
            f"assignment value out of range for num_reducers={num_reducers}")
    order = np.argsort(assignments, kind="stable").astype(np.int64, copy=False)
    splits = np.cumsum(counts)[:-1]
    return [part for part in np.split(order, splits)]


def permutation(num_rows: int, rng: np.random.Generator) -> np.ndarray:
    """Uniform permutation of ``arange(num_rows)``.

    Mirrors ``df.sample(frac=1)`` (reference: shuffle.py:240) but seeded.
    """
    return rng.permutation(num_rows)


def split_sizes(total: int, num_parts: int) -> List[int]:
    """Sizes produced by ``np.array_split(range(total), num_parts)``.

    The reference routes reducer outputs to trainers with ``np.array_split``
    (reference: shuffle.py:188-189); we reproduce its contiguous,
    remainder-first split arithmetic exactly.
    """
    base, rem = divmod(total, num_parts)
    return [base + 1 if i < rem else base for i in range(num_parts)]


def contiguous_splits(items: Sequence, num_parts: int) -> List[list]:
    """Contiguous split of ``items`` into ``num_parts`` groups, array_split-style."""
    out: List[list] = []
    start = 0
    for size in split_sizes(len(items), num_parts):
        out.append(list(items[start:start + size]))
        start += size
    return out
