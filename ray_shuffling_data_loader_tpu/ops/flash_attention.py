"""Pallas TPU flash attention: blockwise online-softmax on the MXU.

The hot op of the BERT-MLM family (BASELINE.json config 4). The inline
attention in models/bert.py materializes the full (B, H, S, S) score
matrix in HBM; these kernels stream K/V *blocks* through VMEM (one block
per grid step — VMEM residency is O(block·D), independent of S) with the
online-softmax recurrence, so scores never leave VMEM and HBM traffic
drops from O(S²) to O(S·D) — the usual flash-attention win, written as
Pallas kernels per /opt/skills/guides/pallas_guide.md (grid over
(batch, head, q-block, k-block) with the K dimension innermost; running
max / denominator / accumulator live in VMEM scratch that persists across
the K iterations; the output block is written on the last K step).

Training is blockwise end-to-end: the forward kernel also emits the
per-query logsumexp, and the backward pass is two more Pallas kernels
(dq: grid over q-blocks streaming K; dk/dv(+dbias): grid over k-blocks
streaming Q) using the standard flash-attention backward identities —
no O(S²) tensor is ever materialized in either direction. Wrapped in a
``jax.custom_vjp``.

Mosaic requires (8, 128)-aligned tiles, so on TPU the sequence dims are
padded up to aligned block multiples (padded keys masked with a large
negative, padded query rows sliced off) rather than silently shrinking
blocks to degenerate sizes. When there is no bias and no padding, the
kernels compile without any bias machinery.

On CPU (tests, the 8-device virtual mesh) the same kernels run under the
Pallas interpreter; ``make_flash_attention_fn`` picks interpret mode
automatically so the op is portable. Composes with models/bert.py via the
``attention_fn`` hook, like ops/ring_attention.py's sequence-parallel
strategies (flash = single-device long-S; ring = cross-device sharded-S).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30   # accumulator init
_MASK = -1e9   # padded-key bias (finite, matches ring_attention.NEG_INF)


def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, dtype)


def _compiler_params(interpret: bool):
    """Mark the grid for Mosaic: batch/head/outer-block dims are parallel
    (no cross-iteration state), the innermost dim is ARBITRARY (the
    online-softmax / gradient accumulators in VMEM scratch carry across
    it) — the standard declaration for flash-style kernels. A/B on the
    shared round-3 chip was noise-bound (~±30% run-to-run), so no perf
    claim is attached; the annotation is kept for its scheduling freedom
    on quieter hardware."""
    if interpret:
        return None
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"))


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# -- kernels ----------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale: float):
    """Grid (B, H, num_q, num_k), K innermost. Blocks: q/o (1,1,bq,D);
    k/v (1,1,bk,D); bias (1,1,1,bk) or absent; lse (1,1,bq,1). Scratch
    m/l (bq,1), acc (bq,D) persist across the K iterations of one
    q-block."""
    j = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0, 0].astype(jnp.float32) * scale      # (bq, D)
    k = k_ref[0, 0].astype(jnp.float32)              # (bk, D)
    v = v_ref[0, 0].astype(jnp.float32)
    s = _dot(q, k, ((1,), (1,)))                     # (bq, bk)
    if bias_ref is not None:
        s = s + bias_ref[0, 0, 0][None, :]
    m_prev, l_prev = m_scr[:], l_scr[:]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    m_scr[:] = m_new
    l_scr[:] = l_prev * corr + p.sum(axis=-1, keepdims=True)
    acc_scr[:] = acc_scr[:] * corr + _dot(p, v, ((1,), (0,)))

    @pl.when(j == num_k - 1)
    def _finish():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[:] + jnp.log(l)


def _fwd_kernel_nobias(q_ref, k_ref, v_ref, o_ref, lse_ref,
                       m_scr, l_scr, acc_scr, *, scale: float):
    _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, scale=scale)


def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, *, scale: float):
    """Grid (B, H, num_q, num_k), K innermost: dq for one q-block."""
    j = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q = q_ref[0, 0].astype(jnp.float32)
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    s = _dot(q, k, ((1,), (1,))) * scale
    if bias_ref is not None:
        s = s + bias_ref[0, 0, 0][None, :]
    p = jnp.exp(s - lse_ref[0, 0])                   # softmax weights
    dp = _dot(do, v, ((1,), (1,)))                   # (bq, bk)
    ds = p * (dp - delta_ref[0, 0])
    dq_scr[:] = dq_scr[:] + _dot(ds, k, ((1,), (0,))) * scale

    @pl.when(j == num_k - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dq_kernel_nobias(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dq_scr, *, scale: float):
    _dq_kernel(q_ref, k_ref, v_ref, None, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, scale=scale)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dbias_ref, dk_scr, dv_scr, dbias_scr, *,
                scale: float):
    """Grid (B, H, num_k, num_q), Q innermost: dk/dv/dbias for one
    k-block. dbias is emitted per-head (summed over heads by the caller)."""
    j = pl.program_id(3)
    num_q = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)
        if dbias_scr is not None:
            dbias_scr[:] = jnp.zeros_like(dbias_scr)

    q = q_ref[0, 0].astype(jnp.float32)
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    s = _dot(q, k, ((1,), (1,))) * scale
    if bias_ref is not None:
        s = s + bias_ref[0, 0, 0][None, :]
    p = jnp.exp(s - lse_ref[0, 0])                   # (bq, bk)
    dv_scr[:] = dv_scr[:] + _dot(p, do, ((0,), (0,)))
    dp = _dot(do, v, ((1,), (1,)))
    ds = p * (dp - delta_ref[0, 0])
    dk_scr[:] = dk_scr[:] + _dot(ds, q, ((0,), (0,))) * scale
    if dbias_scr is not None:
        dbias_scr[:] = dbias_scr[:] + ds.sum(axis=0, keepdims=True)

    @pl.when(j == num_q - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)
        if dbias_ref is not None:
            dbias_ref[0, 0, 0] = dbias_scr[0]


def _dkv_kernel_nobias(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float):
    _dkv_kernel(q_ref, k_ref, v_ref, None, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, None, dk_scr, dv_scr, None, scale=scale)


# -- block planning & padding ----------------------------------------------


def _pick_block(seq: int, preferred: int) -> int:
    """Largest divisor of ``seq`` that is <= preferred (>= 1)."""
    block = min(preferred, seq)
    while seq % block:
        block -= 1
    return block


def _pick_aligned_block(seq: int, preferred: int, align: int) -> int:
    """Aligned tile size: the largest multiple of ``align`` <= preferred
    that divides ``round_up(seq, align)`` (no padding beyond alignment) —
    a fixed preferred block would pad e.g. S=768 up to 1024 with 512
    blocks (~33% wasted FLOPs); this picks 384. But never a DEGENERATE
    divisor: below ~64 rows the MXU runs mostly idle per pass (S=1016 =
    8*127 has no nontrivial aligned divisor), and padding up to the
    preferred block is far cheaper than 8-row tiles — so when only
    tiny divisors exist, fall back to the preferred block and pad."""
    target = _round_up(seq, align)
    cap = min(_round_up(preferred, align), target)
    floor = max(align, min(cap, 64))
    block = cap
    while target % block and block > floor:
        block -= align
    if target % block:
        return cap  # only degenerate divisors: pad instead
    return block


# Default VMEM tile sizes, shared by every public entry point here and by
# the ring-attention flash hops (ops/ring_attention.py) — retune in ONE
# place. From the round-4 on-chip sweep (v5e, D=64): bq=512/bk=1024 beat
# 512/512 by ~14% fwd+bwd at S=2048-4096; blocks clamp to S, so small-S
# kernels are unchanged.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024


def _plan(sq: int, sk: int, block_q: int, block_k: int, interpret: bool):
    """(bq, bk, sq_pad, sk_pad). Interpret mode: any divisor works.
    TPU: blocks must be (8, 128)-tile aligned, so pad the sequence dims
    up to aligned block multiples instead of shrinking blocks."""
    if interpret:
        return (_pick_block(sq, block_q), _pick_block(sk, block_k), sq, sk)
    bq = _pick_aligned_block(sq, block_q, 8)
    sq_pad = _round_up(sq, bq)
    bk = _pick_aligned_block(sk, block_k, 128)
    sk_pad = _round_up(sk, bk)
    return bq, bk, sq_pad, sk_pad


def _pad_dim2(x, target: int):
    """Zero-pad (B, H, S, D) along S to ``target`` rows."""
    if x.shape[2] == target:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, target - x.shape[2]), (0, 0)))


def _prep_bias(bias, b: int, sk: int, sk_pad: int):
    """Validated f32 bias padded to sk_pad (padded keys masked), or None
    when there is neither a bias nor key padding."""
    if bias is not None and bias.shape != (b, 1, 1, sk):
        raise ValueError(
            f"flash_attention bias must be key-side (B, 1, 1, S) = "
            f"{(b, 1, 1, sk)}, got {bias.shape}; full (.., S, S) biases "
            "(e.g. causal masks) are not supported by this kernel")
    if bias is None and sk_pad == sk:
        return None
    base = (jnp.zeros((b, 1, 1, sk), jnp.float32) if bias is None
            else bias.astype(jnp.float32))
    if sk_pad != sk:
        base = jnp.pad(base, ((0, 0), (0, 0), (0, 0), (0, sk_pad - sk)),
                       constant_values=_MASK)
    return base


# -- forward / backward dispatch --------------------------------------------


def _flash_forward(q, k, v, bias, block_q: int, block_k: int,
                   interpret: bool):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk, sq_pad, sk_pad = _plan(sq, sk, block_q, block_k, interpret)
    scale = 1.0 / (d ** 0.5)
    bias_arr = _prep_bias(bias, b, sk, sk_pad)
    qp = _pad_dim2(q, sq_pad)
    kp, vp = _pad_dim2(k, sk_pad), _pad_dim2(v, sk_pad)
    grid = (b, h, sq_pad // bq, sk_pad // bk)

    in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda i, j, g, t: (i, j, g, 0)),
        pl.BlockSpec((1, 1, bk, d), lambda i, j, g, t: (i, j, t, 0)),
        pl.BlockSpec((1, 1, bk, d), lambda i, j, g, t: (i, j, t, 0)),
    ]
    args = [qp, kp, vp]
    if bias_arr is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, 1, bk), lambda i, j, g, t: (i, 0, 0, t)))
        args.append(bias_arr)
        kernel = functools.partial(_fwd_kernel, scale=scale)
    else:
        kernel = functools.partial(_fwd_kernel_nobias, scale=scale)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda i, j, g, t: (i, j, g, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda i, j, g, t: (i, j, g, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qp.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, sq_pad, 1), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((bq, 1), jnp.float32),
            _vmem((bq, 1), jnp.float32),
            _vmem((bq, d), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(*args)
    if sq_pad != sq:
        out, lse = out[:, :, :sq], lse[:, :, :sq]
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(q: jax.Array,
                    k: jax.Array,
                    v: jax.Array,
                    bias: Optional[jax.Array] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False) -> jax.Array:
    """Exact attention via the Pallas flash kernels.

    Args:
        q, k, v: (B, H, S, D).
        bias: optional additive key-side bias, strictly (B, 1, 1, S).
        block_q/block_k: preferred VMEM tile sizes. Defaults from the
            round-4 on-chip sweep (v5e, D=64, scan-amortized timing):
            bq=512/bk=1024 beat 512/512 by ~14% fwd+bwd at S=2048-4096;
            bk=2048 wins a little more at the extremes but loses at mid
            S. At S <= bk the block clamps to S, so small-S kernels are
            unchanged.
        interpret: run under the Pallas interpreter (CPU tests).

    Fully blockwise in both directions: neither forward nor backward
    materializes an O(S²) tensor.
    """
    out, _ = _flash_forward(q, k, v, bias, block_q, block_k, interpret)
    return out


def flash_forward(q, k, v, bias=None, block_q: int = DEFAULT_BLOCK_Q,
                  block_k: int = DEFAULT_BLOCK_K, interpret: bool = False):
    """Forward kernels only: returns ``(out, lse)`` with lse
    (B, H, Sq, 1) float32 — the partial-softmax residual ring attention
    needs to merge per-hop results (ops/ring_attention.py)."""
    return _flash_forward(q, k, v, bias, block_q, block_k, interpret)


def _flash_fwd(q, k, v, bias, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, bias, block_q, block_k, interpret)
    return out, (q, k, v, bias, out, lse)


def _flash_bwd(block_q, block_k, interpret, residuals, do):
    q, k, v, bias, out, lse = residuals
    return flash_backward(q, k, v, bias, out, lse, do, block_q, block_k,
                          interpret)


def flash_backward(q, k, v, bias, out, lse, do, block_q: int = DEFAULT_BLOCK_Q,
                   block_k: int = DEFAULT_BLOCK_K, interpret: bool = False):
    """Backward kernels: ``(dq, dk, dv, dbias)`` from the standard flash
    residuals. ``lse`` may be global (covering MORE keys than ``k``) — the
    ring backward exploits this: with the global logsumexp, the recomputed
    per-hop weights ``exp(s - lse)`` are the global softmax restricted to
    this hop's keys, so per-hop grads sum to the exact global gradient."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk, sq_pad, sk_pad = _plan(sq, sk, block_q, block_k, interpret)
    scale = 1.0 / (d ** 0.5)
    bias_arr = _prep_bias(bias, b, sk, sk_pad)
    # delta_i = sum_d do_i * o_i — the softmax-backward correction term.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # (B, H, Sq, 1)
    qp, dop = _pad_dim2(q, sq_pad), _pad_dim2(do, sq_pad)
    kp, vp = _pad_dim2(k, sk_pad), _pad_dim2(v, sk_pad)
    lsep, deltap = _pad_dim2(lse[..., None] if lse.ndim == 3 else lse,
                             sq_pad), _pad_dim2(delta, sq_pad)
    has_bias = bias_arr is not None

    q_spec4 = pl.BlockSpec((1, 1, bq, d), lambda i, j, g, t: (i, j, g, 0))
    k_spec4 = pl.BlockSpec((1, 1, bk, d), lambda i, j, g, t: (i, j, t, 0))
    r_spec4 = pl.BlockSpec((1, 1, bq, 1), lambda i, j, g, t: (i, j, g, 0))
    b_spec4 = pl.BlockSpec((1, 1, 1, bk), lambda i, j, g, t: (i, 0, 0, t))
    in_specs = [q_spec4, k_spec4, k_spec4]
    args = [qp, kp, vp]
    if has_bias:
        in_specs.append(b_spec4)
        args.append(bias_arr)
    in_specs += [q_spec4, r_spec4, r_spec4]
    args += [dop, lsep, deltap]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel if has_bias else _dq_kernel_nobias,
                          scale=scale),
        grid=(b, h, sq_pad // bq, sk_pad // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda i, j, g, t: (i, j, g, 0)),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        scratch_shapes=[_vmem((bq, d), jnp.float32)],
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(*args)
    if sq_pad != sq:
        dq = dq[:, :, :sq]

    # Same inputs, but grid transposed: (B, H, num_k, num_q), Q innermost.
    q_spec_t = pl.BlockSpec((1, 1, bq, d), lambda i, j, t, g: (i, j, g, 0))
    k_spec_t = pl.BlockSpec((1, 1, bk, d), lambda i, j, t, g: (i, j, t, 0))
    r_spec_t = pl.BlockSpec((1, 1, bq, 1), lambda i, j, t, g: (i, j, g, 0))
    b_spec_t = pl.BlockSpec((1, 1, 1, bk), lambda i, j, t, g: (i, 0, 0, t))
    in_specs_t = [q_spec_t, k_spec_t, k_spec_t]
    if has_bias:
        in_specs_t.append(b_spec_t)
    in_specs_t += [q_spec_t, r_spec_t, r_spec_t]

    out_specs = [k_spec_t, k_spec_t]
    out_shape = [jax.ShapeDtypeStruct(kp.shape, k.dtype),
                 jax.ShapeDtypeStruct(vp.shape, v.dtype)]
    scratch = [_vmem((bk, d), jnp.float32), _vmem((bk, d), jnp.float32)]
    if has_bias:
        # Per-head dbias: indexed by the head grid dim, unlike the input
        # bias (which broadcasts over heads from index 0).
        out_specs.append(
            pl.BlockSpec((1, 1, 1, bk), lambda i, j, t, g: (i, j, 0, t)))
        out_shape.append(
            jax.ShapeDtypeStruct((b, h, 1, sk_pad), jnp.float32))
        scratch.append(_vmem((1, bk), jnp.float32))

    results = pl.pallas_call(
        functools.partial(_dkv_kernel if has_bias else _dkv_kernel_nobias,
                          scale=scale),
        grid=(b, h, sk_pad // bk, sq_pad // bq),
        in_specs=in_specs_t,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(*args)
    dk, dv = results[0], results[1]
    if sk_pad != sk:
        dk, dv = dk[:, :, :sk], dv[:, :, :sk]

    dbias = None
    if bias is not None:
        dbias_h = results[2][:, :, :, :sk]
        dbias = dbias_h.sum(axis=1, keepdims=True).astype(bias.dtype)
    return dq, dk, dv, dbias


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# Below this sequence length XLA's fused attention ties or beats the
# Pallas kernels on-chip (round-4 bench_attention.py with the retuned
# blocks: 0.89x fwd+bwd at S=512, flash ahead from S=1024 — 1.75x
# there, 2.48x at S=4096).
FLASH_MIN_SEQ_LEN = 1024


def auto_attention_fn(seq_len: int,
                      block_q: int = DEFAULT_BLOCK_Q,
                      block_k: int = DEFAULT_BLOCK_K):
    """The measured-best attention for ``seq_len`` on this backend.

    Returns a flash ``attention_fn`` when running on TPU with
    ``seq_len >= FLASH_MIN_SEQ_LEN``, else ``None`` (models' inline XLA
    attention — which XLA fuses well at short S, and which avoids the
    interpreter's overhead on CPU). Pass the result straight to
    ``models/bert.py``'s ``attention_fn`` hook.
    """
    on_tpu = jax.default_backend() in ("tpu", "axon")
    if on_tpu and seq_len >= FLASH_MIN_SEQ_LEN:
        return make_flash_attention_fn(block_q, block_k)
    return None


def make_flash_attention_fn(block_q: int = DEFAULT_BLOCK_Q,
                            block_k: int = DEFAULT_BLOCK_K,
                            interpret: Optional[bool] = None):
    """An ``attention_fn(q, k, v, bias)`` closure for models/bert.py.

    ``interpret=None`` auto-selects the Pallas interpreter off-TPU.
    """
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    def attention_fn(q, k, v, bias=None):
        return flash_attention(q, k, v, bias, block_q, block_k, interpret)

    return attention_fn
