"""Sequence/context-parallel attention: ring attention and Ulysses.

The reference has no sequence axis at all (SURVEY.md §2.4/§5: tabular rows;
its BERT workload batches pre-tokenized fixed-length rows) — long-context
scaling is a capability the TPU build adds, designed mesh-first rather than
ported: both strategies are pure XLA collectives (``ppermute`` /
``all_to_all``) inside ``shard_map`` over a sequence mesh axis, so they run
over ICI on a slice and over DCN across slices with no custom kernels or
NCCL-style backend.

Two interchangeable strategies, both computing exact (not approximate)
softmax attention for sequences sharded along a mesh axis:

- :func:`ring_self_attention` — blockwise online-softmax attention; K/V
  (and the key-side bias) rotate around the ring one hop per step, so no
  device ever materializes the full sequence or the full score matrix.
  Memory per device: O(S/n · S/n) scores, O(S/n) K/V. The classic ring
  attention construction (Liu et al., 2023), built from ``jax.lax.ppermute``.
- :func:`ulysses_attention` — all-to-all head parallelism (DeepSpeed
  Ulysses): one ``all_to_all`` reshards from sequence-sharded to
  head-sharded, each device runs full-sequence attention for H/n heads,
  and a second ``all_to_all`` reshards back. Cheaper collectives for
  moderate S (2 all-to-alls vs n-1 ppermutes) but requires n | H and
  materializes full-length K/V per device.

Numerics: scores and the softmax accumulators are float32 regardless of
input dtype (bf16 in the models); masking uses a large finite negative so
fully-masked rows stay NaN-free.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e9  # finite, like models/bert.py — keeps softmax NaN-free


def _shard_map(fn, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` across the API move: the public ``jax.shard_map``
    (with ``check_vma``) landed after 0.4.x; earlier jax ships it as
    ``jax.experimental.shard_map.shard_map`` (with ``check_rep``). Both
    replication checks are disabled — the attention bodies run collectives
    whose replication the checker cannot infer."""
    public = getattr(jax, "shard_map", None)
    if public is not None:
        return public(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False)
    from jax.experimental.shard_map import shard_map as experimental
    return experimental(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=False)
_ACC_MIN = -1e30


def causal_bias(q_pos: jax.Array, k_pos: jax.Array) -> jax.Array:
    """(1, 1, Sq, Sk) additive causal mask from global position vectors."""
    return jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0,
                     NEG_INF)[None, None, :, :].astype(jnp.float32)


def _block_attention(q, k, v, bias, m, l, o):
    """One online-softmax accumulation step against a K/V block.

    q: (B, H, Sq, D) f32 (pre-scaled); k/v: (B, H, Sk, D); bias
    broadcastable to (B, H, Sq, Sk) f32. Carries m (running max), l
    (running denominator) of shape (B, H, Sq) and o (B, H, Sq, D), all f32.
    """
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k.astype(jnp.float32))
    if bias is not None:
        scores = scores + bias
    m_new = jnp.maximum(m, scores.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, o_new


def _ring_attention_fwd_impl(axis_name: str, causal: bool, q, k, v, kv_bias):
    """Forward ring pass; returns (out, lse) with lse = per-query
    logsumexp (B, H, Sq) — the residual that makes the recompute-per-hop
    backward pass possible without saving any per-step intermediate.
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    sq, sk = q.shape[2], k.shape[2]
    d = q.shape[-1]
    qf = q.astype(jnp.float32) * (1.0 / jnp.sqrt(d).astype(jnp.float32))
    m0 = jnp.full(q.shape[:3], _ACC_MIN, jnp.float32)
    l0 = jnp.zeros(q.shape[:3], jnp.float32)
    o0 = jnp.zeros(q.shape, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_bias = kv_bias is not None  # static: shapes the loop carry

    def step(i, carry):
        k_c, v_c, bias_c, m, l, o = carry
        # After i rotations this device holds chunk (my - i) mod n.
        src = (my - i) % n

        def accumulate(m, l, o):
            bias = bias_c
            if causal:
                cb = causal_bias(my * sq + jnp.arange(sq),
                                 src * sk + jnp.arange(sk))
                bias = cb if bias is None else bias + cb
            return _block_attention(qf, k_c, v_c, bias, m, l, o)

        if causal:
            # Chunks strictly in this query chunk's future contribute
            # nothing — skip their attention FLOPs entirely (about half
            # the ring steps at large n). The ppermutes below still run
            # every step, keeping the loop collective-uniform.
            m, l, o = jax.lax.cond(src > my, lambda m, l, o: (m, l, o),
                                   accumulate, m, l, o)
        else:
            m, l, o = accumulate(m, l, o)
        # One hop: send our current chunk to the next device on the ring.
        # (The final iteration's hop returns chunks to their owners — one
        # redundant ppermute, kept so the loop body is collective-uniform.)
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        if has_bias:
            bias_c = jax.lax.ppermute(bias_c, axis_name, perm)
        return k_c, v_c, bias_c, m, l, o

    bias0 = kv_bias.astype(jnp.float32) if has_bias else None
    _, _, _, m, l, o = jax.lax.fori_loop(
        0, n, step, (k, v, bias0, m0, l0, o0))
    l_safe = jnp.maximum(l, 1e-30)
    out = (o / l_safe[..., None]).astype(q.dtype)
    lse = m + jnp.log(l_safe)
    return out, lse


def _ring_attention_bwd_impl(axis_name: str, causal: bool, q, k, v, kv_bias,
                             out, lse, do):
    """Backward ring pass (recompute per hop, standard flash identities).

    dk/dv (and dbias) accumulators travel around the ring *with* their K/V
    chunks: after the loop's n rotations every gradient chunk is back at
    its owner. Per-step memory is O(Sq/n · Sk/n) — no residuals from the
    forward other than (out, lse).
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    sq, sk = q.shape[2], k.shape[2]
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    # delta_i = sum_d do_i * out_i (softmax-backward correction).
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # (B, H, Sq)
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_bias = kv_bias is not None

    def step(i, carry):
        k_c, v_c, bias_c, dk_c, dv_c, dbias_c, dq = carry
        src = (my - i) % n

        def accumulate(dk_c, dv_c, dbias_c, dq):
            kf, vf = k_c.astype(jnp.float32), v_c.astype(jnp.float32)
            s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
            if has_bias:
                s = s + bias_c
            if causal:
                s = s + causal_bias(my * sq + jnp.arange(sq),
                                    src * sk + jnp.arange(sk))[0]
            p = jnp.exp(s - lse[..., None])  # recomputed softmax weights
            dv_new = dv_c + jnp.einsum("bhqk,bhqd->bhkd", p, dof)
            dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
            ds = p * (dp - delta[..., None])
            dq_new = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
            dk_new = dk_c + jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
            dbias_new = dbias_c
            if has_bias:
                dbias_new = dbias_c + ds.sum(axis=(1, 2))[:, None, None, :]
            return dk_new, dv_new, dbias_new, dq_new

        if causal:
            dk_c, dv_c, dbias_c, dq = jax.lax.cond(
                src > my, lambda a, b, c, e: (a, b, c, e), accumulate,
                dk_c, dv_c, dbias_c, dq)
        else:
            dk_c, dv_c, dbias_c, dq = accumulate(dk_c, dv_c, dbias_c, dq)
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        dk_c = jax.lax.ppermute(dk_c, axis_name, perm)
        dv_c = jax.lax.ppermute(dv_c, axis_name, perm)
        if has_bias:
            bias_c = jax.lax.ppermute(bias_c, axis_name, perm)
            dbias_c = jax.lax.ppermute(dbias_c, axis_name, perm)
        return k_c, v_c, bias_c, dk_c, dv_c, dbias_c, dq

    bias0 = kv_bias.astype(jnp.float32) if has_bias else None
    dbias0 = jnp.zeros((q.shape[0], 1, 1, sk), jnp.float32) if has_bias \
        else None
    carry0 = (k, v, bias0, jnp.zeros(k.shape, jnp.float32),
              jnp.zeros(v.shape, jnp.float32), dbias0,
              jnp.zeros(q.shape, jnp.float32))
    _, _, _, dk, dv, dbias, dq = jax.lax.fori_loop(0, n, step, carry0)
    dbias_out = dbias.astype(kv_bias.dtype) if has_bias else None
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias_out)


def _ring_flash_fwd_impl(axis_name: str, block_q: int, block_k: int,
                         interpret: bool, q, k, v, kv_bias):
    """Forward ring pass where each hop's local attention runs the Pallas
    flash kernels (ops/flash_attention.py) instead of einsum — no per-hop
    O(Sq·Sk) score tensor even locally; per-hop partials merge by
    logsumexp. Non-causal only (the flash bias is key-side, which cannot
    express a q×k causal mask)."""
    from ray_shuffling_data_loader_tpu.ops import flash_attention as fa

    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_bias = kv_bias is not None
    o0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full(q.shape[:3], _ACC_MIN, jnp.float32)

    def step(i, carry):
        k_c, v_c, bias_c, o, lse = carry
        out_h, lse_h = fa.flash_forward(q, k_c, v_c, bias_c,
                                        block_q=block_q, block_k=block_k,
                                        interpret=interpret)
        lse_h = lse_h[..., 0]
        lse_new = jnp.logaddexp(lse, lse_h)
        # out_h is this hop's normalized partial; exp(lse_h - lse_new)
        # rescales it to the global softmax denominator.
        o = (o * jnp.exp(lse - lse_new)[..., None]
             + out_h.astype(jnp.float32)
             * jnp.exp(lse_h - lse_new)[..., None])
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        if has_bias:
            bias_c = jax.lax.ppermute(bias_c, axis_name, perm)
        return k_c, v_c, bias_c, o, lse_new

    bias0 = kv_bias.astype(jnp.float32) if has_bias else None
    _, _, _, o, lse = jax.lax.fori_loop(0, n, step, (k, v, bias0, o0, lse0))
    return o.astype(q.dtype), lse


def _ring_flash_bwd_impl(axis_name: str, block_q: int, block_k: int,
                         interpret: bool, q, k, v, kv_bias, out, lse, do):
    """Backward ring pass through the flash backward kernels. The GLOBAL
    lse makes each hop's recomputed weights the global softmax restricted
    to that hop's keys, so per-hop kernel grads sum exactly; dk/dv
    accumulators ride the ring home with their chunks."""
    from ray_shuffling_data_loader_tpu.ops import flash_attention as fa

    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_bias = kv_bias is not None

    def step(i, carry):
        k_c, v_c, bias_c, dk_c, dv_c, dbias_c, dq = carry
        dq_h, dk_h, dv_h, dbias_h = fa.flash_backward(
            q, k_c, v_c, bias_c, out, lse, do, block_q=block_q,
            block_k=block_k, interpret=interpret)
        dq = dq + dq_h.astype(jnp.float32)
        dk_c = dk_c + dk_h.astype(jnp.float32)
        dv_c = dv_c + dv_h.astype(jnp.float32)
        if has_bias:
            dbias_c = dbias_c + dbias_h.astype(jnp.float32)
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        dk_c = jax.lax.ppermute(dk_c, axis_name, perm)
        dv_c = jax.lax.ppermute(dv_c, axis_name, perm)
        if has_bias:
            bias_c = jax.lax.ppermute(bias_c, axis_name, perm)
            dbias_c = jax.lax.ppermute(dbias_c, axis_name, perm)
        return k_c, v_c, bias_c, dk_c, dv_c, dbias_c, dq

    bias0 = kv_bias.astype(jnp.float32) if has_bias else None
    dbias0 = (jnp.zeros((q.shape[0], 1, 1, k.shape[2]), jnp.float32)
              if has_bias else None)
    carry0 = (k, v, bias0, jnp.zeros(k.shape, jnp.float32),
              jnp.zeros(v.shape, jnp.float32), dbias0,
              jnp.zeros(q.shape, jnp.float32))
    _, _, _, dk, dv, dbias, dq = jax.lax.fori_loop(0, n, step, carry0)
    dbias_out = dbias.astype(kv_bias.dtype) if has_bias else None
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias_out)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _ring_flash_prim(axis_name: str, block_q: int, block_k: int,
                     interpret: bool, q, k, v, kv_bias):
    return _ring_flash_fwd_impl(axis_name, block_q, block_k, interpret,
                                q, k, v, kv_bias)[0]


def _ring_flash_prim_fwd(axis_name, block_q, block_k, interpret, q, k, v,
                         kv_bias):
    out, lse = _ring_flash_fwd_impl(axis_name, block_q, block_k, interpret,
                                    q, k, v, kv_bias)
    return out, (q, k, v, kv_bias, out, lse)


def _ring_flash_prim_bwd(axis_name, block_q, block_k, interpret, residuals,
                         do):
    q, k, v, kv_bias, out, lse = residuals
    return _ring_flash_bwd_impl(axis_name, block_q, block_k, interpret,
                                q, k, v, kv_bias, out, lse, do)


_ring_flash_prim.defvjp(_ring_flash_prim_fwd, _ring_flash_prim_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ring_attention_prim(axis_name: str, causal: bool, q, k, v, kv_bias):
    return _ring_attention_fwd_impl(axis_name, causal, q, k, v, kv_bias)[0]


def _ring_prim_fwd(axis_name, causal, q, k, v, kv_bias):
    out, lse = _ring_attention_fwd_impl(axis_name, causal, q, k, v, kv_bias)
    return out, (q, k, v, kv_bias, out, lse)


def _ring_prim_bwd(axis_name, causal, residuals, do):
    q, k, v, kv_bias, out, lse = residuals
    return _ring_attention_bwd_impl(axis_name, causal, q, k, v, kv_bias,
                                    out, lse, do)


_ring_attention_prim.defvjp(_ring_prim_fwd, _ring_prim_bwd)


def _ring_attention_shard(q, k, v, kv_bias, axis_name: str, causal: bool,
                          use_flash: bool = False,
                          block_q: Optional[int] = None,
                          block_k: Optional[int] = None,
                          interpret: bool = False):
    """Per-shard ring attention body; must run under shard_map/pmap.

    Block defaults come from flash_attention's DEFAULT_BLOCK_Q/K (one
    retuning site); the kernel clamps blocks to the per-hop shard
    length, so small S/world shards compile exactly as before.

    q/k/v: (B, H, S_local, D) — this device's sequence chunk. kv_bias:
    (B, 1, 1, S_local) additive key-side bias or None. K/V (+bias) rotate
    around ``axis_name``; the local chunk's global offset is recovered from
    the ring step, which is what makes the causal mask correct.

    Differentiable via a custom VJP that reruns the ring (recompute per
    hop) instead of letting AD save every per-step O(S²/n²) intermediate.
    With ``use_flash`` each hop runs the Pallas flash kernels, removing
    even the per-hop local score tensor (non-causal only).
    """
    if use_flash:
        from ray_shuffling_data_loader_tpu.ops import flash_attention as fa
        if block_q is None:
            block_q = fa.DEFAULT_BLOCK_Q
        if block_k is None:
            block_k = fa.DEFAULT_BLOCK_K
        return _ring_flash_prim(axis_name, block_q, block_k, interpret,
                                q, k, v, kv_bias)
    return _ring_attention_prim(axis_name, causal, q, k, v, kv_bias)


def _dispatch_sharded(shard_fn, q, k, v, bias, mesh: Mesh, seq_axis: str,
                      batch_axis: Optional[str]):
    """shard_map a per-shard attention body with the standard specs:
    q/k/v sequence-sharded on dim 2, bias (if any) on its key dim 3."""
    qkv_spec = P(batch_axis, None, seq_axis, None)
    bias_spec = P(batch_axis, None, None, seq_axis)
    if bias is None:
        fn = _shard_map(lambda q_, k_, v_: shard_fn(q_, k_, v_, None),
                        mesh=mesh, in_specs=(qkv_spec,) * 3,
                        out_specs=qkv_spec)
        return fn(q, k, v)
    fn = _shard_map(shard_fn, mesh=mesh,
                    in_specs=(qkv_spec,) * 3 + (bias_spec,),
                    out_specs=qkv_spec)
    return fn(q, k, v, bias)


def ring_self_attention(q: jax.Array,
                        k: jax.Array,
                        v: jax.Array,
                        mesh: Mesh,
                        seq_axis: str,
                        bias: Optional[jax.Array] = None,
                        batch_axis: Optional[str] = None,
                        causal: bool = False,
                        use_flash: Optional[bool] = None) -> jax.Array:
    """Exact attention over a sequence sharded on ``mesh[seq_axis]``.

    Args:
        q, k, v: (B, H, S, D) with S (globally) sharded over ``seq_axis``
            and optionally B over ``batch_axis``.
        bias: optional additive key-side bias (B, 1, 1, S) (e.g. padding
            mask as 0 / NEG_INF), sharded like the K sequence axis.
        causal: apply a causal mask using global positions.
        use_flash: run each hop's local attention through the Pallas flash
            kernels (no per-hop score tensor at all). ``None`` = auto: on
            for non-causal on a real TPU backend, off elsewhere. Explicit
            ``True`` off-TPU uses the (slow) Pallas interpreter — tests
            only. Causal + flash is rejected: the flash bias is key-side
            and cannot express a q×k causal mask.

    Returns (B, H, S, D), sharded like ``q``.
    """
    interpret = jax.default_backend() != "tpu"
    if use_flash is None:
        use_flash = not causal and not interpret
    if use_flash and causal:
        raise ValueError(
            "use_flash=True does not support causal=True (key-side bias "
            "cannot express the causal mask); use the einsum ring path")
    shard_fn = functools.partial(_ring_attention_shard, axis_name=seq_axis,
                                 causal=causal, use_flash=use_flash,
                                 interpret=interpret)
    return _dispatch_sharded(shard_fn, q, k, v, bias, mesh, seq_axis,
                             batch_axis)


def _full_attention(q, k, v, bias):
    """Plain full-sequence attention (f32 softmax), used per Ulysses shard
    and as the reference implementation in tests."""
    d = q.shape[-1]
    qf = q.astype(jnp.float32) * (1.0 / jnp.sqrt(d).astype(jnp.float32))
    scores = jnp.einsum("bhqd,bhkd->bhqk", qf, k.astype(jnp.float32))
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", weights,
                     v.astype(jnp.float32))
    return out.astype(q.dtype)


def _ulysses_shard(q, k, v, kv_bias, axis_name: str, causal: bool,
                   use_flash: bool = False, interpret: bool = False):
    """Per-shard Ulysses body: seq-sharded -> head-sharded -> back."""
    # (B, H, S/n, D) -> (B, H/n, S, D): scatter heads, gather sequence.
    q = jax.lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                           tiled=True)
    k = jax.lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                           tiled=True)
    v = jax.lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                           tiled=True)
    bias = None
    if kv_bias is not None:
        # Key-side bias has no head dim to scatter — gather the full-length
        # bias on every device instead.
        bias = jax.lax.all_gather(kv_bias, axis_name, axis=3, tiled=True)
    if use_flash and not causal:
        from ray_shuffling_data_loader_tpu.ops import flash_attention as fa
        out = fa.flash_attention(q, k, v, bias, interpret=interpret)
    else:
        if causal:
            pos = jnp.arange(q.shape[2])
            cb = causal_bias(pos, pos)
            bias = cb if bias is None else bias + cb
        out = _full_attention(q, k, v, bias)
    # (B, H/n, S, D) -> (B, H, S/n, D): back to sequence-sharded.
    return jax.lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def ulysses_attention(q: jax.Array,
                      k: jax.Array,
                      v: jax.Array,
                      mesh: Mesh,
                      seq_axis: str,
                      bias: Optional[jax.Array] = None,
                      batch_axis: Optional[str] = None,
                      causal: bool = False,
                      use_flash: Optional[bool] = None) -> jax.Array:
    """DeepSpeed-Ulysses-style all-to-all sequence parallelism.

    Same contract as :func:`ring_self_attention`; additionally requires the
    head count be divisible by the ``seq_axis`` size. ``use_flash`` runs
    the per-shard full-sequence attention through the Pallas flash kernels
    (non-causal only; ``None`` = auto-on for non-causal on real TPUs).
    """
    n = mesh.shape[seq_axis]
    if q.shape[1] % n != 0:
        raise ValueError(
            f"ulysses_attention needs num_heads ({q.shape[1]}) divisible by "
            f"mesh axis '{seq_axis}' size ({n})")
    interpret = jax.default_backend() != "tpu"
    if use_flash is None:
        use_flash = not causal and not interpret
    if use_flash and causal:
        raise ValueError(
            "use_flash=True does not support causal=True (key-side bias "
            "cannot express the causal mask)")
    shard_fn = functools.partial(_ulysses_shard, axis_name=seq_axis,
                                 causal=causal, use_flash=use_flash,
                                 interpret=interpret)
    return _dispatch_sharded(shard_fn, q, k, v, bias, mesh, seq_axis,
                             batch_axis)


def make_attention_fn(mesh: Mesh,
                      seq_axis: str,
                      strategy: str = "ring",
                      batch_axis: Optional[str] = None,
                      causal: bool = False,
                      use_flash: Optional[bool] = None):
    """An ``attention_fn(q, k, v, bias) -> out`` closure for models/bert.py's
    pluggable attention, bound to a mesh and strategy ("ring" | "ulysses").
    ``use_flash`` (ring only) routes each hop through the Pallas flash
    kernels — see :func:`ring_self_attention`."""
    if strategy == "ring":
        impl = functools.partial(ring_self_attention, use_flash=use_flash)
    elif strategy == "ulysses":
        impl = functools.partial(ulysses_attention, use_flash=use_flash)
    else:
        raise ValueError(f"unknown strategy {strategy!r}")

    def attention_fn(q, k, v, bias=None):
        return impl(q, k, v, mesh, seq_axis, bias=bias,
                    batch_axis=batch_axis, causal=causal)

    return attention_fn
