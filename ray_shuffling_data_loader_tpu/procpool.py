"""Process-pool data plane: worker subprocesses + shared-memory Arrow handoff.

The thread executor (executor.py) keeps the whole map/reduce hot path in
ONE Python process — pyarrow and the native kernels release the GIL, but
everything interpreter-bound (per-task Python bookkeeping, numpy fallback
arms, Parquet metadata churn) serializes on it, and BENCH_r05 measured the
stream producer-bound there. This module is the multicore plane behind the
same ``Executor`` contract (``submit`` / ``submit_once`` / ``wait`` /
``TaskRef``):

- N worker **subprocesses** (``multiprocessing`` spawn, supervisor-style
  respawn-on-death like the PR 5 queue server) each run map/reduce tasks
  end to end.
- Handoff is **zero-copy Arrow over shared memory**: a worker writes its
  output table as an Arrow IPC file into a tmpfs segment dir (``/dev/shm``
  by default) and sends back only the path; the driver, other workers, and
  the spill tier ``pa.memory_map`` the very buffers the worker wrote —
  tables never cross a pickle.
- Decoded-table segments double as the cross-epoch file cache (the
  process-backend analog of ``shuffle.FileTableCache``), budgeted by the
  ``executor_shm_bytes`` policy knob and charged to the process-wide
  buffer ledger (``native.buffer_ledger()``) like every other in-flight
  byte.
- Worker death is recovered from **lineage**: map/reduce payloads are pure
  functions of ``(seed, epoch, task)`` plus file paths, so the dispatcher
  resubmits the dead worker's task to a surviving worker (recorded as a
  lineage recompute in ``stats.fault_stats()``) and respawns the worker
  with bounded backoff.

Workers inherit the environment, so ``RSDL_CHAOS_SPEC`` chaos,
``RSDL_TELEMETRY`` and ``RSDL_TRACE_DIR`` all apply per worker: each
worker records its own ``map_read`` / ``reduce_gather`` spans and dumps
its flight recorder at exit, which is what lets ``tools/rsdl_trace.py``
merge a critical path spanning the driver plus every pool worker.
"""

from __future__ import annotations

import collections
import concurrent.futures as cf
import multiprocessing as mp
import os
import pickle
import tempfile
import threading
import timeit
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ray_shuffling_data_loader_tpu import executor as ex
from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
from ray_shuffling_data_loader_tpu.runtime import retry as rt_retry
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

# Worker respawn budget/backoff — supervisor-grade, not call-retry-grade
# (same reasoning as runtime/supervisor.py): a preempted host may lose
# several workers in one run.
rt_policy.register_defaults("procpool", retry_max_attempts=4,
                            retry_initial_backoff_s=0.1,
                            retry_max_backoff_s=2.0)

#: Per-task resubmission budget after a worker death (the task itself is a
#: pure lineage function, so a second execution is a recompute, not a
#: replay hazard).
_TASK_RESUBMITS = 2


class WorkerDied(RuntimeError):
    """A pool worker died while running the task and the resubmission
    budget is exhausted (or the task is one-shot)."""


class RemoteTaskError(RuntimeError):
    """A task raised in a worker and its exception could not be pickled
    back verbatim; carries the remote type name and traceback text."""


def shm_base_dir(override: Optional[str] = None) -> str:
    """Segment root: ``executor_shm_dir`` policy, else ``/dev/shm`` when
    writable (true shared memory), else the system temp dir (degrades to
    page-cache-backed mmap — still correct, still no pickling)."""
    configured = rt_policy.resolve("executor", "executor_shm_dir",
                                   override=override)
    if configured:
        return configured
    if os.path.isdir("/dev/shm") and os.access("/dev/shm", os.W_OK):
        return "/dev/shm"
    return tempfile.gettempdir()


def shm_available(override: Optional[str] = None) -> bool:
    base = shm_base_dir(override)
    return os.path.isdir(base) and os.access(base, os.W_OK)


def default_shm_bytes(base_dir: str) -> int:
    """Auto segment-cache budget: half the free bytes of the segment
    filesystem (decoded tables are the dominant resident)."""
    import shutil as _shutil
    try:
        return _shutil.disk_usage(base_dir).free // 2
    except OSError:
        return 1 << 30


def picklable(obj: Any) -> bool:
    if obj is None:
        return True
    try:
        pickle.dumps(obj)
        return True
    except Exception:  # noqa: BLE001 - any failure means "can't ship it"
        return False


def resolve_backend(override: Optional[str] = None,
                    num_workers: Optional[int] = None,
                    transforms: Sequence[Any] = ()) -> str:
    """``thread`` | ``process`` for a driver that owns its pool.

    ``auto`` picks the process backend when it can actually help and
    actually work: more than one host core, a writable shared-memory dir,
    every workload hook picklable (they must cross to the workers), and no
    *programmatic* chaos injector active (an env-spec chaos reproduces in
    the workers by construction; an ``install()``-ed one lives only in the
    driver process and would silently stop firing).
    """
    backend = rt_policy.resolve("executor", "executor_backend",
                                override=override)
    if backend not in ("thread", "process", "auto"):
        raise ValueError(
            f"executor_backend must be thread|process|auto, got {backend!r}")
    if backend == "auto":
        from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
        cores = os.cpu_count() or 1
        workers = num_workers if num_workers else cores
        programmatic_chaos = (
            rt_faults.active()
            and not any(os.environ.get(name, "").strip()
                        for name in rt_faults._SPEC_ENVS))
        if (cores > 1 and workers > 1 and shm_available()
                and not programmatic_chaos):
            backend = "process"
        else:
            backend = "thread"
    if backend == "process" and not shm_available():
        logger.warning("executor_backend=process but no writable shm/temp "
                       "dir; falling back to the thread backend")
        backend = "thread"
    if backend == "process" and not all(picklable(t) for t in transforms):
        # Applies to EXPLICIT process selection too: a closure transform
        # cannot cross to the workers, and failing the whole shuffle over
        # an env var would be worse than the thread pool it replaces.
        logger.warning("executor_backend=process but a map/reduce "
                       "transform is not picklable; falling back to the "
                       "thread backend")
        backend = "thread"
    return backend


# ---------------------------------------------------------------------------
# Segment I/O (shared by driver and workers)
# ---------------------------------------------------------------------------


def write_table_segment(table, path: str) -> int:
    """Write ``table`` as an Arrow IPC file at ``path`` (tmp + atomic
    rename so a dying writer never leaves a torn segment under the final
    name). Returns the on-disk byte size."""
    import pyarrow as pa
    tmp = f"{path}.{os.getpid()}.tmp"
    with pa.OSFile(tmp, "wb") as sink:
        with pa.ipc.new_file(sink, table.schema) as writer:
            writer.write_table(table)
    os.replace(tmp, path)
    return os.stat(path).st_size


def open_table_segment(path: str):
    """Memory-map an IPC segment back as a table — zero-copy: the Arrow
    buffers ARE the shm pages the writer produced."""
    import pyarrow as pa
    with pa.memory_map(path) as source:
        return pa.ipc.open_file(source).read_all()


def write_buffer_segment(buf, path: str) -> int:
    """Write an already-serialized buffer (e.g. an Arrow IPC stream from
    ``pa.BufferOutputStream``) at ``path`` with the same tmp + atomic
    rename discipline as :func:`write_table_segment`. Returns the byte
    size. The queue serving plane uses this for shm-handle delivery: the
    one serialization the v2 wire already paid becomes the segment the
    consumer mmaps, and no byte ever rides the socket."""
    import pyarrow as pa
    tmp = f"{path}.{os.getpid()}.tmp"
    with pa.OSFile(tmp, "wb") as sink:
        sink.write(buf)
    os.replace(tmp, path)
    return os.stat(path).st_size


def read_segment_buffer(path: str):
    """Memory-map a segment back as one zero-copy ``pa.Buffer`` (the raw
    bytes, not a decoded table): CRC verification and Arrow IPC decode
    both read straight off the mapped pages."""
    import pyarrow as pa
    with pa.memory_map(path) as source:
        return source.read_buffer()


def pin_segment(nbytes: int) -> int:
    """Charge a segment's bytes to the process-wide buffer ledger
    (``native.buffer_ledger()``) on behalf of an EXTERNAL consumer — the
    queue server pins each unacked handle frame's segment so the budget
    machinery sees replay-held shm like any other in-flight byte.
    Returns the ledger id for :func:`release_segment`."""
    from ray_shuffling_data_loader_tpu import native
    return native.buffer_ledger().register(nbytes)


def release_segment(ledger_id: Optional[int], path: Optional[str] = None,
                    unlink: bool = False) -> None:
    """Release a :func:`pin_segment` lease (idempotent) and optionally
    unlink the segment file — consumers that already mmap'd it keep
    their mapping (POSIX unlink semantics), so acked frames free the
    name immediately without racing a slow reader."""
    from ray_shuffling_data_loader_tpu import native
    if ledger_id is not None:
        try:
            native.buffer_ledger().decref(ledger_id)
        except KeyError:
            pass
    if unlink and path:
        _unlink_quiet(path)


def write_index_segment(path: str, offsets: np.ndarray,
                        flat: np.ndarray) -> int:
    """Partition-plan segment: int64 header ``[num_reducers, num_rows]``
    then offsets then the flat row-index array."""
    header = np.array([len(offsets) - 1, len(flat)], dtype=np.int64)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(header.tobytes())
        f.write(np.ascontiguousarray(offsets, dtype=np.int64).tobytes())
        f.write(np.ascontiguousarray(flat, dtype=np.int64).tobytes())
    os.replace(tmp, path)
    return os.stat(path).st_size


def read_index_segment(path: str) -> "tuple[np.ndarray, np.ndarray]":
    """``(offsets, flat)`` views of an index segment (mmap-backed)."""
    raw = np.memmap(path, dtype=np.int64, mode="r")
    num_reducers, num_rows = int(raw[0]), int(raw[1])
    offsets = raw[2:3 + num_reducers]
    flat = raw[3 + num_reducers:3 + num_reducers + num_rows]
    return offsets, flat


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


# ---------------------------------------------------------------------------
# Worker process
# ---------------------------------------------------------------------------

#: Worker-local mmap cache of decoded-table segments: every reducer of
#: every epoch gathers from the same file segments, so re-opening per task
#: would re-pay Arrow IPC footer parsing num_reducers times per epoch.
_seg_table_cache: Dict[str, Any] = {}


def _cached_segment_table(path: str):
    table = _seg_table_cache.get(path)
    if table is None:
        table = _seg_table_cache[path] = open_table_segment(path)
    return table


def _load_blob(blob: Optional[bytes]):
    return None if blob is None else pickle.loads(blob)


def _worker_task_map(payload: dict) -> dict:
    """Map task body: decode (or mmap the cached segment), optionally
    publish the decoded table as a new cache segment, run the fused
    partition plan, and write the plan as an index segment. Fault/retry/
    quarantine semantics mirror ``shuffle.shuffle_map`` exactly."""
    import importlib
    import pyarrow as pa
    sh = importlib.import_module("ray_shuffling_data_loader_tpu.shuffle")
    from ray_shuffling_data_loader_tpu.ops import partition as ops_p
    from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults

    filename = payload["filename"]
    epoch, file_index = payload["epoch"], payload["file_index"]
    seed = payload["seed"]
    rt_telemetry.set_trace_seed(seed)
    start = timeit.default_timer()
    table = None
    table_seg = payload.get("table_seg")
    wrote_table_bytes = 0
    cached = False
    if table_seg is not None:
        try:
            table = _cached_segment_table(table_seg)
            cached = True
        except (OSError, pa.ArrowInvalid) as e:
            logger.warning("table segment %s unreadable (%s); re-decoding",
                           table_seg, e)
            _seg_table_cache.pop(table_seg, None)
            table = None
            table_seg = None
    grouped = False
    grouped_offsets = None
    if table is None:
        import functools
        read_retry = rt_retry.RetryPolicy.for_component(
            "map_read", retryable=sh._transient_read_retryable)
        map_transform = _load_blob(payload.get("map_transform"))
        streamed = None
        tried_fused = False
        # Streaming fast path — epoch-scoped segments only: a cross-epoch
        # cache grant must publish the DECODED table (the grouped layout
        # depends on (seed, epoch), so it cannot be reused next epoch).
        if not payload.get("cache_grant") and sh._fused_pipeline_enabled():
            tried_fused = True
            rt_faults.inject("map_read", epoch=epoch, task=file_index)
            fused_fn = functools.partial(
                sh._fused_stream_columns, filename,
                payload["num_reducers"], seed, epoch, file_index,
                map_transform)
            try:
                streamed = read_retry.call(
                    fused_fn, describe=f"stream {filename}")
            except (OSError, pa.ArrowInvalid) as e:
                if payload.get("on_bad_file") != "skip":
                    raise
                return {"quarantined": rt_faults.QuarantinedFile(
                    filename=filename, epoch=epoch, file_index=file_index,
                    error=f"{type(e).__name__}: {e}")}
        if streamed is not None:
            out_cols, grouped_offsets, names = streamed
            # The segment IS the grouped layout: reducer r's rows are the
            # contiguous slice [offsets[r], offsets[r+1]) in original row
            # order, so the reduce stage slices instead of gathering —
            # bit-identical rows either way (same stable order).
            table = pa.table({name: out_cols[name] for name in names})
            grouped = True
        else:
            try:
                table = sh._read_map_table(filename, epoch, file_index,
                                           read_retry,
                                           inject=not tried_fused)
            except (OSError, pa.ArrowInvalid) as e:
                if payload.get("on_bad_file") != "skip":
                    raise
                return {"quarantined": rt_faults.QuarantinedFile(
                    filename=filename, epoch=epoch, file_index=file_index,
                    error=f"{type(e).__name__}: {e}")}
            if map_transform is not None:
                table = map_transform(table)
            # Single-chunk columns => zero-copy numpy views for every
            # reducer that maps this segment (same invariant as the
            # thread-mode cache).
            table = table.combine_chunks()
        # The reducers gather from the SEGMENT, so the decoded table must
        # always be published — either into the cross-epoch cache slot the
        # driver granted, or into an epoch-scoped segment the driver
        # unlinks when the epoch's reduces finish. A write failure is a
        # task failure (there is nothing for the reduce stage to read).
        write_seg = payload.get("write_table_seg") or \
            f"{payload['idx_seg']}.table.arrow"
        wrote_table_bytes = write_table_segment(table, write_seg)
        cached = bool(payload.get("cache_grant")) and \
            write_seg == payload.get("write_table_seg")
        if cached:
            _seg_table_cache[write_seg] = table
        table_seg = write_seg
    end_read = timeit.default_timer()
    rt_telemetry.record("map_read", epoch=epoch, task=file_index,
                        dur_s=end_read - start)
    if grouped:
        # The stream already placed every row; the index segment carries
        # only the region offsets (empty flat array).
        idx_bytes = write_index_segment(payload["idx_seg"], grouped_offsets,
                                        np.empty(0, dtype=np.int64))
    else:
        flat, offsets = ops_p.plan_partition_flat(
            table.num_rows, payload["num_reducers"], seed, epoch,
            file_index, nthreads=payload.get("plan_threads") or 1)
        idx_bytes = write_index_segment(payload["idx_seg"], offsets, flat)
    return {
        "num_rows": table.num_rows,
        "table_seg": table_seg,
        "cached": cached,
        "grouped": grouped,
        "wrote_table_bytes": wrote_table_bytes,
        "idx_seg": payload["idx_seg"],
        "idx_bytes": idx_bytes,
        "read_s": end_read - start,
        "dur_s": timeit.default_timer() - start,
    }


def _worker_task_reduce(payload: dict) -> dict:
    """Reduce task body: gather this reducer's rows from every map
    segment with the SAME fused kernel path as the thread backend
    (``shuffle.shuffle_reduce`` over lazy chunks), then publish the output
    as a fresh segment. Bit-identity with the thread backend is
    structural: same plan segments, same permutation RNG, same gather."""
    import importlib
    sh = importlib.import_module("ray_shuffling_data_loader_tpu.shuffle")
    from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults

    reduce_index = payload["reduce_index"]
    epoch, seed = payload["epoch"], payload["seed"]
    rt_telemetry.set_trace_seed(seed)
    start = timeit.default_timer()
    reduce_transform = _load_blob(payload.get("reduce_transform"))

    def _gather_and_shuffle():
        with rt_telemetry.span("reduce_gather", epoch=epoch,
                               task=reduce_index):
            rt_faults.inject("reduce_gather", epoch=epoch,
                             task=reduce_index)
            chunks = []
            for source in payload["sources"]:
                table_seg, idx_seg, cacheable = source[:3]
                grouped = len(source) > 3 and bool(source[3])
                # Epoch-scoped segments are unlinked when the epoch
                # drains; caching them in the worker would pin the pages
                # past that, so only cross-epoch cache segments persist.
                table = (_cached_segment_table(table_seg) if cacheable
                         else open_table_segment(table_seg))
                offsets, flat = read_index_segment(idx_seg)
                if grouped:
                    # Streaming-pipeline segment: rows already grouped by
                    # reducer in original row order — a zero-copy slice
                    # replaces the gather, bit-identically.
                    lo = int(offsets[reduce_index])
                    hi = int(offsets[reduce_index + 1])
                    chunks.append(table.slice(lo, hi - lo))
                else:
                    idx = np.asarray(
                        flat[offsets[reduce_index]:
                             offsets[reduce_index + 1]])
                    chunks.append(sh.MapShard(table, [idx])[0])
            return sh.shuffle_reduce(reduce_index, seed, epoch, chunks,
                                     None, reduce_transform,
                                     payload.get("gather_threads"))

    retry = rt_retry.RetryPolicy.for_component("reduce")
    shuffled = retry.call(_gather_and_shuffle,
                          describe=f"reduce e{epoch} r{reduce_index}")
    out_seg = payload["out_seg"]
    nbytes = write_table_segment(shuffled, out_seg)
    return {
        "out_seg": out_seg,
        "num_rows": shuffled.num_rows,
        "nbytes": nbytes,
        "dur_s": timeit.default_timer() - start,
    }


def _worker_task_call(payload: dict) -> Any:
    fn, args, kwargs = pickle.loads(payload["blob"])
    return fn(*args, **kwargs)


def _worker_task_ping(payload: dict) -> dict:
    return {"pid": os.getpid(), "worker_index": payload.get("worker_index")}


def _run_worker_task(kind: str, payload: dict) -> Any:
    """Dispatch one task body; speculative backup attempts (plan
    scheduler first-completion-wins duplicates, ``payload["attempt"]``)
    run under ``telemetry.speculative()`` so their recorder events carry
    the ``spec`` attr and never double-count in trace merge or
    attribution."""
    handler = _TASK_HANDLERS[kind]
    attempt = payload.get("attempt", 0) if isinstance(payload, dict) else 0
    if attempt:
        with rt_telemetry.speculative(attempt):
            return handler(payload)
    return handler(payload)


_TASK_HANDLERS: Dict[str, Callable[[dict], Any]] = {
    "map": _worker_task_map,
    "reduce": _worker_task_reduce,
    "call": _worker_task_call,
    "ping": _worker_task_ping,
}


def _worker_main(conn, worker_index: int) -> None:
    """Worker loop: one task at a time off the duplex pipe.

    SIGTERM converts to SystemExit (same pattern as the supervised queue
    server's ``_serve_main``) so atexit hooks — notably the
    ``RSDL_TRACE_DIR`` flight-recorder dump — run even when the driver
    tears the pool down with terminate().
    """
    import signal as _signal

    def _on_sigterm(signum, frame):
        raise SystemExit(0)

    try:
        _signal.signal(_signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass
    threading.current_thread().name = f"rsdl-proc-worker-{worker_index}"
    # The worker owns host CPU work only; it must never initialize (or
    # wait on) an accelerator the driver owns.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # Ops-plane federation (inherited through the spawn env like
    # RSDL_CHAOS_SPEC): the worker writes its per-pid metrics shard
    # under RSDL_TELEMETRY_DIR so the driver's merged exposition counts
    # the processes doing the work, and answers the incident capture's
    # SIGUSR1 with a flight-recorder dump into RSDL_TRACE_DIR.
    rt_telemetry.install_signal_dump()
    rt_metrics.maybe_start_shard_writer()
    tasks_done = rt_metrics.counter(
        "rsdl_worker_tasks_total",
        "tasks completed inside pool worker processes",
        worker=str(worker_index))
    # Service loop, not a retry: exits on pipe EOF (driver gone) or the
    # explicit shutdown sentinel. rsdl-lint: disable=unbounded-retry
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            break
        if msg is None:
            break
        task_id, kind, payload = msg
        try:
            result = _run_worker_task(kind, payload)
            reply = (task_id, True, result)
            tasks_done.inc()
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 - shipped to the driver
            import traceback as _tb
            try:
                pickle.dumps(e)
                err: Any = e
            except Exception:  # noqa: BLE001 - unpicklable exception
                err = RemoteTaskError(
                    f"{type(e).__name__}: {e}\n{_tb.format_exc()}")
            reply = (task_id, False, err)
        try:
            conn.send(reply)
        except (BrokenPipeError, OSError):
            break


# ---------------------------------------------------------------------------
# Driver side
# ---------------------------------------------------------------------------


class ProcTaskRef(ex.TaskRef):
    """TaskRef whose ``result()`` optionally applies a driver-side
    transform to the worker's raw reply (e.g. mmap a reduce-output
    segment into a table) — applied once, cached, thread-safe."""

    __slots__ = ("_transform", "_final", "_final_error", "_final_lock",
                 "_finalized")

    def __init__(self, future: cf.Future, transform=None):
        super().__init__(future)
        self._transform = transform
        self._final = None
        self._final_error: Optional[BaseException] = None
        # Runs the caller-supplied transform while held (exactly-once
        # with side effects: ledger decrefs, segment unlinks), so
        # locksan records edges into whatever locks that opaque
        # callable takes — edges the static pass cannot resolve. Safe:
        # this is a per-future leaf lock no transform can reach back
        # into, so it cannot close a cycle.
        # rsdl-lint: disable=inconsistent-lock-order
        self._final_lock = threading.Lock()
        self._finalized = False

    def result(self, timeout: Optional[float] = None) -> Any:
        raw = self._future.result(timeout)
        if self._transform is None:
            return raw
        with self._final_lock:
            if not self._finalized:
                try:
                    self._final = self._transform(raw)
                except BaseException as e:  # noqa: BLE001 - replayed below
                    self._final_error = e
                self._finalized = True
            if self._final_error is not None:
                raise self._final_error
            return self._final


class _Task:
    __slots__ = ("id", "kind", "payload", "future", "retryable", "attempts",
                 "affinity")

    def __init__(self, task_id: int, kind: str, payload: dict,
                 retryable: bool, affinity: Optional[int]):
        self.id = task_id
        self.kind = kind
        self.payload = payload
        self.future: cf.Future = cf.Future()
        self.retryable = retryable
        self.attempts = 0
        self.affinity = affinity


class _Worker:
    __slots__ = ("proc", "conn", "index", "restarts")

    def __init__(self, proc, conn, index: int):
        self.proc = proc
        self.conn = conn
        self.index = index
        self.restarts = 0


class ProcessPoolExecutor:
    """Per-host process-pool executor (the multicore data plane).

    Satisfies the ``executor.Executor`` contract — ``submit`` /
    ``submit_once`` return :class:`ex.TaskRef`-compatible refs that
    ``executor.wait`` / ``executor.get`` accept unchanged — plus the
    shuffle-specific ``submit_kind`` used by the process-mode epoch path
    (procpool.process_epoch). Generic ``submit`` pickles ``(fn, args,
    kwargs)``, so only module-level callables travel; the shuffle path
    never ships closures, only segment paths and lineage integers.
    """

    backend = "process"

    def __init__(self, num_workers: Optional[int] = None,
                 shm_dir: Optional[str] = None,
                 shm_bytes: Optional[int] = None,
                 name: str = "rsdl-procpool",
                 task_retries: int = 0):
        if num_workers is None:
            num_workers = rt_policy.resolve("executor", "executor_workers")
        if not num_workers:
            num_workers = os.cpu_count() or 1
        self._num_workers = max(1, int(num_workers))
        self._name = name
        base = shm_base_dir(shm_dir)
        os.makedirs(base, exist_ok=True)
        self.segment_dir = tempfile.mkdtemp(prefix="rsdl-pool-", dir=base)
        budget = rt_policy.resolve("executor", "executor_shm_bytes",
                                   override=shm_bytes)
        self.shm_bytes = budget if budget else default_shm_bytes(base)
        # task_retries parity with the thread executor: pure tasks may be
        # re-run after a worker death; the budget below is per task.
        self._task_resubmits = max(_TASK_RESUBMITS, task_retries)
        self._ctx = mp.get_context("spawn")
        self._lock = threading.Condition()
        self._next_task_id = 1
        self._global_q: "collections.deque[_Task]" = collections.deque()
        self._affinity_q: List["collections.deque[_Task]"] = [
            collections.deque() for _ in range(self._num_workers)]
        self._shutdown = False
        self._wait_for_tasks = True
        self._alive_dispatchers = self._num_workers
        restart_policy = rt_retry.RetryPolicy.for_component("procpool")
        self._max_restarts = restart_policy.max_attempts
        self._backoffs = restart_policy.backoffs()
        # Segment-cache registry (driver-authoritative): filename ->
        # (segment path, bytes). Charged to the buffer ledger below.
        self._table_segs: Dict[str, "tuple[str, int]"] = {}
        self._table_seg_inflight: set = set()
        self._table_seg_bytes = 0
        self._cache_full = False
        self._ledger_ids: List[int] = []
        rt_metrics.gauge("rsdl_executor_workers",
                         "pool width by pool name",
                         pool=name).set(self._num_workers)
        self._tasks_submitted = rt_metrics.counter(
            "rsdl_executor_tasks_total", "tasks submitted by pool name",
            pool=name)
        self._worker_restarts = rt_metrics.counter(
            "rsdl_pool_worker_restarts_total",
            "pool worker processes respawned after death", pool=name)
        self._workers: List[_Worker] = [
            self._spawn_worker(i) for i in range(self._num_workers)]
        self._dispatchers = [
            threading.Thread(target=self._dispatch_loop, args=(i,),
                             name=f"{name}-dispatch-{i}", daemon=True)
            for i in range(self._num_workers)]
        for t in self._dispatchers:
            t.start()
        ex.note_worker_pool("process", self._num_workers,
                            self.worker_pids())
        self._publish_worker_pids()

    # -- Executor contract ---------------------------------------------

    @property
    def num_workers(self) -> int:
        return self._num_workers

    def worker_pids(self) -> List[int]:
        return [w.proc.pid for w in self._workers
                if w.proc is not None and w.proc.pid is not None]

    def _publish_worker_pids(self) -> None:
        """Per-pid pool membership for the ops plane: rsdl_top's
        per-process view marks these pids as pool workers, and the
        incident capture signals them for trace dumps."""
        for pid in self.worker_pids():
            rt_metrics.gauge("rsdl_executor_worker_up",
                             "1 while the pid is a live pool worker",
                             pool=self._name, pid=str(pid)).set(1)

    def submit(self, fn: Callable, *args, **kwargs) -> ProcTaskRef:
        blob = pickle.dumps((fn, args, kwargs))
        return self.submit_kind("call", {"blob": blob}, retryable=True)

    def submit_once(self, fn: Callable, *args, **kwargs) -> ProcTaskRef:
        blob = pickle.dumps((fn, args, kwargs))
        return self.submit_kind("call", {"blob": blob}, retryable=False)

    def map(self, fn: Callable, items: Sequence) -> List[ProcTaskRef]:
        return [self.submit(fn, item) for item in items]

    def submit_kind(self, kind: str, payload: dict,
                    affinity: Optional[int] = None,
                    transform=None, retryable: bool = True) -> ProcTaskRef:
        with self._lock:
            if self._shutdown:
                raise RuntimeError("executor is shut down")
            task = _Task(self._next_task_id, kind, payload, retryable,
                         affinity)
            self._next_task_id += 1
            if affinity is not None:
                self._affinity_q[affinity % self._num_workers].append(task)
            else:
                self._global_q.append(task)
            self._lock.notify_all()
        self._tasks_submitted.inc()
        return ProcTaskRef(task.future, transform)

    def shutdown(self, wait_for_tasks: bool = True) -> None:
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            self._wait_for_tasks = wait_for_tasks
            self._lock.notify_all()
        if not wait_for_tasks:
            for worker in self._workers:
                if worker.proc is not None and worker.proc.is_alive():
                    worker.proc.terminate()
        for t in self._dispatchers:
            t.join(timeout=60.0)
        for worker in self._workers:
            self._stop_worker(worker)
        self._release_segments()

    def __enter__(self) -> "ProcessPoolExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- Segment cache (decoded tables, cross-epoch) -------------------

    @property
    def bytes_cached(self) -> int:
        """Resident bytes of the decoded-table segment cache — the budget
        machinery (spill.make_budget_state) discounts cache growth from
        the transient-byte ledger, same duck-typed surface as
        shuffle.FileTableCache."""
        with self._lock:
            return self._table_seg_bytes

    def segment_path(self, stem: str) -> str:
        return os.path.join(self.segment_dir, stem)

    def cached_table_seg(self, filename: str) -> Optional[str]:
        with self._lock:
            entry = self._table_segs.get(filename)
            return entry[0] if entry else None

    def plan_table_seg_write(self, filename: str, file_index: int
                             ) -> Optional[str]:
        """Decide (driver-authoritative, so concurrent epochs cannot race)
        whether this map task should publish the decoded table as a cache
        segment; returns the target path or None."""
        with self._lock:
            if (self._cache_full or filename in self._table_segs
                    or filename in self._table_seg_inflight):
                return None
            self._table_seg_inflight.add(filename)
        return self.segment_path(f"table_f{file_index}.arrow")

    def note_table_seg(self, filename: str, path: Optional[str],
                       nbytes: int) -> None:
        """Record a map task's cache-segment outcome and charge the
        ledger; past the byte budget the cache stops growing (files keep
        re-decoding — same degradation as DiskTableCache)."""
        from ray_shuffling_data_loader_tpu import native
        with self._lock:
            self._table_seg_inflight.discard(filename)
            if not path or not nbytes:
                return
            if filename in self._table_segs:
                return
            self._table_segs[filename] = (path, nbytes)
            self._table_seg_bytes += nbytes
            if self._table_seg_bytes >= self.shm_bytes:
                self._cache_full = True
        # Register outside the pool condition (the ledger has its own
        # lock), but the id list is shared with _release_segments on
        # the shutdown path, so the append itself goes back under it.
        buf_id = native.buffer_ledger().register(nbytes)
        with self._lock:
            self._ledger_ids.append(buf_id)

    def _release_segments(self) -> None:
        from ray_shuffling_data_loader_tpu import native
        import shutil as _shutil
        ledger = native.buffer_ledger()
        with self._lock:
            ledger_ids, self._ledger_ids = self._ledger_ids, []
        for buf_id in ledger_ids:
            try:
                ledger.decref(buf_id)
            except KeyError:
                pass
        _shutil.rmtree(self.segment_dir, ignore_errors=True)

    # -- Worker lifecycle ----------------------------------------------

    def _spawn_worker(self, index: int) -> _Worker:
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        proc = self._ctx.Process(
            target=_worker_main, args=(child_conn, index),
            name=f"{self._name}-worker-{index}", daemon=True)
        proc.start()
        child_conn.close()
        return _Worker(proc, parent_conn, index)

    def _stop_worker(self, worker: _Worker, timeout_s: float = 5.0) -> None:
        if worker.proc is None:
            return
        try:
            if worker.proc.is_alive():
                try:
                    worker.conn.send(None)
                except (BrokenPipeError, OSError):
                    pass
                worker.proc.join(timeout=timeout_s)
            if worker.proc.is_alive():
                worker.proc.terminate()
                worker.proc.join(timeout=timeout_s)
            if worker.proc.is_alive():
                worker.proc.kill()
                worker.proc.join(timeout=timeout_s)
        finally:
            try:
                worker.conn.close()
            except OSError:
                pass

    def _next_task(self, index: int) -> Optional[_Task]:
        """Own affinity queue first (segment warmth), then the global
        queue, then steal from the longest sibling queue — affinity is a
        hint, so stealing is always safe."""
        with self._lock:
            # Condition-wait loop, not a retry: exits on shutdown, and
            # every pass either pops work or blocks on the condition.
            # rsdl-lint: disable=unbounded-retry
            while True:
                queue = self._affinity_q[index]
                if not queue and self._global_q:
                    queue = self._global_q
                if not queue:
                    siblings = [q for q in self._affinity_q if q]
                    if siblings:
                        queue = max(siblings, key=len)
                if queue:
                    return queue.popleft()
                if self._shutdown:
                    return None
                self._lock.wait(timeout=0.2)

    def _complete(self, task: _Task, ok: bool, result: Any) -> None:
        try:
            if ok:
                task.future.set_result(result)
            else:
                task.future.set_exception(result)
        except cf.InvalidStateError:
            pass  # cancelled ref

    def _handle_worker_death(self, index: int, task: Optional[_Task]
                             ) -> bool:
        """Respawn the dead worker (bounded backoff) and resubmit the
        in-flight task from lineage. Returns False when the respawn
        budget is exhausted (the dispatcher slot retires)."""
        from ray_shuffling_data_loader_tpu import stats as stats_mod
        worker = self._workers[index]
        exitcode = worker.proc.exitcode if worker.proc else None
        rt_telemetry.record("pool_worker_crash", rc=exitcode, worker=index)
        self._worker_restarts.inc()
        if task is not None:
            task.attempts += 1
            if task.retryable and task.attempts <= self._task_resubmits:
                logger.warning(
                    "%s: worker %d died (rc=%s) running %s task %d; "
                    "resubmitting from lineage (attempt %d)", self._name,
                    index, exitcode, task.kind, task.id, task.attempts)
                stats_mod.fault_stats().record_recompute("lineage", 0.0)
                with self._lock:
                    self._global_q.appendleft(task)
                    self._lock.notify_all()
            else:
                self._complete(task, False, WorkerDied(
                    f"pool worker {index} died (exitcode {exitcode}) "
                    f"running {task.kind} task {task.id}"))
        worker.restarts += 1
        if worker.restarts >= self._max_restarts:
            logger.error(
                "%s: worker %d restart budget (%d) exhausted; retiring "
                "the slot", self._name, index, self._max_restarts)
            return False
        with self._lock:
            # The backoff generator is shared by every dispatcher slot;
            # generators are not re-entrant, so draw under the lock.
            pause = next(self._backoffs)
        logger.error("%s: worker %d died (rc=%s); respawning in %.2fs "
                     "(%d/%d)", self._name, index, exitcode,
                     pause, worker.restarts, self._max_restarts - 1)
        with self._lock:
            if self._shutdown:
                return False
        import time as _time
        _time.sleep(pause)
        try:
            worker.conn.close()
        except OSError:
            pass
        dead_pid = worker.proc.pid if worker.proc is not None else None
        replacement = self._spawn_worker(index)
        replacement.restarts = worker.restarts
        self._workers[index] = replacement
        ex.note_worker_pool("process", self._num_workers,
                            self.worker_pids())
        if dead_pid is not None:
            rt_metrics.gauge("rsdl_executor_worker_up",
                             "1 while the pid is a live pool worker",
                             pool=self._name, pid=str(dead_pid)).set(0)
        self._publish_worker_pids()
        return True

    def _dispatch_loop(self, index: int) -> None:
        try:
            # Service loop: exits via the shutdown sentinel from
            # _next_task or a retired respawn budget — each death path is
            # itself bounded. rsdl-lint: disable=unbounded-retry
            while True:
                task = self._next_task(index)
                if task is None:
                    return
                if task.future.cancelled():
                    continue
                worker = self._workers[index]
                try:
                    worker.conn.send((task.id, task.kind, task.payload))
                    reply = worker.conn.recv()
                except (EOFError, OSError):
                    with self._lock:
                        dying = self._shutdown and not self._wait_for_tasks
                    if dying:
                        self._complete(task, False, WorkerDied(
                            "pool shut down while the task was in flight"))
                        return
                    if not self._handle_worker_death(index, task):
                        return
                    continue
                task_id, ok, result = reply
                assert task_id == task.id, (task_id, task.id)
                self._complete(task, ok, result)
        finally:
            self._retire_dispatcher(index)

    def _retire_dispatcher(self, index: int) -> None:
        with self._lock:
            self._alive_dispatchers -= 1
            # Orphaned affinity work must not starve: spill it to the
            # global queue for surviving slots.
            while self._affinity_q[index]:
                self._global_q.append(self._affinity_q[index].popleft())
            last = self._alive_dispatchers == 0
            self._lock.notify_all()
        if last:
            # Every slot retired (crash storm, or a no-wait shutdown with
            # work still queued): fail what's queued so callers see
            # WorkerDied instead of hanging on a future nobody will
            # resolve. Bounded by the queue length (each pass pops one
            # task, submit refuses after shutdown).
            # rsdl-lint: disable=unbounded-retry
            while True:
                with self._lock:
                    if not self._global_q:
                        break
                    task = self._global_q.popleft()
                self._complete(task, False, WorkerDied(
                    "pool retired before the task ran (worker restart "
                    "budget exhausted, or no-wait shutdown)"))


# ---------------------------------------------------------------------------
# Process-mode shuffle epoch (driver side)
# ---------------------------------------------------------------------------


def process_epoch(plan,
                  pool: ProcessPoolExecutor,
                  stats_collector=None,
                  map_transform_blob: Optional[bytes] = None,
                  reduce_transform_blob: Optional[bytes] = None,
                  spill_manager=None,
                  gather_threads: Optional[int] = None,
                  on_bad_file: str = "raise",
                  spill_recompute_factory=None) -> List[ProcTaskRef]:
    """Execute one epoch's :class:`plan.ir.EpochPlan` on the process
    pool; returns reducer refs whose ``result()`` is a driver-mmap'd
    (then accounted / possibly spilled / trace-stamped) table — the same
    contract as the thread-mode ``_reduce_task`` refs.

    The plan scheduler drives dispatch: map nodes go out with file
    affinity (segment warmth — locality-aware placement), reduce nodes
    dispatch only after the ``map`` stage barrier collects every map's
    segment results on the scheduler's driver thread (never on a pool
    dispatcher thread, which a blocking collect could deadlock). A map
    task that fails even after the pool's worker-death resubmission is
    re-run once more from lineage inside that barrier; only exhausted
    recovery propagates (thread-mode ``EpochLineage`` semantics).
    Speculative backup attempts (``RSDL_PLAN_SPECULATION``) re-run the
    same lineage payload on another worker under ATTEMPT-SCOPED segment
    paths (``…a1.idx``): a cache-granted primary writes a flat
    ``(offsets, flat)`` index while an ungranted backup streams the
    grouped layout, so the two attempts' bytes are NOT identical and a
    shared path would let the loser's atomic rewrite silently mismatch
    the winner's ``grouped`` flag in ``sources`` (an empty flat array
    read as a gather index drops the whole file's rows). With per-attempt
    paths first-completion-wins is safe: the winner's ``res`` carries its
    own paths into ``sources``, and the loser's files are reaped at epoch
    drain (or by pool teardown if the loser finishes after the drain).
    """
    import importlib
    sh = importlib.import_module("ray_shuffling_data_loader_tpu.shuffle")
    from ray_shuffling_data_loader_tpu import stats as stats_mod
    from ray_shuffling_data_loader_tpu.plan import (
        scheduler as plan_scheduler)

    epoch, seed = plan.epoch, plan.seed
    num_reducers = plan.num_reducers
    filenames = plan.filenames
    plan_threads = sh.derive_gather_threads(len(filenames),
                                            pool.num_workers)

    def _map_payload(file_index: int, filename: str,
                     allow_cache_write: bool, attempt: int = 0) -> dict:
        # Attempt-scoped paths: a backup attempt must never rewrite the
        # primary's segments (the two can legally differ in layout — see
        # the function docstring).
        suffix = f".a{attempt}" if attempt else ""
        payload = {
            "filename": filename,
            "num_reducers": num_reducers,
            "seed": seed,
            "epoch": epoch,
            "file_index": file_index,
            "on_bad_file": on_bad_file,
            "map_transform": map_transform_blob,
            "plan_threads": plan_threads,
            "idx_seg": pool.segment_path(
                f"e{epoch}_f{file_index}{suffix}.idx"),
            "table_seg": pool.cached_table_seg(filename),
        }
        if payload["table_seg"] is None:
            grant = (pool.plan_table_seg_write(filename, file_index)
                     if allow_cache_write else None)
            payload["cache_grant"] = grant is not None
            payload["write_table_seg"] = grant or pool.segment_path(
                f"e{epoch}_f{file_index}_table{suffix}.arrow")
        return payload

    holder: Dict[str, Any] = {}
    sources: List["tuple[str, str, bool, bool]"] = []
    epoch_segs: List[str] = []  # epoch-scoped: unlinked at epoch drain
    transient = {"bytes": 0, "buf_id": None}

    def _dispatch_map(node, attempt: int) -> ProcTaskRef:
        file_index = node.key.task
        payload = _map_payload(file_index, node.meta["file"],
                               allow_cache_write=attempt == 0,
                               attempt=attempt)
        if attempt:
            payload["attempt"] = attempt
            # Pre-register the backup's epoch-scoped segments so the
            # loser's files are reaped at epoch drain; if it wins,
            # _collect_maps re-appends the same paths (unlink is quiet).
            epoch_segs.append(payload["idx_seg"])
            if payload.get("write_table_seg"):
                epoch_segs.append(payload["write_table_seg"])
        elif stats_collector is not None:
            stats_collector.map_start(epoch)
        return pool.submit_kind("map", payload, affinity=file_index)

    def _collect_maps() -> None:
        """Map-stage barrier (scheduler driver thread): fold every map
        node's segment reply into the reduce inputs, exactly the
        bookkeeping the old await-then-submit loop did inline."""
        from ray_shuffling_data_loader_tpu import native
        scheduler = holder["scheduler"]
        for node in sorted(plan.maps(), key=lambda n: n.key.task):
            file_index = node.key.task
            filename = node.meta["file"]
            try:
                res = scheduler.ref_for(node.id).result()
            except Exception as e:  # noqa: BLE001 - lineage re-run below
                logger.warning(
                    "map task %d (epoch %d) failed on the pool (%s); "
                    "recomputing from lineage", file_index, epoch, e)
                start = timeit.default_timer()
                retry_ref = pool.submit_kind(
                    "map", _map_payload(file_index, filename, False),
                    affinity=file_index)
                res = retry_ref.result()  # exhausted recovery propagates
                stats_mod.fault_stats().record_recompute(
                    "lineage", timeit.default_timer() - start)
            quarantined = res.get("quarantined")
            if quarantined is not None:
                stats_mod.fault_stats().record_quarantine(quarantined)
                logger.error(
                    "quarantined unreadable input file %s (epoch %d, "
                    "file %d): %s (on_bad_file='skip')", filename, epoch,
                    file_index, quarantined.error)
                if stats_collector is not None:
                    stats_collector.map_done(epoch, 0.0, 0.0)
                continue
            cached = bool(res.get("cached"))
            if cached:
                pool.note_table_seg(filename, res.get("table_seg"),
                                    res.get("wrote_table_bytes", 0))
            else:
                # Clears any unused cache grant (e.g. the granted attempt
                # died and the lineage re-run published an epoch-scoped
                # segment).
                pool.note_table_seg(filename, None, 0)
                epoch_segs.append(res["table_seg"])
                transient["bytes"] += res.get("wrote_table_bytes", 0)
            epoch_segs.append(res["idx_seg"])
            transient["bytes"] += res.get("idx_bytes", 0)
            sources.append((res["table_seg"], res["idx_seg"], cached,
                            bool(res.get("grouped"))))
            if stats_collector is not None:
                stats_collector.map_done(epoch, res["dur_s"], res["read_s"])
            rt_telemetry.observe_stage("map_read", epoch=epoch,
                                       task=file_index,
                                       dur_s=res["read_s"])
        if transient["bytes"]:
            transient["buf_id"] = native.buffer_ledger().register(
                transient["bytes"])

    def _dispatch_reduce(node, attempt: int) -> ProcTaskRef:
        reduce_index = node.key.task
        suffix = f".a{attempt}" if attempt else ""
        payload = {
            "reduce_index": reduce_index,
            "seed": seed,
            "epoch": epoch,
            "sources": sources,
            "gather_threads": gather_threads,
            "reduce_transform": reduce_transform_blob,
            "out_seg": pool.segment_path(
                f"e{epoch}_r{reduce_index}{suffix}.arrow"),
        }
        if attempt:
            payload["attempt"] = attempt
            # Reap the loser's output at epoch drain; a winner's file is
            # unlink-while-mmapped (safe) and its finalize unlink is
            # quiet. A loser that finishes after the drain is left for
            # pool teardown.
            epoch_segs.append(payload["out_seg"])
        elif stats_collector is not None:
            stats_collector.reduce_start(epoch)
        return pool.submit_kind("reduce", payload)

    pending = {"reduces": num_reducers}
    cleanup_lock = threading.Lock()

    def _epoch_cleanup() -> None:
        # Last reduce reply consumed -> the epoch's plan segments (and any
        # uncached table segments) have no readers left.
        from ray_shuffling_data_loader_tpu import native
        for path in epoch_segs:
            _unlink_quiet(path)
        if transient["buf_id"] is not None:
            try:
                native.buffer_ledger().decref(transient["buf_id"])
            except KeyError:
                pass

    def _finalize_factory(reduce_index: int):
        recompute = (spill_recompute_factory(reduce_index)
                     if spill_recompute_factory is not None else None)

        def _finalize(res: dict):
            table = open_table_segment(res["out_seg"])
            weakref.finalize(table, _unlink_quiet, res["out_seg"])
            if stats_collector is not None:
                stats_collector.reduce_done(epoch, res["dur_s"])
            rt_telemetry.observe_stage("reduce_gather", epoch=epoch,
                                       task=reduce_index,
                                       dur_s=res["dur_s"])
            with cleanup_lock:
                pending["reduces"] -= 1
                if pending["reduces"] == 0:
                    _epoch_cleanup()
            return sh.account_and_maybe_spill(
                table, spill_manager, recompute=recompute, epoch=epoch,
                task=reduce_index, seed=seed)

        return _finalize

    scheduler = plan_scheduler.PlanScheduler(
        plan, pool,
        dispatchers={"map": _dispatch_map, "reduce": _dispatch_reduce},
        barriers={"map": _collect_maps})
    holder["scheduler"] = scheduler
    scheduler.start()
    return [ProcTaskRef(future, _finalize_factory(reduce_index))
            for reduce_index, future in enumerate(
                scheduler.futures("reduce"))]
