"""Native (C++) host kernels, loaded via ctypes.

The reference's native substrate is Ray's C++ core (SURVEY.md §2.3). Here the
native layer is a small shared library built from ``src/shuffle_native.cpp``
at first import (g++ -O3, cached next to the source). Everything has a NumPy
fallback, so the package works even when no compiler is present — but the
native path is the default on TPU-VM hosts.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "src", "shuffle_native.cpp")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "src", "libshuffle_native.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
_load_lock = threading.Lock()


def _notify_release() -> None:
    """Wake budget waiters blocked on ledger releases (runtime/release.py).

    Called on every last-ref decref and free-list trim — the ledger-side
    half of the release-event channel that replaced the budget wait's
    ``gc.collect()`` polling cadence. Lazy import: the runtime package
    must stay importable without this module and vice versa.
    """
    from ray_shuffling_data_loader_tpu.runtime import release
    release.notify_release()

# Fixed so fill_random_* output is host-independent for a given seed; the
# per-thread stream layout is a function of this value, not of cpu_count.
_DEFAULT_FILL_THREADS = 8


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
        "-pthread", _SRC, "-o", _LIB_PATH,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    return proc.returncode == 0


def _bind(lib: ctypes.CDLL) -> None:
    i64, u64, u32p, i64p, f64p = (ctypes.c_int64, ctypes.c_uint64,
                                  ctypes.POINTER(ctypes.c_uint32),
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.POINTER(ctypes.c_double))
    lib.rsdl_partition_indices.argtypes = [u32p, i64, i64, i64p, i64p]
    lib.rsdl_partition_indices.restype = ctypes.c_int
    lib.rsdl_plan_partition.argtypes = [i64, i64, u64, i64p, i64p,
                                        ctypes.c_int]
    lib.rsdl_plan_partition.restype = ctypes.c_int
    lib.rsdl_partition_counts.argtypes = [i64, i64, u64, i64, i64p,
                                          ctypes.c_int]
    lib.rsdl_partition_counts.restype = ctypes.c_int
    lib.rsdl_assign_dest.argtypes = [i64, i64, u64, i64, i64p,
                                     ctypes.POINTER(ctypes.c_int32)]
    lib.rsdl_assign_dest.restype = ctypes.c_int
    lib.rsdl_crc32.argtypes = [ctypes.c_void_p, i64, ctypes.c_uint32]
    lib.rsdl_crc32.restype = ctypes.c_uint32
    lib.rsdl_scatter_gather.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        i64, ctypes.c_int32, ctypes.c_int
    ]
    lib.rsdl_scatter_gather.restype = ctypes.c_int
    lib.rsdl_fill_random_int64.argtypes = [i64p, i64, i64, u64, ctypes.c_int]
    lib.rsdl_fill_random_int64.restype = None
    lib.rsdl_fill_random_double.argtypes = [f64p, i64, u64, ctypes.c_int]
    lib.rsdl_fill_random_double.restype = None
    lib.rsdl_buffer_alloc.argtypes = [i64]
    lib.rsdl_buffer_alloc.restype = i64
    lib.rsdl_buffer_register.argtypes = [i64]
    lib.rsdl_buffer_register.restype = i64
    lib.rsdl_buffer_data.argtypes = [i64]
    lib.rsdl_buffer_data.restype = ctypes.c_void_p
    lib.rsdl_buffer_size.argtypes = [i64]
    lib.rsdl_buffer_size.restype = i64
    lib.rsdl_buffer_incref.argtypes = [i64]
    lib.rsdl_buffer_incref.restype = i64
    lib.rsdl_buffer_decref.argtypes = [i64]
    lib.rsdl_buffer_decref.restype = i64
    lib.rsdl_buffer_bytes_in_use.argtypes = []
    lib.rsdl_buffer_bytes_in_use.restype = i64
    lib.rsdl_buffer_count.argtypes = []
    lib.rsdl_buffer_count.restype = i64
    lib.rsdl_frame_send.argtypes = [ctypes.c_int, ctypes.c_void_p, i64,
                                    ctypes.c_void_p, i64]
    lib.rsdl_frame_send.restype = ctypes.c_int
    lib.rsdl_read_exact.argtypes = [ctypes.c_int, ctypes.c_void_p, i64]
    lib.rsdl_read_exact.restype = i64
    lib.rsdl_buffer_trim_freelist.argtypes = []
    lib.rsdl_buffer_trim_freelist.restype = None
    lib.rsdl_buffer_freelist_bytes.argtypes = []
    lib.rsdl_buffer_freelist_bytes.restype = i64


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    with _load_lock:
        if _load_attempted:
            return _lib
        if os.environ.get("RSDL_TPU_DISABLE_NATIVE"):
            _load_attempted = True
            return None
        try:
            needs_build = (not os.path.exists(_LIB_PATH)
                           or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC))
            if needs_build and not _build():
                return None
            lib = ctypes.CDLL(_LIB_PATH)
            _bind(lib)
            _lib = lib
        except (OSError, AttributeError):
            # AttributeError = stale .so missing a newly-bound symbol; try one
            # rebuild, then fall back to NumPy permanently.
            try:
                if _build():
                    lib = ctypes.CDLL(_LIB_PATH)
                    _bind(lib)
                    _lib = lib
            except (OSError, AttributeError):
                _lib = None
        finally:
            _load_attempted = True
        return _lib


def available() -> bool:
    """True if the native library is built and loaded."""
    return _load() is not None


_crc_backend_cached: Optional[str] = None


def crc_backend() -> str:
    """The resolved CRC backend: ``"native"`` or ``"zlib"``.

    Policy knob ``crc_backend`` (env ``RSDL_CRC_BACKEND``): ``auto``
    (default — native when the library is loaded), ``native``, ``zlib``.
    Resolved once per process (the wire path calls :func:`crc32` per
    frame); tests flip backends via :func:`reset_crc_backend`. An explicit
    ``native`` request without a loaded library degrades to zlib — the
    checksums are bit-identical, so integrity is never at stake, only
    speed.
    """
    global _crc_backend_cached
    if _crc_backend_cached is None:
        from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
        choice = rt_policy.resolve("native", "crc_backend")
        if choice == "zlib":
            _crc_backend_cached = "zlib"
        else:
            _crc_backend_cached = "native" if available() else "zlib"
    return _crc_backend_cached


def reset_crc_backend() -> None:
    """Drop the cached backend choice (test hook for env flips)."""
    global _crc_backend_cached
    _crc_backend_cached = None


def crc32(data, value: int = 0) -> int:
    """``zlib.crc32``-compatible checksum over any contiguous buffer.

    Same polynomial, same init/running-value semantics as ``zlib.crc32``
    (``crc = crc32(chunk, crc)`` chains), so every recorded checksum —
    wire frames, spill files, shm segments, watermark journals — stays
    valid across backend switches. The native kernel (slice-by-8 tables,
    ARMv8 ``crc32`` intrinsics where available) runs without the GIL.
    """
    import zlib
    if crc_backend() != "native":
        return zlib.crc32(data, value)
    try:
        buf = np.frombuffer(data, dtype=np.uint8)
    except ValueError:  # non-contiguous / exotic buffer: zlib handles it
        return zlib.crc32(data, value)
    if buf.nbytes == 0:
        return value & 0xFFFFFFFF
    lib = _load()
    assert lib is not None
    return int(lib.rsdl_crc32(buf.ctypes.data, buf.nbytes,
                              value & 0xFFFFFFFF))


def partition_indices(assignments: np.ndarray,
                      num_reducers: int) -> List[np.ndarray]:
    """O(n) stable counting-sort partition (see ops/partition.py docstring)."""
    if num_reducers < 1:
        raise ValueError(f"num_reducers must be >= 1, got {num_reducers}")
    lib = _load()
    assert lib is not None
    assignments = np.asarray(assignments)
    if assignments.dtype != np.uint32:
        # Guard the lossy cast: values that would wrap modulo 2**32 must
        # raise like the NumPy fallback does, not silently mis-partition.
        if assignments.size and (assignments.min() < 0
                                 or assignments.max() >= 2**32):
            raise ValueError(
                f"assignment value out of range for num_reducers={num_reducers}")
    assignments = np.ascontiguousarray(assignments, dtype=np.uint32)
    n = len(assignments)
    out = np.empty(n, dtype=np.int64)
    offsets = np.empty(num_reducers + 1, dtype=np.int64)
    rc = lib.rsdl_partition_indices(
        assignments.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), n,
        num_reducers, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if rc != 0:
        raise ValueError(
            f"assignment value out of range for num_reducers={num_reducers}")
    return [out[offsets[r]:offsets[r + 1]] for r in range(num_reducers)]


_GOLDEN = np.uint64(0x9e3779b97f4a7c15)
_MIX_C1 = np.uint64(0xbf58476d1ce4e5b9)
_MIX_C2 = np.uint64(0x94d049bb133111eb)


def hash_assign(num_rows: int, num_reducers: int, key: int,
                row0: int = 0) -> np.ndarray:
    """Vectorized splitmix64 per-row reducer assignment.

    Bit-identical to the per-row hash inside the native
    ``rsdl_plan_partition`` kernel (same constants, same stream layout:
    ``mix64(key + (i+1) * golden) % num_reducers``), so the NumPy plan
    fallback and the fused native plan produce the same partition on any
    host. Counter-based on purpose: every row's draw is independent, which
    is what lets the native kernel recompute assignments in its placement
    pass instead of materializing them — and what lets the streaming map
    pipeline draw any batch's slice of the stream via ``row0`` (global row
    offset of the batch's first row) without touching earlier rows.
    """
    if num_reducers < 1:
        raise ValueError(f"num_reducers must be >= 1, got {num_reducers}")
    i = np.arange(row0 + 1, row0 + num_rows + 1, dtype=np.uint64)
    x = np.uint64(key & 0xFFFFFFFFFFFFFFFF) + i * _GOLDEN
    x ^= x >> np.uint64(30)
    x *= _MIX_C1
    x ^= x >> np.uint64(27)
    x *= _MIX_C2
    x ^= x >> np.uint64(31)
    return (x % np.uint64(num_reducers)).astype(np.uint32)


def partition_counts(num_rows: int, num_reducers: int, key: int,
                     row0: int = 0, nthreads: int = 1) -> np.ndarray:
    """Per-reducer row counts for ``num_rows`` rows of the ``key`` hash
    stream starting at global row ``row0`` — no data, no index array
    (native kernel). Prefix-summing the result gives the exact region
    offsets the streaming map pipeline scatters into."""
    lib = _load()
    assert lib is not None
    counts = np.empty(num_reducers, dtype=np.int64)
    rc = lib.rsdl_partition_counts(
        num_rows, num_reducers, key & 0xFFFFFFFFFFFFFFFF, row0,
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        max(1, nthreads))
    if rc != 0:
        raise ValueError(
            f"invalid partition_counts arguments (num_rows={num_rows}, "
            f"num_reducers={num_reducers})")
    return counts


def assign_dest(num_rows: int, num_reducers: int, key: int, row0: int,
                cursors: np.ndarray) -> np.ndarray:
    """Destination slots for one record batch of the streaming map
    pipeline: ``dest[i] = cursors[assign(row0 + i)]++`` (native kernel,
    cursors advanced in place). int32 output; raises when a slot exceeds
    int32 range (callers fall back to the NumPy int64 path)."""
    lib = _load()
    assert lib is not None
    assert cursors.dtype == np.int64 and cursors.flags.c_contiguous
    dest = np.empty(num_rows, dtype=np.int32)
    rc = lib.rsdl_assign_dest(
        num_rows, num_reducers, key & 0xFFFFFFFFFFFFFFFF, row0,
        cursors.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        dest.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        raise ValueError(
            "assign_dest arguments invalid or destination exceeds int32 "
            f"(num_rows={num_rows}, num_reducers={num_reducers})")
    return dest


def plan_partition_flat(num_rows: int, num_reducers: int, key: int,
                        nthreads: int = 1
                        ) -> "tuple[np.ndarray, np.ndarray]":
    """Fused RNG -> stable counting-sort partition plan (native kernel).

    Returns ``(indices, offsets)``: ``indices`` is a permutation of
    ``arange(num_rows)`` grouped by reducer (original row order within a
    group), ``offsets`` has ``num_reducers + 1`` entries delimiting the
    groups. The assignment array is never materialized — the kernel
    recomputes the per-row hash in its placement pass.
    """
    lib = _load()
    assert lib is not None
    indices = np.empty(num_rows, dtype=np.int64)
    offsets = np.empty(num_reducers + 1, dtype=np.int64)
    rc = lib.rsdl_plan_partition(
        num_rows, num_reducers, key & 0xFFFFFFFFFFFFFFFF,
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        max(1, nthreads))
    if rc != 0:
        raise ValueError(
            f"invalid plan_partition arguments (num_rows={num_rows}, "
            f"num_reducers={num_reducers})")
    return indices, offsets


def scatter_gather(src: np.ndarray, idx: Optional[np.ndarray],
                   dest: np.ndarray, out: np.ndarray,
                   nthreads: int = 1) -> None:
    """Fused ``out[dest] = src[idx]`` (``src[i]`` when ``idx`` is None) in
    one memory pass — NumPy's fancy-index form gathers into a temporary
    then scatters it. ``dest`` entries must be unique; ``idx``/``dest``
    must be int32; ``src``/``out`` must share a 1/2/4/8-byte dtype.
    """
    lib = _load()
    assert lib is not None
    n = len(dest)
    if idx is not None:
        assert idx.dtype == np.int32 and idx.flags.c_contiguous
        assert len(idx) == n
    assert dest.dtype == np.int32 and dest.flags.c_contiguous
    assert src.flags.c_contiguous and out.flags.c_contiguous
    assert src.dtype.itemsize == out.dtype.itemsize
    rc = lib.rsdl_scatter_gather(
        src.ctypes.data, 0 if idx is None else idx.ctypes.data,
        dest.ctypes.data, out.ctypes.data, n, src.dtype.itemsize,
        nthreads)
    if rc != 0:
        raise ValueError(
            f"unsupported element size {src.dtype.itemsize} for "
            "native scatter_gather")


def fill_random_int64(n: int, bound: int, seed: int,
                      nthreads: int = 0) -> np.ndarray:
    """Threaded uniform int64 fill in [0, bound).

    Output depends on (seed, nthreads) only — the default nthreads is a
    fixed constant (not cpu_count) so the same seed reproduces the same
    data on any host.
    """
    if bound < 1:
        raise ValueError(f"bound must be >= 1, got {bound}")
    lib = _load()
    assert lib is not None
    if nthreads <= 0:
        nthreads = _DEFAULT_FILL_THREADS
    out = np.empty(n, dtype=np.int64)
    lib.rsdl_fill_random_int64(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n, bound,
        seed & 0xFFFFFFFFFFFFFFFF, nthreads)
    return out


def fill_random_double(n: int, seed: int, nthreads: int = 0) -> np.ndarray:
    """Threaded uniform double fill in [0, 1). Same (seed, nthreads)
    determinism contract as :func:`fill_random_int64`."""
    lib = _load()
    assert lib is not None
    if nthreads <= 0:
        nthreads = _DEFAULT_FILL_THREADS
    out = np.empty(n, dtype=np.float64)
    lib.rsdl_fill_random_double(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n,
        seed & 0xFFFFFFFFFFFFFFFF, nthreads)
    return out


class NativeBufferPool:
    """Thin Python handle over the C++ ref-counted host buffer pool.

    Plasma-equivalent role (SURVEY.md §2.3): host-RAM buffers with explicit
    refcounts so the shuffle's memory footprint is observable and bounded.
    Two kinds of entries share the ledger:

    - ``alloc``: real 64-byte-aligned allocations (transport recv buffers
      use these — see :func:`alloc_tracked_buffer`).
    - ``register``: accounting-only entries for bytes owned by an external
      allocator (Arrow tables — see :func:`account_table`).
    """

    def register(self, size: int) -> int:
        """Ledger-only entry for externally-allocated bytes."""
        if size < 0:
            raise ValueError(f"buffer size must be >= 0, got {size}")
        lib = _load()
        assert lib is not None
        buf_id = lib.rsdl_buffer_register(size)
        if buf_id == 0:
            raise MemoryError(f"native buffer register of {size} bytes failed")
        return buf_id

    def alloc(self, size: int) -> int:
        if size < 0:
            raise ValueError(f"buffer size must be >= 0, got {size}")
        lib = _load()
        assert lib is not None
        buf_id = lib.rsdl_buffer_alloc(size)
        if buf_id == 0:
            raise MemoryError(f"native buffer alloc of {size} bytes failed")
        return buf_id

    def view(self, buf_id: int) -> np.ndarray:
        """uint8 view of the buffer (no copy, no ownership transfer)."""
        lib = _load()
        assert lib is not None
        size = lib.rsdl_buffer_size(buf_id)
        if size < 0:
            raise KeyError(f"unknown buffer id {buf_id}")
        data = lib.rsdl_buffer_data(buf_id)
        if not data:
            # register()-created ledger entries carry no memory; wrapping
            # the NULL pointer would hand out a segfaulting array.
            raise KeyError(f"buffer id {buf_id} is accounting-only")
        return np.ctypeslib.as_array(
            ctypes.cast(data, ctypes.POINTER(ctypes.c_uint8)), shape=(size,))

    def incref(self, buf_id: int) -> int:
        lib = _load()
        assert lib is not None
        count = lib.rsdl_buffer_incref(buf_id)
        if count < 0:
            raise KeyError(f"unknown buffer id {buf_id}")
        return count

    def decref(self, buf_id: int) -> int:
        lib = _load()
        assert lib is not None
        count = lib.rsdl_buffer_decref(buf_id)
        if count < 0:
            raise KeyError(f"unknown buffer id {buf_id}")
        if count == 0:
            _notify_release()
        return count

    def bytes_in_use(self) -> int:
        lib = _load()
        assert lib is not None
        return lib.rsdl_buffer_bytes_in_use()

    def buffer_count(self) -> int:
        lib = _load()
        assert lib is not None
        return lib.rsdl_buffer_count()

    def freelist_bytes(self) -> int:
        """Bytes held in the exact-size reuse cache (not in use)."""
        lib = _load()
        assert lib is not None
        return lib.rsdl_buffer_freelist_bytes()

    def trim_freelist(self) -> None:
        """Release every cached free-list block back to the OS."""
        lib = _load()
        assert lib is not None
        lib.rsdl_buffer_trim_freelist()
        _notify_release()


class PythonBufferLedger:
    """Pure-Python fallback with NativeBufferPool's accounting API, used
    when no compiler is present (RSDL_TPU_DISABLE_NATIVE, minimal images)
    so pipeline memory accounting works everywhere. ``alloc`` entries are
    backed by numpy arrays."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}  # id -> [data_or_None, size, refcount]
        self._next_id = 1
        self._bytes = 0

    def _new_entry(self, data, size: int) -> int:
        with self._lock:
            buf_id = self._next_id
            self._next_id += 1
            self._entries[buf_id] = [data, size, 1]
            self._bytes += size
            return buf_id

    def register(self, size: int) -> int:
        if size < 0:
            raise ValueError(f"buffer size must be >= 0, got {size}")
        return self._new_entry(None, size)

    def alloc(self, size: int) -> int:
        if size < 0:
            raise ValueError(f"buffer size must be >= 0, got {size}")
        return self._new_entry(np.empty(size, dtype=np.uint8), size)

    def view(self, buf_id: int) -> np.ndarray:
        with self._lock:
            if buf_id not in self._entries:
                raise KeyError(f"unknown buffer id {buf_id}")
            data = self._entries[buf_id][0]
        if data is None:
            raise KeyError(f"buffer id {buf_id} is accounting-only")
        return data

    def incref(self, buf_id: int) -> int:
        with self._lock:
            if buf_id not in self._entries:
                raise KeyError(f"unknown buffer id {buf_id}")
            self._entries[buf_id][2] += 1
            return self._entries[buf_id][2]

    def decref(self, buf_id: int) -> int:
        with self._lock:
            if buf_id not in self._entries:
                raise KeyError(f"unknown buffer id {buf_id}")
            entry = self._entries[buf_id]
            entry[2] -= 1
            if entry[2] == 0:
                del self._entries[buf_id]
                self._bytes -= entry[1]
        if entry[2] == 0:
            _notify_release()
        return entry[2]

    def bytes_in_use(self) -> int:
        with self._lock:
            return self._bytes

    def buffer_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def freelist_bytes(self) -> int:
        """Always 0: numpy's allocator does its own recycling."""
        return 0

    def trim_freelist(self) -> None:
        pass


_py_ledger: Optional[PythonBufferLedger] = None
_py_ledger_lock = threading.Lock()


def buffer_ledger():
    """THE process-wide buffer ledger: the native pool when the C++ library
    is loaded, else the Python fallback. All pipeline memory accounting
    (file cache, in-flight reducer tables, transport recv buffers) goes
    through this one object; ``stats.get_memory_stats().pool_bytes``
    reports its total."""
    global _py_ledger
    if available():
        return NativeBufferPool()
    with _py_ledger_lock:
        if _py_ledger is None:
            _py_ledger = PythonBufferLedger()
        return _py_ledger


def trim_freelist() -> None:
    """Release the pool's recycled (free) buffers back to the OS. Shared
    end-of-trial hygiene for the shuffle drivers; no-op on the Python
    ledger."""
    buffer_ledger().trim_freelist()


def account_table(table) -> None:
    """Charge an Arrow table's bytes to the ledger for the lifetime of its
    Python wrapper (released by GC — the wrapper is the handle every
    pipeline stage passes around, so 'wrapper alive' is 'bytes in flight').
    """
    import weakref
    nbytes = table.nbytes
    if nbytes <= 0:
        return
    ledger = buffer_ledger()
    buf_id = ledger.register(nbytes)
    weakref.finalize(table, ledger.decref, buf_id)


def frame_send(fd: int, header, payload) -> None:
    """Send a framed message (header then payload) as one scatter-gather
    ``writev`` stream, entirely outside the GIL. ``header``/``payload`` are
    any contiguous buffer-protocol objects. Raises OSError on socket errors
    (callers treat it like a failed ``sendall``)."""
    lib = _load()
    assert lib is not None
    h = np.frombuffer(header, dtype=np.uint8)
    p = np.frombuffer(payload, dtype=np.uint8)
    rc = lib.rsdl_frame_send(fd, h.ctypes.data, h.nbytes, p.ctypes.data,
                             p.nbytes)
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc))


# Mirrors RSDL_EEOF_MID_MESSAGE in shuffle_native.cpp: a sentinel far
# outside the errno range, so real socket errnos (including a genuine
# EPIPE from read()) are reported faithfully.
_EEOF_MID_MESSAGE = 1000000


def read_exact_into(fd: int, buf: np.ndarray, n: int) -> bool:
    """Read exactly ``n`` bytes from ``fd`` into ``buf`` with one GIL-free
    call. Returns True on success, False on clean EOF before the first
    byte; raises OSError on socket errors or mid-message EOF."""
    import errno as _errno
    lib = _load()
    assert lib is not None
    assert buf.nbytes >= n and buf.flags.c_contiguous
    got = lib.rsdl_read_exact(fd, buf.ctypes.data, n)
    if got == n:
        return True
    if got == 0:
        return False
    err = -got
    if err == _EEOF_MID_MESSAGE:
        raise OSError(_errno.EPIPE, "peer closed connection mid-message")
    raise OSError(err, os.strerror(err))


def alloc_tracked_buffer(size: int) -> np.ndarray:
    """Pool-allocated uint8 buffer returned as an ndarray; the bytes are
    returned to the pool when the array (and everything referencing it —
    memoryviews, Arrow buffers made with pa.py_buffer) is collected."""
    import weakref
    ledger = buffer_ledger()
    if isinstance(ledger, NativeBufferPool):
        buf_id = ledger.alloc(size)
        arr = ledger.view(buf_id)
    else:
        # Fallback: numpy owns the bytes; the ledger only accounts them
        # (storing the array in the ledger would keep it alive forever).
        arr = np.empty(size, dtype=np.uint8)
        buf_id = ledger.register(size)
    weakref.finalize(arr, ledger.decref, buf_id)
    return arr
