"""ctypes loader for the native batch image decoder (src/image_decode.cpp).

Built lazily on first use (g++, linked against the system libjpeg/libpng);
every caller must handle :func:`available` returning False — the PIL
fallback in workloads/imagenet.py keeps the pipeline working on hosts
without a compiler or the codec libraries.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "src", "image_decode.cpp")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "src",
                         "libimage_decode.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
_load_lock = threading.Lock()

_DEFAULT_THREADS = max(1, min(8, (os.cpu_count() or 1)))


def _build() -> bool:
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread", _SRC,
        "-o", _LIB_PATH, "-ljpeg", "-lpng",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    return proc.returncode == 0


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    with _load_lock:
        if _load_attempted:
            return _lib
        if os.environ.get("RSDL_TPU_DISABLE_NATIVE"):
            _load_attempted = True
            return None
        try:
            needs_build = (not os.path.exists(_LIB_PATH)
                           or os.path.getmtime(_LIB_PATH)
                           < os.path.getmtime(_SRC))
            if needs_build and not _build():
                return None
            lib = ctypes.CDLL(_LIB_PATH)
            lib.rsdl_decode_images.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int
            ]
            lib.rsdl_decode_images.restype = ctypes.c_int64
            _lib = lib
        except (OSError, AttributeError):
            _lib = None
        finally:
            _load_attempted = True
        return _lib


def available() -> bool:
    """True if the native decoder is built and loaded."""
    return _load() is not None


def decode_batch(payloads: List[bytes], height: int, width: int,
                 nthreads: Optional[int] = None) -> np.ndarray:
    """Decode JPEG/PNG payloads to one ``(n, height*width*3)`` uint8 array.

    Raises ValueError naming the first payload that failed to decode or
    had the wrong dimensions.
    """
    lib = _load()
    assert lib is not None
    n = len(payloads)
    out = np.empty((n, height * width * 3), dtype=np.uint8)
    if n == 0:
        return out
    srcs = (ctypes.c_char_p * n)(*payloads)
    sizes = np.fromiter((len(p) for p in payloads), dtype=np.int64, count=n)
    rc = lib.rsdl_decode_images(
        srcs, sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
        height, width, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        nthreads or _DEFAULT_THREADS)
    if rc != 0:
        raise ValueError(
            f"image {rc - 1} failed to decode to ({height}, {width}, 3) — "
            "unsupported format or wrong dimensions; the TPU pipeline "
            "requires fixed shapes")
    return out
