// Native host-side kernels for the TPU shuffling data loader.
//
// The reference delegates its native work to Ray's C++ core (plasma object
// store + raylet; see SURVEY.md §2.3). Our runtime is host-local per TPU-VM,
// so the native components we need are the hot host-CPU kernels and a
// ref-counted host buffer pool:
//
//   - partition_indices: O(n) stable counting-sort of row indices by reducer
//     assignment (replaces the reference's O(n * num_reducers) boolean-mask
//     partition, reference: shuffle.py:215-218).
//   - fill_random_*: threaded xoshiro256** generators for the synthetic data
//     generator hot loop (reference: data_generation.py:98-110).
//   - buffer pool: aligned host allocations with explicit refcounts
//     (replaces plasma ref-counted buffers, SURVEY.md §2.3).
//
// Exposed with a plain C ABI and loaded from Python via ctypes.

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Partition kernel
// ---------------------------------------------------------------------------

// Stable counting sort: out_indices[i] receives the row indices assigned to
// reducer i, in original row order. out_indices must have room for n int64s,
// laid out contiguously; out_offsets gets num_reducers+1 entries.
// Returns 0 on success, -1 if any assignment is >= num_reducers (in which
// case no output is written).
int rsdl_partition_indices(const uint32_t* assignments, int64_t n,
                           int64_t num_reducers, int64_t* out_indices,
                           int64_t* out_offsets) {
  if (num_reducers < 1) return -1;
  std::vector<int64_t> counts(num_reducers, 0);
  const uint64_t bound = static_cast<uint64_t>(num_reducers);
  for (int64_t i = 0; i < n; ++i) {
    if (assignments[i] >= bound) return -1;
    counts[assignments[i]]++;
  }
  out_offsets[0] = 0;
  for (int64_t r = 0; r < num_reducers; ++r)
    out_offsets[r + 1] = out_offsets[r] + counts[r];
  std::vector<int64_t> cursor(out_offsets, out_offsets + num_reducers);
  for (int64_t i = 0; i < n; ++i) out_indices[cursor[assignments[i]]++] = i;
  return 0;
}

// ---------------------------------------------------------------------------
// Fused scatter-gather: out[dest[i]] = src[idx[i]]
// ---------------------------------------------------------------------------

// The reduce stage's permute is the shuffle's hottest loop. NumPy evaluates
// out[dest] = src[idx] as a gather into a temporary followed by a scatter
// (two memory passes + an allocation); this kernel is the single fused pass.
// idx == nullptr means "src is already in order" (out[dest[i]] = src[i]).
// dest entries must be unique (they are a slice of a permutation), so
// threads writing disjoint i-ranges never race.
}  // extern "C" (template helper below needs C++ linkage)

template <typename T>
static void scatter_gather_typed(const T* src, const int32_t* idx,
                                 const int32_t* dest, T* out, int64_t n,
                                 int nthreads) {
  auto work = [&](int64_t lo, int64_t hi) {
    if (idx == nullptr) {
      for (int64_t i = lo; i < hi; ++i) out[dest[i]] = src[i];
    } else {
      for (int64_t i = lo; i < hi; ++i) out[dest[i]] = src[idx[i]];
    }
  };
  if (nthreads <= 1 || n < (1 << 16)) {
    work(0, n);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t)
    threads.emplace_back(work, n * t / nthreads, n * (t + 1) / nthreads);
  for (auto& th : threads) th.join();
}

extern "C" {

// elem_size must be 1, 2, 4, or 8; returns -1 otherwise, 0 on success.
int rsdl_scatter_gather(const void* src, const int32_t* idx,
                        const int32_t* dest, void* out, int64_t n,
                        int32_t elem_size, int nthreads) {
  switch (elem_size) {
    case 1:
      scatter_gather_typed(static_cast<const uint8_t*>(src), idx, dest,
                           static_cast<uint8_t*>(out), n, nthreads);
      return 0;
    case 2:
      scatter_gather_typed(static_cast<const uint16_t*>(src), idx, dest,
                           static_cast<uint16_t*>(out), n, nthreads);
      return 0;
    case 4:
      scatter_gather_typed(static_cast<const uint32_t*>(src), idx, dest,
                           static_cast<uint32_t*>(out), n, nthreads);
      return 0;
    case 8:
      scatter_gather_typed(static_cast<const uint64_t*>(src), idx, dest,
                           static_cast<uint64_t*>(out), n, nthreads);
      return 0;
    default:
      return -1;
  }
}

// ---------------------------------------------------------------------------
// Threaded random fill (xoshiro256**) for synthetic data generation
// ---------------------------------------------------------------------------

static inline uint64_t rotl(uint64_t x, int k) {
  return (x << k) | (x >> (64 - k));
}

struct Xoshiro256 {
  uint64_t s[4];
  explicit Xoshiro256(uint64_t seed) {
    // splitmix64 seeding
    uint64_t z = seed;
    for (int i = 0; i < 4; ++i) {
      z += 0x9e3779b97f4a7c15ULL;
      uint64_t t = z;
      t = (t ^ (t >> 30)) * 0xbf58476d1ce4e5b9ULL;
      t = (t ^ (t >> 27)) * 0x94d049bb133111ebULL;
      s[i] = t ^ (t >> 31);
    }
  }
  inline uint64_t next() {
    uint64_t result = rotl(s[1] * 5, 7) * 9;
    uint64_t t = s[1] << 17;
    s[2] ^= s[0];
    s[3] ^= s[1];
    s[1] ^= s[2];
    s[0] ^= s[3];
    s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }
};

// Fill out[0..n) with uniform int64 in [0, bound) using nthreads threads.
// bound must be >= 1 (validated by the Python wrapper; guarded here too).
void rsdl_fill_random_int64(int64_t* out, int64_t n, int64_t bound,
                            uint64_t seed, int nthreads) {
  if (bound < 1) bound = 1;
  if (nthreads < 1) nthreads = 1;
  auto work = [&](int t) {
    int64_t lo = n * t / nthreads, hi = n * (t + 1) / nthreads;
    Xoshiro256 rng(seed * 0x100000001b3ULL + t + 1);
    // Rejection-free modulo is fine for data generation (bias < 2^-40 for
    // the cardinalities involved).
    for (int64_t i = lo; i < hi; ++i)
      out[i] = static_cast<int64_t>(rng.next() % static_cast<uint64_t>(bound));
  };
  if (nthreads == 1) {
    work(0);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) threads.emplace_back(work, t);
  for (auto& th : threads) th.join();
}

// Fill out[0..n) with uniform doubles in [0, 1).
void rsdl_fill_random_double(double* out, int64_t n, uint64_t seed,
                             int nthreads) {
  if (nthreads < 1) nthreads = 1;
  auto work = [&](int t) {
    int64_t lo = n * t / nthreads, hi = n * (t + 1) / nthreads;
    Xoshiro256 rng(seed * 0x9e3779b97f4a7c15ULL + t + 1);
    for (int64_t i = lo; i < hi; ++i)
      out[i] = (rng.next() >> 11) * 0x1.0p-53;
  };
  if (nthreads == 1) {
    work(0);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) threads.emplace_back(work, t);
  for (auto& th : threads) th.join();
}

// ---------------------------------------------------------------------------
// Ref-counted host buffer pool
// ---------------------------------------------------------------------------

namespace {

struct Buffer {
  void* data;
  int64_t size;
  std::atomic<int64_t> refcount;
  Buffer(void* d, int64_t s) : data(d), size(s), refcount(1) {}
};

std::mutex g_pool_mutex;
std::unordered_map<int64_t, Buffer*> g_pool;
int64_t g_next_id = 1;
std::atomic<int64_t> g_bytes_in_use{0};

}  // namespace

// Allocate a 64-byte-aligned buffer; returns an id (0 on failure or
// negative size).
int64_t rsdl_buffer_alloc(int64_t size) {
  if (size < 0) return 0;
  void* data = nullptr;
  if (posix_memalign(&data, 64, static_cast<size_t>(size > 0 ? size : 1)) != 0)
    return 0;
  auto* buf = new Buffer(data, size);
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  int64_t id = g_next_id++;
  g_pool[id] = buf;
  g_bytes_in_use.fetch_add(size);
  return id;
}

// Ledger-only entry: account `size` bytes owned by an EXTERNAL allocator
// (Arrow tables, fsspec buffers) under the pool's refcount lifetime without
// allocating. data() reports nullptr for these; decref at zero only drops
// the ledger entry. This is how the Python layer makes pipeline-wide memory
// (cache + in-flight reducer outputs + transport buffers) observable
// through one counter, plasma-store style.
int64_t rsdl_buffer_register(int64_t size) {
  if (size < 0) return 0;
  auto* buf = new Buffer(nullptr, size);
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  int64_t id = g_next_id++;
  g_pool[id] = buf;
  g_bytes_in_use.fetch_add(size);
  return id;
}

void* rsdl_buffer_data(int64_t id) {
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  auto it = g_pool.find(id);
  return it == g_pool.end() ? nullptr : it->second->data;
}

int64_t rsdl_buffer_size(int64_t id) {
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  auto it = g_pool.find(id);
  return it == g_pool.end() ? -1 : it->second->size;
}

// Increment refcount; returns new count or -1 if unknown id.
int64_t rsdl_buffer_incref(int64_t id) {
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  auto it = g_pool.find(id);
  if (it == g_pool.end()) return -1;
  return it->second->refcount.fetch_add(1) + 1;
}

// Decrement refcount; frees at zero. Returns new count or -1 if unknown id.
int64_t rsdl_buffer_decref(int64_t id) {
  Buffer* to_free = nullptr;
  int64_t count;
  {
    std::lock_guard<std::mutex> lock(g_pool_mutex);
    auto it = g_pool.find(id);
    if (it == g_pool.end()) return -1;
    count = it->second->refcount.fetch_sub(1) - 1;
    if (count == 0) {
      to_free = it->second;
      g_pool.erase(it);
      g_bytes_in_use.fetch_sub(to_free->size);
    }
  }
  if (to_free != nullptr) {
    free(to_free->data);
    delete to_free;
  }
  return count;
}

int64_t rsdl_buffer_bytes_in_use() { return g_bytes_in_use.load(); }

int64_t rsdl_buffer_count() {
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  return static_cast<int64_t>(g_pool.size());
}

}  // extern "C"
