// Native host-side kernels for the TPU shuffling data loader.
//
// The reference delegates its native work to Ray's C++ core (plasma object
// store + raylet; see SURVEY.md §2.3). Our runtime is host-local per TPU-VM,
// so the native components we need are the hot host-CPU kernels and a
// ref-counted host buffer pool:
//
//   - partition_indices: O(n) stable counting-sort of row indices by reducer
//     assignment (replaces the reference's O(n * num_reducers) boolean-mask
//     partition, reference: shuffle.py:215-218).
//   - fill_random_*: threaded xoshiro256** generators for the synthetic data
//     generator hot loop (reference: data_generation.py:98-110).
//   - buffer pool: aligned host allocations with explicit refcounts
//     (replaces plasma ref-counted buffers, SURVEY.md §2.3).
//
// Exposed with a plain C ABI and loaded from Python via ctypes.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include <sys/uio.h>
#include <unistd.h>

#if defined(__ARM_FEATURE_CRC32)
#include <arm_acle.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// Partition kernel
// ---------------------------------------------------------------------------

// Stable counting sort: out_indices[i] receives the row indices assigned to
// reducer i, in original row order. out_indices must have room for n int64s,
// laid out contiguously; out_offsets gets num_reducers+1 entries.
// Returns 0 on success, -1 if any assignment is >= num_reducers (in which
// case no output is written).
int rsdl_partition_indices(const uint32_t* assignments, int64_t n,
                           int64_t num_reducers, int64_t* out_indices,
                           int64_t* out_offsets) {
  if (num_reducers < 1) return -1;
  std::vector<int64_t> counts(num_reducers, 0);
  const uint64_t bound = static_cast<uint64_t>(num_reducers);
  for (int64_t i = 0; i < n; ++i) {
    if (assignments[i] >= bound) return -1;
    counts[assignments[i]]++;
  }
  out_offsets[0] = 0;
  for (int64_t r = 0; r < num_reducers; ++r)
    out_offsets[r + 1] = out_offsets[r] + counts[r];
  std::vector<int64_t> cursor(out_offsets, out_offsets + num_reducers);
  for (int64_t i = 0; i < n; ++i) out_indices[cursor[assignments[i]]++] = i;
  return 0;
}

// ---------------------------------------------------------------------------
// Fused partition plan: per-row RNG -> stable counting sort, one kernel
// ---------------------------------------------------------------------------

// The map stage's assign -> partition pipeline used to materialize a uint32
// assignment array via a numpy Philox draw, cross the ctypes boundary, and
// counting-sort it (rsdl_partition_indices) — three passes over n and two
// kernel launches. This kernel fuses the stages: each row's reducer
// assignment is a stateless splitmix64 hash of (key, row) computed in the
// count pass and stashed in a scratch vector the placement pass re-reads
// (4n scratch bytes stream through cache faster than a second round of
// 64-bit multiplies). The hash is counter-based, so both passes parallelize
// over contiguous row chunks and placement stays stable via per-(chunk,
// reducer) cursors. The Python fallback (native/__init__.py hash_assign)
// vectorizes the identical arithmetic, so native and NumPy plans are
// bit-identical by construction.

static inline uint64_t mix64(uint64_t x) {
  x ^= x >> 30;
  x *= 0xbf58476d1ce4e5b9ULL;
  x ^= x >> 27;
  x *= 0x94d049bb133111ebULL;
  x ^= x >> 31;
  return x;
}

static inline uint64_t row_assign(uint64_t key, int64_t i, uint64_t bound) {
  // splitmix64 stream: state = key + (i+1) * golden ratio; output = mix.
  // Modulo bias < 2^-40 for the reducer counts involved (same argument as
  // rsdl_fill_random_int64).
  return mix64(key + static_cast<uint64_t>(i + 1) * 0x9e3779b97f4a7c15ULL)
         % bound;
}

int rsdl_plan_partition(int64_t n, int64_t num_reducers, uint64_t key,
                        int64_t* out_indices, int64_t* out_offsets,
                        int nthreads) {
  if (num_reducers < 1 || n < 0) return -1;
  if (nthreads < 1) nthreads = 1;
  if (n < (1 << 16)) nthreads = 1;  // below this the spawn cost dominates
  const uint64_t bound = static_cast<uint64_t>(num_reducers);
  std::vector<uint32_t> assign(static_cast<size_t>(n));
  // counts[chunk][reducer], chunk-major so the prefix walk below is cheap.
  std::vector<std::vector<int64_t>> counts(
      nthreads, std::vector<int64_t>(num_reducers, 0));
  auto count_work = [&](int t) {
    int64_t lo = n * t / nthreads, hi = n * (t + 1) / nthreads;
    auto& local = counts[t];
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t r = static_cast<uint32_t>(row_assign(key, i, bound));
      assign[i] = r;
      local[r]++;
    }
  };
  if (nthreads == 1) {
    count_work(0);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) threads.emplace_back(count_work, t);
    for (auto& th : threads) th.join();
  }
  out_offsets[0] = 0;
  for (int64_t r = 0; r < num_reducers; ++r) {
    int64_t total = 0;
    for (int t = 0; t < nthreads; ++t) total += counts[t][r];
    out_offsets[r + 1] = out_offsets[r] + total;
  }
  // cursor[chunk][reducer]: where chunk t's first row for reducer r lands —
  // reducer start + rows earlier chunks contribute to r. Earlier chunks
  // hold smaller row indices, so within a reducer the output stays in
  // original row order (stability, same contract as rsdl_partition_indices).
  std::vector<std::vector<int64_t>> cursor(
      nthreads, std::vector<int64_t>(num_reducers, 0));
  for (int64_t r = 0; r < num_reducers; ++r) {
    int64_t at = out_offsets[r];
    for (int t = 0; t < nthreads; ++t) {
      cursor[t][r] = at;
      at += counts[t][r];
    }
  }
  auto place_work = [&](int t) {
    int64_t lo = n * t / nthreads, hi = n * (t + 1) / nthreads;
    auto& local = cursor[t];
    for (int64_t i = lo; i < hi; ++i)
      out_indices[local[assign[i]]++] = i;
  };
  if (nthreads == 1) {
    place_work(0);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) threads.emplace_back(place_work, t);
    for (auto& th : threads) th.join();
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Streaming map pipeline: counts-only plan + per-batch destination assign
// ---------------------------------------------------------------------------
//
// The fused decode->partition->gather map path streams Parquet record
// batches straight into per-reducer output buffers, so it needs the plan in
// two pieces instead of one:
//
//   1. rsdl_partition_counts — per-reducer row counts for the WHOLE file,
//      computed from the hash stream alone (no data, no index array): the
//      assignment is counter-based, so the counts are known before the
//      first batch is decoded. This sizes the per-reducer output regions.
//   2. rsdl_assign_dest — for one record batch starting at global row
//      `row0`, emit each row's destination slot (cursor[r]++ over the
//      running per-reducer cursors). Rows are visited in increasing global
//      row order, so every reducer's region fills in original row order —
//      the same stable order rsdl_plan_partition's counting sort produces,
//      which is what makes the streamed output bit-identical to the legacy
//      plan-then-gather path.
//
// Both use row_assign() above, i.e. the exact (seed, epoch, file) hash
// stream of rsdl_plan_partition and the NumPy hash_assign fallback.

int rsdl_partition_counts(int64_t n, int64_t num_reducers, uint64_t key,
                          int64_t row0, int64_t* out_counts, int nthreads) {
  if (num_reducers < 1 || n < 0 || row0 < 0) return -1;
  if (nthreads < 1) nthreads = 1;
  if (n < (1 << 16)) nthreads = 1;
  const uint64_t bound = static_cast<uint64_t>(num_reducers);
  std::vector<std::vector<int64_t>> counts(
      nthreads, std::vector<int64_t>(num_reducers, 0));
  auto work = [&](int t) {
    int64_t lo = n * t / nthreads, hi = n * (t + 1) / nthreads;
    auto& local = counts[t];
    for (int64_t i = lo; i < hi; ++i)
      local[row_assign(key, row0 + i, bound)]++;
  };
  if (nthreads == 1) {
    work(0);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) threads.emplace_back(work, t);
    for (auto& th : threads) th.join();
  }
  for (int64_t r = 0; r < num_reducers; ++r) {
    int64_t total = 0;
    for (int t = 0; t < nthreads; ++t) total += counts[t][r];
    out_counts[r] = total;
  }
  return 0;
}

// Serial on purpose: the cursors advance in strict row order (stability),
// and a record batch is ~64K rows — at ~1.5 ns/row the loop is far below
// the decode cost it overlaps with. Returns -1 when a destination slot
// exceeds int32 range (caller falls back to the 64-bit NumPy path).
int rsdl_assign_dest(int64_t n, int64_t num_reducers, uint64_t key,
                     int64_t row0, int64_t* cursors, int32_t* out_dest) {
  if (num_reducers < 1 || n < 0 || row0 < 0) return -1;
  const uint64_t bound = static_cast<uint64_t>(num_reducers);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t r = row_assign(key, row0 + i, bound);
    int64_t d = cursors[r]++;
    if (d > INT32_MAX) return -1;
    out_dest[i] = static_cast<int32_t>(d);
  }
  return 0;
}

// ---------------------------------------------------------------------------
// CRC32 (zlib polynomial)
// ---------------------------------------------------------------------------
//
// zlib.crc32-compatible checksum: reflected ISO-HDLC polynomial 0xEDB88320.
// The x86 SSE4.2 `crc32` instruction computes CRC-32C (Castagnoli,
// 0x82F63B78) and can NOT produce zlib-compatible output, so on x86 the
// fast path is slice-by-8 tables (~8 table lookups per 8 bytes, multi-GB/s,
// several times zlib's Python-call throughput once the ctypes call runs
// without the GIL). ARMv8's __crc32* intrinsics implement the zlib
// polynomial directly and are used when the compiler advertises them.

#if !defined(__ARM_FEATURE_CRC32)
namespace {

struct Crc32Tables {
  uint32_t t[8][256];
  Crc32Tables() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int j = 1; j < 8; ++j)
        t[j][i] = (t[j - 1][i] >> 8) ^ t[0][t[j - 1][i] & 0xFF];
  }
};

const Crc32Tables g_crc;  // 8 KiB, built once at load

}  // namespace
#endif

uint32_t rsdl_crc32(const void* data, int64_t n, uint32_t init) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  uint32_t c = ~init;
#if defined(__ARM_FEATURE_CRC32)
  while (n >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    c = __crc32d(c, v);
    p += 8;
    n -= 8;
  }
  while (n-- > 0) c = __crc32b(c, *p++);
#else
  // Slice-by-8: two 32-bit little-endian loads per iteration (x86/ARM are
  // both little-endian; the byte-at-a-time tail is endian-agnostic).
  while (n >= 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, p, 4);
    std::memcpy(&hi, p + 4, 4);
    c ^= lo;
    c = g_crc.t[7][c & 0xFF] ^ g_crc.t[6][(c >> 8) & 0xFF] ^
        g_crc.t[5][(c >> 16) & 0xFF] ^ g_crc.t[4][c >> 24] ^
        g_crc.t[3][hi & 0xFF] ^ g_crc.t[2][(hi >> 8) & 0xFF] ^
        g_crc.t[1][(hi >> 16) & 0xFF] ^ g_crc.t[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n-- > 0) c = g_crc.t[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
#endif
  return ~c;
}

// ---------------------------------------------------------------------------
// Fused scatter-gather: out[dest[i]] = src[idx[i]]
// ---------------------------------------------------------------------------

// The reduce stage's permute is the shuffle's hottest loop. NumPy evaluates
// out[dest] = src[idx] as a gather into a temporary followed by a scatter
// (two memory passes + an allocation); this kernel is the single fused pass.
// idx == nullptr means "src is already in order" (out[dest[i]] = src[i]).
// dest entries must be unique (they are a slice of a permutation), so
// threads writing disjoint i-ranges never race.
}  // extern "C" (template helper below needs C++ linkage)

template <typename T>
static void scatter_gather_typed(const T* src, const int32_t* idx,
                                 const int32_t* dest, T* out, int64_t n,
                                 int nthreads) {
  auto work = [&](int64_t lo, int64_t hi) {
    if (idx == nullptr) {
      for (int64_t i = lo; i < hi; ++i) out[dest[i]] = src[i];
    } else {
      for (int64_t i = lo; i < hi; ++i) out[dest[i]] = src[idx[i]];
    }
  };
  if (nthreads <= 1 || n < (1 << 16)) {
    work(0, n);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t)
    threads.emplace_back(work, n * t / nthreads, n * (t + 1) / nthreads);
  for (auto& th : threads) th.join();
}

extern "C" {

// elem_size must be 1, 2, 4, or 8; returns -1 otherwise, 0 on success.
int rsdl_scatter_gather(const void* src, const int32_t* idx,
                        const int32_t* dest, void* out, int64_t n,
                        int32_t elem_size, int nthreads) {
  switch (elem_size) {
    case 1:
      scatter_gather_typed(static_cast<const uint8_t*>(src), idx, dest,
                           static_cast<uint8_t*>(out), n, nthreads);
      return 0;
    case 2:
      scatter_gather_typed(static_cast<const uint16_t*>(src), idx, dest,
                           static_cast<uint16_t*>(out), n, nthreads);
      return 0;
    case 4:
      scatter_gather_typed(static_cast<const uint32_t*>(src), idx, dest,
                           static_cast<uint32_t*>(out), n, nthreads);
      return 0;
    case 8:
      scatter_gather_typed(static_cast<const uint64_t*>(src), idx, dest,
                           static_cast<uint64_t*>(out), n, nthreads);
      return 0;
    default:
      return -1;
  }
}

// ---------------------------------------------------------------------------
// Threaded random fill (xoshiro256**) for synthetic data generation
// ---------------------------------------------------------------------------

static inline uint64_t rotl(uint64_t x, int k) {
  return (x << k) | (x >> (64 - k));
}

struct Xoshiro256 {
  uint64_t s[4];
  explicit Xoshiro256(uint64_t seed) {
    // splitmix64 seeding
    uint64_t z = seed;
    for (int i = 0; i < 4; ++i) {
      z += 0x9e3779b97f4a7c15ULL;
      uint64_t t = z;
      t = (t ^ (t >> 30)) * 0xbf58476d1ce4e5b9ULL;
      t = (t ^ (t >> 27)) * 0x94d049bb133111ebULL;
      s[i] = t ^ (t >> 31);
    }
  }
  inline uint64_t next() {
    uint64_t result = rotl(s[1] * 5, 7) * 9;
    uint64_t t = s[1] << 17;
    s[2] ^= s[0];
    s[3] ^= s[1];
    s[1] ^= s[2];
    s[0] ^= s[3];
    s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }
};

// Fill out[0..n) with uniform int64 in [0, bound) using nthreads threads.
// bound must be >= 1 (validated by the Python wrapper; guarded here too).
void rsdl_fill_random_int64(int64_t* out, int64_t n, int64_t bound,
                            uint64_t seed, int nthreads) {
  if (bound < 1) bound = 1;
  if (nthreads < 1) nthreads = 1;
  auto work = [&](int t) {
    int64_t lo = n * t / nthreads, hi = n * (t + 1) / nthreads;
    Xoshiro256 rng(seed * 0x100000001b3ULL + t + 1);
    // Rejection-free modulo is fine for data generation (bias < 2^-40 for
    // the cardinalities involved).
    for (int64_t i = lo; i < hi; ++i)
      out[i] = static_cast<int64_t>(rng.next() % static_cast<uint64_t>(bound));
  };
  if (nthreads == 1) {
    work(0);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) threads.emplace_back(work, t);
  for (auto& th : threads) th.join();
}

// Fill out[0..n) with uniform doubles in [0, 1).
void rsdl_fill_random_double(double* out, int64_t n, uint64_t seed,
                             int nthreads) {
  if (nthreads < 1) nthreads = 1;
  auto work = [&](int t) {
    int64_t lo = n * t / nthreads, hi = n * (t + 1) / nthreads;
    Xoshiro256 rng(seed * 0x9e3779b97f4a7c15ULL + t + 1);
    for (int64_t i = lo; i < hi; ++i)
      out[i] = (rng.next() >> 11) * 0x1.0p-53;
  };
  if (nthreads == 1) {
    work(0);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) threads.emplace_back(work, t);
  for (auto& th : threads) th.join();
}

// ---------------------------------------------------------------------------
// Ref-counted host buffer pool
// ---------------------------------------------------------------------------

namespace {

struct Buffer {
  void* data;
  int64_t size;        // bytes requested (what the ledger accounts)
  int64_t alloc_size;  // bytes actually reserved (the size class; 0 for
                       // register()-only entries with no memory)
  std::atomic<int64_t> refcount;
  Buffer(void* d, int64_t s, int64_t a)
      : data(d), size(s), alloc_size(a), refcount(1) {}
};

std::mutex g_pool_mutex;
std::unordered_map<int64_t, Buffer*> g_pool;
int64_t g_next_id = 1;
std::atomic<int64_t> g_bytes_in_use{0};

// Free list: released allocations cached for reuse (plasma-style
// recycling). Steady-state transport recvs allocate similar sizes over and
// over; reusing warm pages skips both mmap and the first-touch page faults
// of a fresh block. Blocks are reserved in power-of-two size classes so
// near-miss sizes still recycle, and insertion over the cap evicts the
// oldest blocks of the fattest class so a burst of stale sizes cannot pin
// the cache forever.
std::unordered_map<int64_t, std::vector<void*>> g_freelist;  // class -> LIFO
int64_t g_freelist_bytes = 0;  // sum of class bytes cached
int64_t g_freelist_cap = 256LL << 20;

int64_t size_class(int64_t size) {
  int64_t c = 4096;
  while (c < size) c <<= 1;  // callers guard size <= 2^62, so no overflow
  return c;
}

// Move whole classes out of the free list until it is under the cap,
// fattest class first. Caller holds g_pool_mutex and frees the returned
// blocks AFTER releasing it (eviction is O(evicted blocks); the scan per
// round touches only the ~30 possible size classes).
std::vector<void*> freelist_evict_until_under_cap() {
  std::vector<void*> evicted;
  while (g_freelist_bytes > g_freelist_cap && !g_freelist.empty()) {
    auto fattest = g_freelist.begin();
    int64_t fattest_bytes = -1;
    for (auto it = g_freelist.begin(); it != g_freelist.end(); ++it) {
      int64_t bytes = it->first * static_cast<int64_t>(it->second.size());
      if (bytes > fattest_bytes) {
        fattest = it;
        fattest_bytes = bytes;
      }
    }
    g_freelist_bytes -= fattest_bytes;
    evicted.insert(evicted.end(), fattest->second.begin(),
                   fattest->second.end());
    g_freelist.erase(fattest);
  }
  return evicted;
}

}  // namespace

// Allocate a 64-byte-aligned buffer; returns an id (0 on failure or
// negative size).
int64_t rsdl_buffer_alloc(int64_t size) {
  // Upper bound guards size_class against shift overflow; a corrupt wire
  // length lands here, so it must fail cleanly, not spin.
  if (size < 0 || size > (1LL << 62)) return 0;
  int64_t cls = size_class(size);
  void* data = nullptr;
  {
    std::lock_guard<std::mutex> lock(g_pool_mutex);
    auto it = g_freelist.find(cls);
    if (it != g_freelist.end() && !it->second.empty()) {
      data = it->second.back();  // LIFO: warmest pages first
      it->second.pop_back();
      g_freelist_bytes -= cls;
      if (it->second.empty()) g_freelist.erase(it);
    }
  }
  if (data == nullptr &&
      posix_memalign(&data, 64, static_cast<size_t>(cls)) != 0)
    return 0;
  auto* buf = new Buffer(data, size, cls);
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  int64_t id = g_next_id++;
  g_pool[id] = buf;
  // Charge the RESERVED bytes (the class) so the budget/spill machinery
  // sees real RSS, not the up-to-2x-smaller requested size.
  g_bytes_in_use.fetch_add(cls);
  return id;
}

// Ledger-only entry: account `size` bytes owned by an EXTERNAL allocator
// (Arrow tables, fsspec buffers) under the pool's refcount lifetime without
// allocating. data() reports nullptr for these; decref at zero only drops
// the ledger entry. This is how the Python layer makes pipeline-wide memory
// (cache + in-flight reducer outputs + transport buffers) observable
// through one counter, plasma-store style.
int64_t rsdl_buffer_register(int64_t size) {
  if (size < 0) return 0;
  auto* buf = new Buffer(nullptr, size, 0);
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  int64_t id = g_next_id++;
  g_pool[id] = buf;
  g_bytes_in_use.fetch_add(size);
  return id;
}

void* rsdl_buffer_data(int64_t id) {
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  auto it = g_pool.find(id);
  return it == g_pool.end() ? nullptr : it->second->data;
}

int64_t rsdl_buffer_size(int64_t id) {
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  auto it = g_pool.find(id);
  return it == g_pool.end() ? -1 : it->second->size;
}

// Increment refcount; returns new count or -1 if unknown id.
int64_t rsdl_buffer_incref(int64_t id) {
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  auto it = g_pool.find(id);
  if (it == g_pool.end()) return -1;
  return it->second->refcount.fetch_add(1) + 1;
}

// Decrement refcount; at zero the block moves to the free list (or is
// freed). Returns new count or -1 if unknown id. One mutex acquisition per
// call; evicted blocks are freed after the lock is released.
int64_t rsdl_buffer_decref(int64_t id) {
  Buffer* to_free = nullptr;
  std::vector<void*> evicted;
  int64_t count;
  {
    std::lock_guard<std::mutex> lock(g_pool_mutex);
    auto it = g_pool.find(id);
    if (it == g_pool.end()) return -1;
    count = it->second->refcount.fetch_sub(1) - 1;
    if (count == 0) {
      to_free = it->second;
      g_pool.erase(it);
      // Symmetric with alloc/register: alloc entries were charged their
      // reserved class bytes, register entries their declared size.
      g_bytes_in_use.fetch_sub(
          to_free->alloc_size > 0 ? to_free->alloc_size : to_free->size);
      if (to_free->data != nullptr && to_free->alloc_size > 0) {
        g_freelist[to_free->alloc_size].push_back(to_free->data);
        g_freelist_bytes += to_free->alloc_size;
        to_free->data = nullptr;  // ownership moved to the free list
        evicted = freelist_evict_until_under_cap();
      }
    }
  }
  for (void* p : evicted) free(p);
  if (to_free != nullptr) {
    free(to_free->data);  // nullptr when the block was cached above
    delete to_free;
  }
  return count;
}

// Drop every cached free-list block (testing / memory-pressure hook).
void rsdl_buffer_trim_freelist() {
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  for (auto& entry : g_freelist)
    for (void* p : entry.second) free(p);
  g_freelist.clear();
  g_freelist_bytes = 0;
}

int64_t rsdl_buffer_freelist_bytes() {
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  return g_freelist_bytes;
}

// ---------------------------------------------------------------------------
// Transport data pump (DCN plane)
// ---------------------------------------------------------------------------
//
// The Python transport's per-message cost is dominated by GIL round-trips:
// two sendall() calls per frame and one recv_into() per ~MB of payload.
// These two entry points move a whole frame per C call — ctypes releases
// the GIL for the duration, so multi-MB sends/receives run entirely
// outside the interpreter (plasma's raylet-to-raylet object transfer role,
// SURVEY.md §2.3).

// Write header then payload as one scatter-gather stream (writev), looping
// on partial writes and EINTR. Returns 0 on success, -errno on error.
int rsdl_frame_send(int fd, const void* header, int64_t hlen,
                    const void* payload, int64_t plen) {
  struct iovec iov[2];
  iov[0].iov_base = const_cast<void*>(header);
  iov[0].iov_len = static_cast<size_t>(hlen);
  iov[1].iov_base = const_cast<void*>(payload);
  iov[1].iov_len = static_cast<size_t>(plen);
  int iov_idx = 0;
  while (iov_idx < 2) {
    ssize_t wrote = writev(fd, &iov[iov_idx], 2 - iov_idx);
    if (wrote < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    size_t w = static_cast<size_t>(wrote);
    while (iov_idx < 2 && w >= iov[iov_idx].iov_len) {
      w -= iov[iov_idx].iov_len;
      ++iov_idx;
    }
    if (iov_idx < 2 && w > 0) {
      iov[iov_idx].iov_base = static_cast<char*>(iov[iov_idx].iov_base) + w;
      iov[iov_idx].iov_len -= w;
    }
  }
  return 0;
}

// Sentinel for EOF after a partial read. Deliberately far outside the
// errno range (errnos are small positive ints) so a genuine EPIPE errno
// returned by read() stays distinguishable from a clean peer close
// mid-frame.
const int64_t RSDL_EEOF_MID_MESSAGE = 1000000;

// Read exactly n bytes into dst. Returns n on success, 0 on clean EOF
// before the first byte, -RSDL_EEOF_MID_MESSAGE on EOF mid-read,
// -errno on error.
int64_t rsdl_read_exact(int fd, void* dst, int64_t n) {
  int64_t got = 0;
  while (got < n) {
    ssize_t r = read(fd, static_cast<char*>(dst) + got,
                     static_cast<size_t>(n - got));
    if (r < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (r == 0) return got == 0 ? 0 : -RSDL_EEOF_MID_MESSAGE;
    got += r;
  }
  return got;
}

int64_t rsdl_buffer_bytes_in_use() { return g_bytes_in_use.load(); }

int64_t rsdl_buffer_count() {
  std::lock_guard<std::mutex> lock(g_pool_mutex);
  return static_cast<int64_t>(g_pool.size());
}

}  // extern "C"
