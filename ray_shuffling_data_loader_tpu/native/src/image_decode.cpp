// Native batch image decoder for the ImageNet-Parquet workload.
//
// The shuffle reducers decode encoded image bytes into fixed-shape pixel
// columns (workloads/imagenet.py). A Python/PIL loop decodes one image at
// a time under interpreter dispatch; this kernel decodes a whole reducer
// batch with a thread pool over libjpeg/libpng directly — the same
// decode-in-native-code role Ray's C++ core and pyarrow's C++ Parquet
// reader play elsewhere in the pipeline (SURVEY.md §2.3).
//
// API (C, ctypes-friendly):
//   rsdl_decode_images(srcs, sizes, n, height, width, out, nthreads)
//     srcs:  n pointers to encoded payloads (JPEG or PNG, by magic bytes)
//     out:   n * height * width * 3 uint8, RGB, C-order
//     returns 0 on success, i+1 if payload i failed to decode or had the
//     wrong dimensions (first failing index wins best-effort).
//
// Build: g++ -O2 -shared -fPIC image_decode.cpp -ljpeg -lpng

#include <atomic>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <png.h>

namespace {

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  auto* mgr = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  std::longjmp(mgr->jump, 1);
}

// Returns true on success; decodes RGB into dst (height*width*3).
bool decode_jpeg(const uint8_t* src, int64_t size, int64_t height,
                 int64_t width, uint8_t* dst) {
  jpeg_decompress_struct cinfo;
  JpegErrorMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = jpeg_error_exit;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(src),
               static_cast<unsigned long>(size));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_width != static_cast<JDIMENSION>(width) ||
      cinfo.output_height != static_cast<JDIMENSION>(height) ||
      cinfo.output_components != 3) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = dst + int64_t(cinfo.output_scanline) * width * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

bool decode_png(const uint8_t* src, int64_t size, int64_t height,
                int64_t width, uint8_t* dst) {
  png_image image;
  std::memset(&image, 0, sizeof(image));
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&image, src,
                                        static_cast<size_t>(size))) {
    return false;
  }
  image.format = PNG_FORMAT_RGB;
  if (image.width != static_cast<png_uint_32>(width) ||
      image.height != static_cast<png_uint_32>(height)) {
    png_image_free(&image);
    return false;
  }
  if (!png_image_finish_read(&image, nullptr, dst, 0, nullptr)) {
    png_image_free(&image);
    return false;
  }
  return true;
}

bool decode_one(const uint8_t* src, int64_t size, int64_t height,
                int64_t width, uint8_t* dst) {
  if (size >= 3 && src[0] == 0xFF && src[1] == 0xD8 && src[2] == 0xFF) {
    return decode_jpeg(src, size, height, width, dst);
  }
  if (size >= 8 && src[0] == 0x89 && src[1] == 'P' && src[2] == 'N' &&
      src[3] == 'G') {
    return decode_png(src, size, height, width, dst);
  }
  return false;
}

}  // namespace

extern "C" {

int64_t rsdl_decode_images(const uint8_t* const* srcs, const int64_t* sizes,
                           int64_t n, int64_t height, int64_t width,
                           uint8_t* out, int nthreads) {
  if (n == 0) return 0;
  if (nthreads < 1) nthreads = 1;
  if (nthreads > n) nthreads = static_cast<int>(n);
  const int64_t row_bytes = height * width * 3;
  std::atomic<int64_t> failed{0};  // i+1 of a failing payload, 0 = none

  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      if (failed.load(std::memory_order_relaxed)) return;
      if (!decode_one(srcs[i], sizes[i], height, width,
                      out + i * row_bytes)) {
        int64_t expected = 0;
        failed.compare_exchange_strong(expected, i + 1);
        return;
      }
    }
  };

  if (nthreads == 1) {
    work(0, n);
  } else {
    std::vector<std::thread> threads;
    const int64_t chunk = (n + nthreads - 1) / nthreads;
    for (int t = 0; t < nthreads; ++t) {
      const int64_t lo = t * chunk;
      const int64_t hi = std::min(n, lo + chunk);
      if (lo >= hi) break;
      threads.emplace_back(work, lo, hi);
    }
    for (auto& th : threads) th.join();
  }
  return failed.load();
}

}  // extern "C"
