"""Self-healing serving plane: journaled, SLO-driven live queue
rebalancing with crash-safe exactly-once handoff.

PR 10 sharded the queue fabric and PR 17 made tenants share a shard
fairly, but placement stayed **frozen at launch**: when one tenant or
rank runs hot its shard saturates while siblings idle, and nothing
closed the detector->actuator loop. This package is that loop's brain —
the *decision* plane. The *actuator* (the two-phase PREPARE/ADOPT/
RELEASE wire protocol that actually moves a live queue between shard
processes) lives in ``multiqueue_service.py``; :func:`migrate` drives
it end to end.

The discipline is the membership/tenancy recipe applied to placement:

- :class:`PlacementDecision` — one journaled decision (``intent``,
  ``commit``, ``abort``; ``bootstrap``/``snapshot`` are journal bases).
- :class:`PlacementState` — the immutable fold target: committed
  ``overrides`` (rank -> shard), the placement ``generation`` (the
  fence stamped into every wire frame), and at most ONE ``pending``
  in-flight move.
- :func:`apply_decision` — THE pure transition function. No wall
  clock, no dict-order dependence: a journal is a fold of decisions
  over the bootstrap state, so :func:`replay` re-derives every decision
  byte-identically and raises on any divergence.
- :class:`RebalanceJournal` — the crc'd append-only JSONL discipline of
  ``checkpoint.WatermarkJournal`` (torn tails skipped, interior
  corruption raises, atomic compact) applied to placement decisions.
- :class:`RebalanceController` — the runtime hub: owns the state,
  journals decisions, enforces the RSDL_REBALANCE_* policy knobs
  (SLO threshold, cooldown, max moves per window), and — the
  crash-safety keystone — **journals an abort for any trailing
  uncommitted intent at restart**, so a driver killed mid-decision
  always recovers to "source authoritative".

Crash matrix (the headline): kill -9 of the source shard mid-PREPARE
or the target shard mid-COMMIT leaves the commit unjournaled, so the
source stays authoritative and the supervised restart resumes it from
its watermark journal; a driver killed mid-decision aborts on restart.
In every case the merged delivered stream is bit-identical — zero
missed or duplicated ``row_offset``s — because adoption replays the
source's unacked frames from a CRC'd manifest and the client's seq
dedup drops anything already delivered. A zombie source that wakes up
after the move stamps its frames with the stale generation and the
client fences them loudly (``rsdl_rebalance_fenced_frames_total``).
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_shuffling_data_loader_tpu import checkpoint as ckpt
from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

#: Journaled decision kinds. ``bootstrap``/``snapshot`` carry a whole
#: state (journal base lines); the rest are the deltas folded over it.
DECISION_KINDS = ("bootstrap", "snapshot", "intent", "commit", "abort")


@dataclasses.dataclass(frozen=True)
class PlacementDecision:
    """One placement transition. ``rank``/``source``/``target`` are
    meaningful for ``intent``/``commit``/``abort``; base records use
    rank -1. ``reason`` is free text for humans and telemetry (inside
    the crc'd line, so it replays byte-identically too)."""

    kind: str
    rank: int = -1
    source: int = -1
    target: int = -1
    reason: str = ""

    def to_dict(self) -> dict:
        return {"kind": self.kind, "rank": self.rank,
                "source": self.source, "target": self.target,
                "reason": self.reason}

    @classmethod
    def from_dict(cls, data: dict) -> "PlacementDecision":
        return cls(kind=data["kind"], rank=int(data["rank"]),
                   source=int(data["source"]), target=int(data["target"]),
                   reason=data.get("reason", ""))


@dataclasses.dataclass(frozen=True)
class PlacementState:
    """One immutable placement: the committed rank->shard ``overrides``
    layered over the static ``rank % num_shards`` arithmetic, the
    placement ``generation`` (bumped once per COMMIT — the wire fence),
    and at most one ``pending`` in-flight move ``(rank, source,
    target)`` between its intent and its commit/abort."""

    num_trainers: int
    num_shards: int
    generation: int
    overrides: Tuple[Tuple[int, int], ...]  # sorted (rank, shard)
    pending: Optional[Tuple[int, int, int]] = None

    def shard_for_rank(self, rank: int) -> int:
        for r, shard in self.overrides:
            if r == rank:
                return shard
        return rank % self.num_shards

    def to_dict(self) -> dict:
        return {"num_trainers": self.num_trainers,
                "num_shards": self.num_shards,
                "generation": self.generation,
                "overrides": [[r, s] for r, s in self.overrides],
                "pending": list(self.pending) if self.pending else None}

    @classmethod
    def from_dict(cls, data: dict) -> "PlacementState":
        pending = data.get("pending")
        return cls(num_trainers=int(data["num_trainers"]),
                   num_shards=int(data["num_shards"]),
                   generation=int(data["generation"]),
                   overrides=tuple((int(r), int(s))
                                   for r, s in data["overrides"]),
                   pending=tuple(int(v) for v in pending)
                   if pending else None)

    @classmethod
    def bootstrap(cls, shard_map: plan_ir.ShardMap) -> "PlacementState":
        return cls(num_trainers=shard_map.num_trainers,
                   num_shards=shard_map.num_shards,
                   generation=shard_map.generation,
                   overrides=tuple(sorted(
                       (int(r), int(s))
                       for r, s in shard_map.overrides.items())))


def apply_decision(state: PlacementState,
                   decision: PlacementDecision) -> PlacementState:
    """THE pure placement-transition function: ``(state, decision) ->
    state``. No wall clock, no randomness, no dict-order dependence — a
    journal is a fold of its decisions over the bootstrap state, and
    :func:`replay` re-runs the fold to prove the journal.

    An ``intent`` whose target is already the rank's home is a no-op
    (returns ``state`` unchanged — the controller never journals it);
    an intent over a pending move, or a commit/abort that does not
    match the pending move, is a protocol violation and raises — the
    two-phase discipline allows exactly one move in flight."""
    if decision.kind not in DECISION_KINDS:
        raise ValueError(
            f"unknown placement decision kind {decision.kind!r}")
    if decision.kind in ("bootstrap", "snapshot"):
        raise ValueError(
            f"{decision.kind} records carry their own state; "
            "apply_decision folds only intent/commit/abort deltas")
    if decision.kind == "intent":
        if state.pending is not None:
            raise ValueError(
                f"intent for rank {decision.rank} while move "
                f"{state.pending} is pending (one move in flight)")
        if not 0 <= decision.rank < state.num_trainers:
            raise ValueError(f"intent for unknown rank {decision.rank}")
        if not 0 <= decision.target < state.num_shards:
            raise ValueError(
                f"intent routes rank {decision.rank} to unknown shard "
                f"{decision.target}")
        source = state.shard_for_rank(decision.rank)
        if decision.source != source:
            raise ValueError(
                f"intent names source {decision.source} but rank "
                f"{decision.rank} lives on shard {source}")
        if decision.target == source:
            return state  # no-op: never journaled, never replayed
        return dataclasses.replace(
            state, pending=(decision.rank, source, decision.target))
    # commit/abort: must resolve THE pending move.
    move = (decision.rank, decision.source, decision.target)
    if state.pending != move:
        raise ValueError(
            f"{decision.kind} for move {move} but pending is "
            f"{state.pending}")
    if decision.kind == "abort":
        return dataclasses.replace(state, pending=None)
    overrides = {r: s for r, s in state.overrides}
    if decision.target == decision.rank % state.num_shards:
        overrides.pop(decision.rank, None)  # back on its static home
    else:
        overrides[decision.rank] = decision.target
    return dataclasses.replace(
        state, generation=state.generation + 1,
        overrides=tuple(sorted(overrides.items())), pending=None)


class RebalanceJournal:
    """Crc'd append-only journal of placement decisions.

    Each line is ``{"decision": ..., "placement": ...}`` in the shared
    :func:`checkpoint.crc_line` discipline: the recorded placement is
    the RESULT of folding the decision over the previous line's state,
    which is what makes the file self-verifying — :func:`replay` re-runs
    the fold and any divergence (tamper, version skew, an unjournaled
    transition) raises. The first line is always a base record
    (``bootstrap``, or ``snapshot`` after :meth:`compact`).

    ``path=None`` keeps the journal in memory; with a path every line
    is flushed + fsync'd BEFORE the decision takes effect anywhere, so
    a crashed driver restarts into the exact decision history it last
    advertised — the abort-trailing-intent recovery hangs off this.
    """

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._lock = threading.Lock()
        self._file = None
        self._lines: List[str] = []

    @property
    def path(self) -> Optional[str]:
        return self._path

    @staticmethod
    def encode(decision: PlacementDecision, state: PlacementState) -> str:
        return ckpt.crc_line({"decision": decision.to_dict(),
                              "placement": state.to_dict()})

    def record(self, decision: PlacementDecision,
               state: PlacementState) -> None:
        line = self.encode(decision, state)
        with self._lock:
            self._lines.append(line)
            if self._path is not None:
                if self._file is None:
                    directory = os.path.dirname(os.path.abspath(self._path))
                    os.makedirs(directory, exist_ok=True)
                    self._file = open(self._path, "a", encoding="utf-8")
                self._file.write(line + "\n")
                self._file.flush()
                os.fsync(self._file.fileno())

    def journal_bytes(self) -> bytes:
        """The journal as emitted (the replay-comparison target)."""
        with self._lock:
            return "".join(line + "\n" for line in self._lines).encode()

    @classmethod
    def load(cls, path: str) -> List[dict]:
        """Every intact ``{"decision", "placement"}`` record in append
        order; a torn TAIL line (crash mid-write) is skipped with a
        warning, but an unreadable line with intact lines after it is
        corruption and raises — an interior gap would silently rewrite
        history."""
        records: List[dict] = []
        bad: Optional[Tuple[int, str]] = None
        if not os.path.exists(path):
            return records
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = ckpt.parse_crc_line(line)
                    record = {"decision": PlacementDecision.from_dict(
                                  entry["decision"]),
                              "placement": PlacementState.from_dict(
                                  entry["placement"]),
                              "line": line}
                except (ValueError, KeyError, TypeError) as e:
                    if bad is not None:
                        raise ValueError(
                            f"rebalance journal {path}: multiple "
                            f"unreadable lines ({bad[0]}: {bad[1]}; "
                            f"{lineno}: {e}) — corruption, not a torn "
                            "tail")
                    bad = (lineno, str(e))
                    continue
                if bad is not None:
                    raise ValueError(
                        f"rebalance journal {path}: line {bad[0]} "
                        f"unreadable ({bad[1]}) but line {lineno} is "
                        "intact — interior corruption, not a torn tail")
                records.append(record)
        if bad is not None:
            logger.warning(
                "rebalance journal %s line %d unreadable (%s); skipping "
                "(torn tail from a crash is expected)", path, bad[0],
                bad[1])
        return records

    def compact(self) -> None:
        """Rewrite the journal as ONE snapshot record of the latest
        state — atomic tmp + fsync + rename (the WatermarkJournal
        discipline), so the append-only file cannot grow unboundedly
        across a long-lived serving plane's churn."""
        assert self._path is not None, "in-memory journals need no compact"
        records = self.load(self._path)
        if not records:
            return
        state = records[-1]["placement"]
        line = self.encode(PlacementDecision(kind="snapshot",
                                             reason="compact"), state)
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            directory = os.path.dirname(os.path.abspath(self._path))
            os.makedirs(directory, exist_ok=True)
            fd, tmp_path = tempfile.mkstemp(dir=directory, suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    f.write(line + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp_path, self._path)
                dir_fd = os.open(directory, os.O_RDONLY)
                try:
                    os.fsync(dir_fd)
                finally:
                    os.close(dir_fd)
            except BaseException:
                if os.path.exists(tmp_path):
                    os.remove(tmp_path)
                raise
            self._lines = [line]

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


def replay(path: str) -> PlacementState:
    """Rebuild the latest placement from a journal and PROVE the
    rebuild: every ``intent``/``commit``/``abort`` record's state must
    equal ``apply_decision(previous_state, decision)`` — re-encoded
    byte-identically against the journaled line — and the journal must
    begin with a base record. Any divergence raises ``ValueError``
    (tamper, corruption, or version skew in the transition function).
    Returns the verified latest state."""
    records = RebalanceJournal.load(path)
    if not records:
        raise ValueError(f"rebalance journal {path} has no records")
    first = records[0]
    if first["decision"].kind not in ("bootstrap", "snapshot"):
        raise ValueError(
            f"rebalance journal {path} does not begin with a "
            f"bootstrap/snapshot record (got {first['decision'].kind!r})")
    state = first["placement"]
    for index, record in enumerate(records[1:], 2):
        decision = record["decision"]
        if decision.kind in ("bootstrap", "snapshot"):
            raise ValueError(
                f"rebalance journal {path} record {index}: base record "
                "after the journal head (history rewrite)")
        derived = apply_decision(state, decision)
        rederived = RebalanceJournal.encode(decision, derived)
        if rederived != record["line"]:
            raise ValueError(
                f"rebalance journal {path} record {index} diverged on "
                f"replay: decision {decision.to_dict()} over generation "
                f"{state.generation} re-derives {derived.to_dict()}, "
                "journal disagrees (tamper, corruption, or transition "
                "version skew)")
        if derived == state:
            raise ValueError(
                f"rebalance journal {path} record {index}: journaled "
                f"no-op decision {decision.to_dict()} (the controller "
                "never journals unchanged placements)")
        state = derived
    return state


class RebalanceController:
    """The placement decision hub: current state + journal + policy.

    Decisions come from the ``tenant_delivery_slo`` health detector (a
    sustained per-tenant delivery-p99 breach names a hot rank), from
    chaos, or from an operator. Each one folds through
    :func:`apply_decision`, is journaled BEFORE any actuator byte
    moves, and emits the ``rebalance_*`` telemetry/metric vocabulary.

    Crash recovery is the whole point of the journal: a controller
    constructed over an existing journal replays it (proving every
    byte) and, if the tail is an uncommitted ``intent``, journals the
    matching ``abort`` — the driver died mid-decision, no COMMIT was
    journaled, so the source shard is authoritative and the move never
    happened. The RSDL_REBALANCE_* knobs gate :meth:`may_move`:
    ``rebalance_cooldown_s`` is the sliding window and
    ``rebalance_max_moves`` the commit budget inside it (so one hot
    tenant cannot ping-pong between shards while its post-move p99
    window drains).
    """

    def __init__(self, shard_map: plan_ir.ShardMap,
                 journal_path: Optional[str] = None,
                 component: str = "rebalance", **overrides: Any):
        resolve = lambda key: rt_policy.resolve(  # noqa: E731
            component, key, override=overrides.get(key))
        self.slo_p99_s = float(resolve("rebalance_slo_p99_s"))
        self.cooldown_s = float(resolve("rebalance_cooldown_s"))
        self.max_moves = int(resolve("rebalance_max_moves"))
        self._lock = threading.Lock()
        self._base_map = shard_map
        self._journal = RebalanceJournal(journal_path)
        self._commit_times: List[float] = []
        self.moves_total = 0
        recovered = journal_path is not None and os.path.exists(
            journal_path) and os.path.getsize(journal_path) > 0
        if recovered:
            self._state = replay(journal_path)
        else:
            self._state = PlacementState.bootstrap(shard_map)
            self._journal.record(PlacementDecision(
                kind="bootstrap", reason="initial placement"), self._state)
        self._export(self._state)
        if recovered and self._state.pending is not None:
            rank, source, target = self._state.pending
            self.abort(rank, reason="controller restart with uncommitted "
                                    "intent: source authoritative")

    # -- state ---------------------------------------------------------

    def current_state(self) -> PlacementState:
        with self._lock:
            return self._state

    def current_map(self) -> plan_ir.ShardMap:
        """The live :class:`plan.ir.ShardMap`: the base addresses with
        this controller's committed overrides and generation applied."""
        state = self.current_state()
        shard_map = plan_ir.ShardMap(
            num_trainers=self._base_map.num_trainers,
            addresses=[tuple(a) for a in self._base_map.addresses],
            version=self._base_map.version,
            overrides={r: s for r, s in state.overrides
                       if s != r % state.num_shards},
            generation=state.generation)
        shard_map.validate()
        return shard_map

    @property
    def journal(self) -> RebalanceJournal:
        return self._journal

    # -- policy gates --------------------------------------------------

    def may_move(self, now: Optional[float] = None) -> bool:
        """True when the commit budget allows another move: fewer than
        ``rebalance_max_moves`` commits inside the trailing
        ``rebalance_cooldown_s`` window."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._commit_times = [t for t in self._commit_times
                                  if now - t < self.cooldown_s]
            return len(self._commit_times) < self.max_moves

    def pick_target(self, rank: int) -> int:
        """The deterministic placement choice: the least-loaded shard
        other than ``rank``'s current home (rank count under the
        current placement; lowest shard index breaks ties)."""
        state = self.current_state()
        source = state.shard_for_rank(rank)
        loads = {shard: 0 for shard in range(state.num_shards)}
        for r in range(state.num_trainers):
            loads[state.shard_for_rank(r)] += 1
        candidates = [(load, shard) for shard, load in sorted(loads.items())
                      if shard != source]
        if not candidates:
            return source
        return min(candidates)[1]

    # -- decisions -----------------------------------------------------

    def begin(self, rank: int, target: Optional[int] = None,
              reason: str = "") -> Optional[PlacementDecision]:
        """Journal an ``intent`` to move ``rank`` (to ``target``, or to
        :meth:`pick_target`'s choice). Returns the decision, or None
        when the move is a no-op (already home) or the commit budget is
        exhausted. The ``rebalance_abort`` chaos site fires HERE, after
        the intent is durable and before any actuator byte moves — the
        "driver killed mid-decision" scenario."""
        if not self.may_move():
            logger.warning(
                "rebalance: move budget exhausted (%d moves / %.1fs "
                "window); skipping rank %d", self.max_moves,
                self.cooldown_s, rank)
            return None
        state = self.current_state()
        source = state.shard_for_rank(rank)
        if target is None:
            target = self.pick_target(rank)
        decision = PlacementDecision(kind="intent", rank=int(rank),
                                     source=int(source),
                                     target=int(target), reason=reason)
        if self._transition(decision) is None:
            return None
        from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
        # Keyed by the move's TARGET generation (the one a commit would
        # stamp) — the same key the actuator sites and their telemetry
        # twins use, so the chaos<->telemetry join holds across phases.
        rt_faults.inject("rebalance_abort", epoch=state.generation + 1,
                         task=int(rank))
        return decision

    def commit(self, rank: int, reason: str = "") -> PlacementState:
        """Journal the COMMIT of the pending move. From this line on
        the target shard owns the rank: the placement generation bumps
        (fencing the source's future frames) and consumers are
        redirected. Strictly ordered BEFORE the source releases."""
        pending = self.current_state().pending
        if pending is None or pending[0] != rank:
            raise ValueError(f"commit for rank {rank} but pending move "
                             f"is {pending}")
        decision = PlacementDecision(kind="commit", rank=pending[0],
                                     source=pending[1], target=pending[2],
                                     reason=reason)
        state = self._transition(decision)
        assert state is not None
        now = time.monotonic()
        with self._lock:
            self._commit_times.append(now)
            self.moves_total += 1
        rt_metrics.counter(
            "rsdl_rebalance_moves_total",
            "committed live queue migrations").inc()
        rt_metrics.gauge(
            "rsdl_rebalance_last_move_unixtime",
            "wall-clock time of the last committed migration").set(
            time.time())
        return state

    def abort(self, rank: int, reason: str = "") -> PlacementState:
        """Journal the ABORT of the pending move: the source shard
        stays authoritative, nothing about the placement changes."""
        pending = self.current_state().pending
        if pending is None or pending[0] != rank:
            raise ValueError(f"abort for rank {rank} but pending move "
                             f"is {pending}")
        decision = PlacementDecision(kind="abort", rank=pending[0],
                                     source=pending[1], target=pending[2],
                                     reason=reason)
        state = self._transition(decision)
        assert state is not None
        return state

    def _transition(self,
                    decision: PlacementDecision) -> Optional[PlacementState]:
        with self._lock:
            state = apply_decision(self._state, decision)
            if state == self._state:
                return None  # no-op: never journaled
            self._state = state
            self._journal.record(decision, state)
        logger.warning(
            "rebalance: %s rank %d shard %d -> %d (generation %d)%s",
            decision.kind, decision.rank, decision.source,
            decision.target, state.generation,
            f" ({decision.reason})" if decision.reason else "")
        # ``epoch`` carries the move's TARGET generation (a commit's
        # post-fold generation IS that number; intent/abort are one
        # short of it) — the join key the chaos sites and the wire
        # actuator's telemetry twins share.
        move_gen = (state.generation if decision.kind == "commit"
                    else state.generation + 1)
        rt_telemetry.record(f"rebalance_{decision.kind}",
                            epoch=(move_gen
                                   if decision.kind in ("intent", "commit",
                                                        "abort")
                                   else state.generation),
                            task=decision.rank,
                            source=decision.source,
                            target=decision.target,
                            generation=state.generation,
                            reason=decision.reason)
        rt_metrics.counter(
            "rsdl_rebalance_decisions_total",
            "journaled placement decisions by kind",
            kind=decision.kind).inc()
        self._export(state)
        return state

    def _export(self, state: PlacementState) -> None:
        rt_metrics.gauge(
            "rsdl_rebalance_generation",
            "current placement generation (bumps once per committed "
            "migration)").set(state.generation)
        rt_metrics.gauge(
            "rsdl_rebalance_overrides",
            "ranks currently living off their static home shard").set(
            len(state.overrides))

    def close(self) -> None:
        self._journal.close()


def migrate(controller: RebalanceController, rank: int,
            target: Optional[int] = None, reason: str = "",
            timeout_s: float = 30.0) -> Optional[PlacementState]:
    """Drive one full two-phase live queue migration end to end:

    1. journal ``intent`` (:meth:`RebalanceController.begin`);
    2. **PREPARE** the source shard over the wire — it seals the rank's
       queues at a watermark and exports a CRC'd handoff manifest
       (unacked replay frames + birth stamps + seq cursors);
    3. **ADOPT** the manifest on the target shard — it imports the
       cursors and frames at the NEW placement generation;
    4. journal ``commit`` (the point of no return — any crash before
       this line recovers as an abort with the source authoritative);
    5. **RELEASE** the source — it drops the rank's queues and answers
       future GETs with a ``MOVED`` redirect to the target's address.

    A wire failure between intent and commit journals an ``abort`` and
    un-seals the source (best effort — a dead source un-seals itself by
    restarting from its watermark journal). Returns the committed state
    or None when no move was begun (budget or no-op)."""
    from ray_shuffling_data_loader_tpu import multiqueue_service as mqs
    decision = controller.begin(rank, target=target, reason=reason)
    if decision is None:
        return None
    shard_map = controller.current_map()
    generation = controller.current_state().generation + 1
    source_addr = tuple(shard_map.addresses[decision.source])
    target_addr = tuple(shard_map.addresses[decision.target])
    try:
        manifest = mqs.rebalance_prepare(source_addr, rank,
                                         generation=generation,
                                         timeout_s=timeout_s)
        mqs.rebalance_adopt(target_addr, manifest, timeout_s=timeout_s)
    except BaseException as e:
        controller.abort(rank, reason=f"handoff failed: {e}")
        try:
            mqs.rebalance_unseal(source_addr, rank, timeout_s=timeout_s)
        except OSError:
            pass  # dead source un-seals itself at restart
        raise
    state = controller.commit(rank, reason=reason)
    try:
        mqs.rebalance_release(source_addr, rank, generation=generation,
                              target=target_addr, timeout_s=timeout_s)
    except OSError as e:
        # Post-commit the target is authoritative regardless; a source
        # that missed RELEASE keeps stamping the old generation and the
        # client fence drops its frames loudly.
        logger.warning("rebalance: release of rank %d on %s failed (%s); "
                       "relying on the generation fence", rank,
                       source_addr, e)
    return state


__all__ = ["PlacementDecision", "PlacementState", "RebalanceJournal",
           "RebalanceController", "apply_decision", "replay", "migrate",
           "DECISION_KINDS"]
