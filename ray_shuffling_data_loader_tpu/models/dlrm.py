"""DLRM-style recommendation model — the flagship (BASELINE.json config 5).

The reference's data is DLRM-shaped (17 embedding-index columns with
cardinalities up to ~945k, 2 small categorical columns, a float label —
reference: data_generation.py:74-95) but it never ships a real model; its
example trainer mocks the step entirely (reference:
ray_torch_shuffle.py:199-204). We provide the real thing, TPU-first:

- per-feature embedding tables with the embedding dim sharded over the
  "model" mesh axis (Megatron column-parallel embeddings: every device
  holds ``embed_dim / model_parallel`` of each table, lookups are local,
  XLA all-gathers the slices — divisibility only constrains embed_dim,
  never the ragged vocab sizes);
- dot-product feature interaction (upper triangle), the DLRM signature;
- bottom/top MLPs with the same alternating column/row TP sharding as
  models/mlp.py;
- bf16 compute, f32 params, one static XLA graph.

Functional API: ``init(config, key)``, ``apply(config, params, dense,
sparse)``, ``loss_fn``, ``param_specs(config)``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_shuffling_data_loader_tpu.models import mlp as mlp_mod
from ray_shuffling_data_loader_tpu.ops import embedding

# The reference DATA_SPEC's categorical cardinalities
# (reference: data_generation.py:74-95): 17 embedding columns + 2 one-hots.
DATA_SPEC_VOCAB_SIZES: Tuple[int, ...] = (
    2385, 201, 201, 6, 19, 1441, 201, 22, 156, 1216, 9216, 88999, 941792,
    9405, 83332, 828767, 945195, 3, 50)


@dataclasses.dataclass(frozen=True)
class DLRMConfig:
    vocab_sizes: Tuple[int, ...] = DATA_SPEC_VOCAB_SIZES
    embed_dim: int = 32
    dense_dim: int = 0  # the reference schema has no dense features
    bottom_hidden: Tuple[int, ...] = (64,)
    top_hidden: Tuple[int, ...] = (512, 256)
    compute_dtype: Any = jnp.bfloat16
    # Embedding lookup strategy (ops/embedding.py): "auto" sends small
    # tables through a one-hot MXU matmul and large ones through XLA
    # gather; "pallas" opts into the scalar-prefetch Pallas kernel.
    lookup_mode: str = "auto"

    @property
    def num_sparse(self) -> int:
        return len(self.vocab_sizes)

    @property
    def num_interacting(self) -> int:
        # Dense branch contributes one embed_dim vector when present.
        return self.num_sparse + (1 if self.dense_dim > 0 else 0)

    @property
    def interaction_dim(self) -> int:
        n = self.num_interacting
        return n * (n - 1) // 2

    @property
    def top_in_dim(self) -> int:
        base = self.interaction_dim
        if self.dense_dim > 0:
            base += self.embed_dim
        else:
            # Without a dense branch, also feed the mean embedding so the
            # top MLP sees first-order signal, not only interactions.
            base += self.embed_dim
        return base


def _mlp_cfg(in_dim: int, hidden: Tuple[int, ...], out_dim: int,
             dtype) -> mlp_mod.MLPConfig:
    return mlp_mod.MLPConfig(in_dim=in_dim, hidden_dims=hidden,
                             out_dim=out_dim, compute_dtype=dtype)


def init(config: DLRMConfig, key: jax.Array) -> Dict[str, Any]:
    keys = jax.random.split(key, config.num_sparse + 2)
    params: Dict[str, Any] = {"embeddings": {}}
    for i, vocab in enumerate(config.vocab_sizes):
        params["embeddings"][f"table_{i}"] = (
            jax.random.normal(keys[i], (vocab, config.embed_dim),
                              jnp.float32) / jnp.sqrt(config.embed_dim))
    if config.dense_dim > 0:
        params["bottom"] = mlp_mod.init(
            _mlp_cfg(config.dense_dim, config.bottom_hidden,
                     config.embed_dim, config.compute_dtype),
            keys[config.num_sparse])
    params["top"] = mlp_mod.init(
        _mlp_cfg(config.top_in_dim, config.top_hidden, 1,
                 config.compute_dtype),
        keys[config.num_sparse + 1])
    return params


def param_specs(config: DLRMConfig, model_axis: str = "model"
                ) -> Dict[str, Any]:
    """Embedding dim column-sharded over the model axis; MLPs Megatron-TP."""
    specs: Dict[str, Any] = {
        "embeddings": {
            f"table_{i}": P(None, model_axis)
            for i in range(config.num_sparse)
        }
    }
    if config.dense_dim > 0:
        specs["bottom"] = mlp_mod.param_specs(
            _mlp_cfg(config.dense_dim, config.bottom_hidden,
                     config.embed_dim, config.compute_dtype), model_axis)
    specs["top"] = mlp_mod.param_specs(
        _mlp_cfg(config.top_in_dim, config.top_hidden, 1,
                 config.compute_dtype), model_axis)
    return specs


def apply(config: DLRMConfig, params: Dict[str, Any],
          dense: Optional[jax.Array], sparse) -> jax.Array:
    """Forward: sparse is a (batch, num_sparse) int index array OR a list
    of per-feature (batch,)/(batch, 1) index arrays — the latter is what
    ``JaxShufflingDataset`` yields with per-column narrow dtypes
    (workloads/dlrm_criteo.py). dense (batch, dense_dim) or None.
    Returns (batch, 1) f32 logits."""
    dtype = config.compute_dtype
    is_columns = isinstance(sparse, (list, tuple))
    if is_columns and len(sparse) != config.num_sparse:
        raise ValueError(
            f"expected {config.num_sparse} sparse columns, got "
            f"{len(sparse)}")
    # One embedding lookup per feature (ops/embedding.py picks the hardware
    # path per table size). Tables are stacked feature-wise afterwards.
    vectors = []
    for i in range(config.num_sparse):
        idx = sparse[i].reshape(-1) if is_columns else sparse[:, i]
        vectors.append(
            embedding.lookup(params["embeddings"][f"table_{i}"], idx,
                             dtype, mode=config.lookup_mode))
    if config.dense_dim > 0:
        bottom_cfg = _mlp_cfg(config.dense_dim, config.bottom_hidden,
                              config.embed_dim, dtype)
        vectors.append(
            mlp_mod.apply(bottom_cfg, params["bottom"],
                          dense).astype(dtype))
    stacked = jnp.stack(vectors, axis=1)  # (batch, F, embed_dim)
    # Dot interaction: upper triangle of the F x F Gram matrix — one
    # batched matmul on the MXU (the DLRM signature op).
    gram = jnp.einsum("bfe,bge->bfg", stacked, stacked)
    f = stacked.shape[1]
    iu, ju = jnp.triu_indices(f, k=1)
    interactions = gram[:, iu, ju]  # (batch, F*(F-1)/2)
    first_order = jnp.mean(stacked, axis=1)  # (batch, embed_dim)
    top_in = jnp.concatenate(
        [interactions, first_order], axis=1).astype(dtype)
    top_cfg = _mlp_cfg(config.top_in_dim, config.top_hidden, 1, dtype)
    return mlp_mod.apply(top_cfg, params["top"], top_in)


def validate_sparse_batch(config: DLRMConfig, sparse) -> None:
    """Host-side bounds check for a sparse index batch.

    ``apply`` clips out-of-range indices on device (a stray bad index must
    not NaN a step), which also means a *systematically* broken pipeline
    would train silently on edge rows — run this on the host batch (e.g.
    every N steps or in a debug mode) to surface corruption loudly.
    Accepts both batch layouts ``apply`` does: one (batch, num_sparse)
    array or a list of per-feature (batch,)/(batch, 1) columns.
    """
    import numpy as np
    if isinstance(sparse, (list, tuple)):
        if len(sparse) != config.num_sparse:
            raise ValueError(
                f"expected {config.num_sparse} sparse columns, got "
                f"{len(sparse)}")
        columns = [np.asarray(c).reshape(-1) for c in sparse]
    else:
        arr = np.asarray(sparse)
        if arr.shape[-1] != config.num_sparse:
            raise ValueError(
                f"expected {config.num_sparse} sparse features, got "
                f"{arr.shape[-1]}")
        columns = [arr[:, i] for i in range(config.num_sparse)]
    for i, (col, vocab) in enumerate(zip(columns, config.vocab_sizes)):
        lo, hi = col.min(), col.max()
        if lo < 0 or hi >= vocab:
            raise ValueError(
                f"sparse feature {i} has indices in [{lo}, {hi}] "
                f"outside vocab [0, {vocab})")


def loss_fn(config: DLRMConfig, params: Dict[str, Any],
            dense: Optional[jax.Array], sparse,
            labels: jax.Array) -> jax.Array:
    """Sigmoid BCE-with-logits, mean over the batch."""
    logits = apply(config, params, dense, sparse)
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * labels
        + jnp.log1p(jnp.exp(-jnp.abs(logits))))
