"""BERT-style masked-LM — sequence model family (BASELINE.json config 4:
BERT-base MLM on pre-tokenized Wikipedia Parquet).

The reference treats sequence workloads purely as "batch pre-tokenized
fixed-length rows" (SURVEY.md §5: no sequence-parallel machinery exists or
is needed); the loader delivers (batch, seq_len) int token tables and this
model consumes them. Same functional API as the other families: ``init``,
``apply``, ``loss_fn``, ``param_specs``.

TPU-first choices:
- Megatron TP sharding spec: QKV and FFN-in split column-wise over
  "model", attention-out and FFN-out split row-wise, so each transformer
  block needs exactly two psums; embeddings column-sharded.
- bf16 compute / f32 params & softmax accumulation; static seq_len, fused
  QKV projection; attention is two batched matmuls on the MXU.
- MLM loss masks with a -100 ignore-id convention (positions to predict
  carry their target id, others -100).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

IGNORE_ID = -100


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30_522
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_dim: int = 3072
    max_seq_len: int = 512
    compute_dtype: Any = jnp.bfloat16
    # Rematerialize each transformer layer in the backward pass instead of
    # saving its activations — trades ~30% more FLOPs for O(num_layers)
    # less activation HBM, the standard long-context/large-batch knob
    # (jax.checkpoint; composes with ring-attention SP, whose custom VJP
    # already recomputes per hop).
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_dim // self.num_heads


def bert_base() -> BertConfig:
    return BertConfig()


def bert_tiny() -> BertConfig:
    """For tests/CPU smoke runs."""
    return BertConfig(vocab_size=1000, hidden_dim=64, num_layers=2,
                      num_heads=4, ffn_dim=128, max_seq_len=64)


def init(config: BertConfig, key: jax.Array) -> Dict[str, Any]:
    h, f = config.hidden_dim, config.ffn_dim
    keys = iter(jax.random.split(key, 3 + config.num_layers * 6))
    scale = 0.02
    params: Dict[str, Any] = {
        "token_emb": scale * jax.random.normal(
            next(keys), (config.vocab_size, h), jnp.float32),
        "pos_emb": scale * jax.random.normal(
            next(keys), (config.max_seq_len, h), jnp.float32),
        "emb_ln": {"scale": jnp.ones((h,), jnp.float32),
                   "bias": jnp.zeros((h,), jnp.float32)},
    }
    for layer in range(config.num_layers):
        lp = {
            "qkv_w": scale * jax.random.normal(next(keys), (h, 3 * h),
                                               jnp.float32),
            "qkv_b": jnp.zeros((3 * h,), jnp.float32),
            "attn_out_w": scale * jax.random.normal(next(keys), (h, h),
                                                    jnp.float32),
            "attn_out_b": jnp.zeros((h,), jnp.float32),
            "ln1": {"scale": jnp.ones((h,), jnp.float32),
                    "bias": jnp.zeros((h,), jnp.float32)},
            "ffn_in_w": scale * jax.random.normal(next(keys), (h, f),
                                                  jnp.float32),
            "ffn_in_b": jnp.zeros((f,), jnp.float32),
            "ffn_out_w": scale * jax.random.normal(next(keys), (f, h),
                                                   jnp.float32),
            "ffn_out_b": jnp.zeros((h,), jnp.float32),
            "ln2": {"scale": jnp.ones((h,), jnp.float32),
                    "bias": jnp.zeros((h,), jnp.float32)},
        }
        params[f"layer_{layer}"] = lp
    params["mlm_bias"] = jnp.zeros((config.vocab_size,), jnp.float32)
    return params


def param_specs(config: BertConfig, model_axis: str = "model"
                ) -> Dict[str, Any]:
    ln = {"scale": P(None), "bias": P(None)}
    specs: Dict[str, Any] = {
        "token_emb": P(None, model_axis),
        "pos_emb": P(None, model_axis),
        "emb_ln": dict(ln),
        "mlm_bias": P(None),
    }
    for layer in range(config.num_layers):
        specs[f"layer_{layer}"] = {
            "qkv_w": P(None, model_axis),
            "qkv_b": P(model_axis),
            "attn_out_w": P(model_axis, None),
            "attn_out_b": P(None),
            "ln1": dict(ln),
            "ffn_in_w": P(None, model_axis),
            "ffn_in_b": P(model_axis),
            "ffn_out_w": P(model_axis, None),
            "ffn_out_b": P(None),
            "ln2": dict(ln),
        }
    return specs


def _layer_norm(x, scale, bias, eps=1e-12):
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = xf.var(axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps) * scale + bias
    return out.astype(x.dtype)


def apply(config: BertConfig, params: Dict[str, Any],
          token_ids: jax.Array,
          attention_mask: jax.Array = None,
          attention_fn=None) -> jax.Array:
    """token_ids (B, S) int32 -> logits (B, S, vocab).

    ``attention_mask`` (B, S) with 1 = attend, 0 = padding; None = all 1.

    ``attention_fn(q, k, v, bias) -> (B, H, S, D)`` swaps the attention
    implementation — e.g. ``ops.ring_attention.make_attention_fn(mesh,
    seq_axis)`` for sequence-parallel long-context runs, or the Pallas
    flash kernel. None = inline full attention on the MXU.
    """
    dtype = config.compute_dtype
    b, s = token_ids.shape
    h, nh, hd = config.hidden_dim, config.num_heads, config.head_dim

    x = (jnp.take(params["token_emb"], token_ids, axis=0, mode="clip")
         + params["pos_emb"][:s][None, :, :]).astype(dtype)
    x = _layer_norm(x, params["emb_ln"]["scale"], params["emb_ln"]["bias"])

    if attention_mask is None:
        bias = None  # no mask: skip the zero-add (and any SP bias rotation)
    else:
        bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0,
                         -1e9).astype(jnp.float32)

    def layer_fn(x, lp, bias):
        qkv = x @ lp["qkv_w"].astype(dtype) + lp["qkv_b"].astype(dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)
        if attention_fn is not None:
            attended = attention_fn(q, k, v, bias)
        else:
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
            scores = scores / jnp.sqrt(hd)
            if bias is not None:
                scores = scores + bias
            weights = jax.nn.softmax(scores, axis=-1).astype(dtype)
            attended = jnp.einsum("bhqk,bhkd->bhqd", weights, v)
        attended = attended.transpose(0, 2, 1, 3).reshape(b, s, h)
        attn_out = (attended @ lp["attn_out_w"].astype(dtype)
                    + lp["attn_out_b"].astype(dtype))
        x = _layer_norm(x + attn_out, lp["ln1"]["scale"], lp["ln1"]["bias"])
        ffn = jax.nn.gelu(x @ lp["ffn_in_w"].astype(dtype)
                          + lp["ffn_in_b"].astype(dtype))
        ffn = (ffn @ lp["ffn_out_w"].astype(dtype)
               + lp["ffn_out_b"].astype(dtype))
        return _layer_norm(x + ffn, lp["ln2"]["scale"], lp["ln2"]["bias"])

    if config.remat:
        layer_fn = jax.checkpoint(layer_fn)
    for layer in range(config.num_layers):
        x = layer_fn(x, params[f"layer_{layer}"], bias)

    # MLM head: tied to the token embedding (standard BERT).
    logits = jnp.einsum("bsh,vh->bsv", x,
                        params["token_emb"].astype(dtype))
    return logits.astype(jnp.float32) + params["mlm_bias"]


def loss_fn(config: BertConfig, params: Dict[str, Any],
            token_ids: jax.Array, mlm_targets: jax.Array,
            attention_mask: jax.Array = None,
            attention_fn=None) -> jax.Array:
    """Masked-LM cross-entropy over positions where targets != IGNORE_ID."""
    logits = apply(config, params, token_ids, attention_mask, attention_fn)
    mask = (mlm_targets != IGNORE_ID)
    safe_targets = jnp.where(mask, mlm_targets, 0).astype(jnp.int32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    token_logp = jnp.take_along_axis(
        logp, safe_targets[..., None], axis=-1)[..., 0]
    total = jnp.sum(jnp.where(mask, -token_logp, 0.0))
    count = jnp.maximum(jnp.sum(mask), 1)
    return total / count
