"""Tabular MLP — the minimal end-to-end model (BASELINE.json config 1/2).

Replaces the reference example's torch ``Net`` (reference:
examples/horovod/ray_torch_shuffle.py:106-123, a 4-layer 22->512->...->1
MLP over the DLRM-style tabular features) with a functional JAX model:
``init(key) -> params`` pytree, ``apply(params, batch) -> logits``, plus a
``param_specs`` tree of ``PartitionSpec``s so the same model runs replicated
(DP) or Megatron-style tensor-parallel (hidden dim sharded over the
"model" mesh axis) under jit without code changes.

Design notes for TPU: all math is matmul-shaped for the MXU, compute dtype
is bfloat16 with float32 params/accumulation, and layers are static Python
loops over fixed shapes (one XLA graph, no dynamic control flow).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 22
    hidden_dims: Tuple[int, ...] = (512, 256, 128)
    out_dim: int = 1
    compute_dtype: Any = jnp.bfloat16

    @property
    def dims(self) -> Tuple[int, ...]:
        return (self.in_dim, *self.hidden_dims, self.out_dim)


def init(config: MLPConfig, key: jax.Array) -> Dict[str, Any]:
    """He-initialized params, float32."""
    params: Dict[str, Any] = {}
    dims = config.dims
    keys = jax.random.split(key, len(dims) - 1)
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        params[f"w{i}"] = jax.random.normal(
            keys[i], (d_in, d_out), jnp.float32) * jnp.sqrt(2.0 / d_in)
        params[f"b{i}"] = jnp.zeros((d_out,), jnp.float32)
    return params


def param_specs(config: MLPConfig, model_axis: str = "model"
                ) -> Dict[str, P]:
    """Megatron-style alternating column/row sharding of hidden layers.

    Even layers split the output dim over ``model_axis``; odd layers split
    the input dim, so activations stay sharded between the pair and XLA
    inserts a single psum per pair — the standard TP pattern.
    The final layer is replicated (out_dim=1 is unshardable).
    """
    specs: Dict[str, P] = {}
    n_layers = len(config.dims) - 1
    for i in range(n_layers):
        last = i == n_layers - 1
        if last:
            specs[f"w{i}"] = P(None, None)
            specs[f"b{i}"] = P(None)
        elif i % 2 == 0:
            specs[f"w{i}"] = P(None, model_axis)
            specs[f"b{i}"] = P(model_axis)
        else:
            specs[f"w{i}"] = P(model_axis, None)
            specs[f"b{i}"] = P(None)
    return specs


def apply(config: MLPConfig, params: Dict[str, Any],
          features: jax.Array) -> jax.Array:
    """Forward pass: bf16 matmuls, f32 output logits, shape (batch, out_dim)."""
    x = features.astype(config.compute_dtype)
    n_layers = len(config.dims) - 1
    for i in range(n_layers):
        w = params[f"w{i}"].astype(config.compute_dtype)
        b = params[f"b{i}"].astype(config.compute_dtype)
        x = x @ w + b
        if i < n_layers - 1:
            x = jax.nn.relu(x)
    return x.astype(jnp.float32)


def loss_fn(config: MLPConfig, params: Dict[str, Any], features: jax.Array,
            labels: jax.Array) -> jax.Array:
    """Sigmoid binary cross-entropy (the reference example's BCELoss,
    reference: ray_torch_shuffle.py:168)."""
    logits = apply(config, params, features)
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * labels
        + jnp.log1p(jnp.exp(-jnp.abs(logits))))
