"""ResNet — image model family (BASELINE.json config 3:
ResNet-50 on ImageNet Parquet shards).

The reference never ships an image model (its only net is the tabular MLP
in examples/horovod/ray_torch_shuffle.py:106-123); this covers the
ResNet-50/ImageNet-Parquet target workload with the same functional API as
the other model families: ``init``, ``apply``, ``loss_fn``, ``param_specs``.

TPU-first choices:
- **GroupNorm instead of BatchNorm**: no running statistics, so the model
  stays a pure function (no mutable state threading through jit) and needs
  no cross-replica batch-stat sync; standard practice for JAX ResNets.
- NHWC layout (XLA's native conv layout on TPU), bf16 compute with f32
  params, 3x3/1x1 convs that tile straight onto the MXU.
- TP sharding spec: conv output channels over the "model" axis for the
  widest (stage-3/4) blocks, final FC Megatron-split.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    num_classes: int = 1000
    num_groups: int = 32
    compute_dtype: Any = jnp.bfloat16
    # Rematerialize each residual block in the backward pass
    # (jax.checkpoint): ~30% extra FLOPs for O(depth) less activation HBM.
    remat: bool = False


def resnet50(num_classes: int = 1000) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(3, 4, 6, 3), num_classes=num_classes)


def resnet18_cifar(num_classes: int = 10) -> ResNetConfig:
    """Small variant for tests/CPU smoke runs."""
    return ResNetConfig(stage_sizes=(1, 1), width=16, num_classes=num_classes,
                        num_groups=8)


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout),
                             jnp.float32) * jnp.sqrt(2.0 / fan_in)


def _gn_params(c):
    return {"scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32)}


def init(config: ResNetConfig, key: jax.Array) -> Dict[str, Any]:
    params: Dict[str, Any] = {}
    n_stages = len(config.stage_sizes)
    keys = iter(jax.random.split(key, 4 + sum(config.stage_sizes) * 4))
    params["stem_conv"] = _conv_init(next(keys), 7, 7, 3, config.width)
    params["stem_gn"] = _gn_params(config.width)
    cin = config.width
    for stage, num_blocks in enumerate(config.stage_sizes):
        cmid = config.width * (2 ** stage)
        cout = cmid * 4
        for block in range(num_blocks):
            name = f"s{stage}b{block}"
            params[f"{name}_conv1"] = _conv_init(next(keys), 1, 1, cin, cmid)
            params[f"{name}_gn1"] = _gn_params(cmid)
            params[f"{name}_conv2"] = _conv_init(next(keys), 3, 3, cmid, cmid)
            params[f"{name}_gn2"] = _gn_params(cmid)
            params[f"{name}_conv3"] = _conv_init(next(keys), 1, 1, cmid, cout)
            params[f"{name}_gn3"] = _gn_params(cout)
            if block == 0:
                params[f"{name}_proj"] = _conv_init(next(keys), 1, 1, cin,
                                                    cout)
                params[f"{name}_proj_gn"] = _gn_params(cout)
            cin = cout
    params["fc_w"] = jax.random.normal(
        next(keys), (cin, config.num_classes),
        jnp.float32) * jnp.sqrt(1.0 / cin)
    params["fc_b"] = jnp.zeros((config.num_classes,), jnp.float32)
    return params


def _param_names(config: ResNetConfig):
    yield "stem_conv"
    yield "stem_gn"
    for stage, num_blocks in enumerate(config.stage_sizes):
        for block in range(num_blocks):
            name = f"s{stage}b{block}"
            for suffix in ("_conv1", "_gn1", "_conv2", "_gn2", "_conv3",
                           "_gn3"):
                yield name + suffix
            if block == 0:
                yield name + "_proj"
                yield name + "_proj_gn"
    yield "fc_w"
    yield "fc_b"


def param_specs(config: ResNetConfig, model_axis: str = "model"
                ) -> Dict[str, Any]:
    """Channel-sharded convs (output-channel dim over the model axis);
    GN params replicated; final FC Megatron-split on its input dim."""
    specs: Dict[str, Any] = {}
    for name in _param_names(config):
        if "_gn" in name or name == "stem_gn":
            specs[name] = {"scale": P(None), "bias": P(None)}
        elif name == "fc_w":
            specs[name] = P(model_axis, None)
        elif name == "fc_b":
            specs[name] = P(None)
        else:  # conv kernels (kh, kw, cin, cout): shard cout
            specs[name] = P(None, None, None, model_axis)
    return specs


def _group_norm(x, scale, bias, num_groups, eps=1e-5):
    n, h, w, c = x.shape
    groups = min(num_groups, c)
    while c % groups:
        groups -= 1
    xg = x.reshape(n, h, w, groups, c // groups).astype(jnp.float32)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    out = xg.reshape(n, h, w, c)
    return (out * scale + bias).astype(x.dtype)


def _conv(x, kernel, stride=1):
    return jax.lax.conv_general_dilated(
        x, kernel.astype(x.dtype), window_strides=(stride, stride),
        padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def apply(config: ResNetConfig, params: Dict[str, Any],
          images: jax.Array) -> jax.Array:
    """images (N, H, W, 3) -> logits (N, num_classes)."""
    dtype = config.compute_dtype
    x = images.astype(dtype)
    x = _conv(x, params["stem_conv"], stride=2)
    x = _group_norm(x, params["stem_gn"]["scale"], params["stem_gn"]["bias"],
                    config.num_groups)
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
    def block_fn(x, block_params, stride, has_proj):
        residual = x
        y = _conv(x, block_params["conv1"])
        y = _group_norm(y, block_params["gn1"]["scale"],
                        block_params["gn1"]["bias"], config.num_groups)
        y = jax.nn.relu(y)
        y = _conv(y, block_params["conv2"], stride=stride)
        y = _group_norm(y, block_params["gn2"]["scale"],
                        block_params["gn2"]["bias"], config.num_groups)
        y = jax.nn.relu(y)
        y = _conv(y, block_params["conv3"])
        y = _group_norm(y, block_params["gn3"]["scale"],
                        block_params["gn3"]["bias"], config.num_groups)
        if has_proj:
            residual = _conv(residual, block_params["proj"], stride=stride)
            residual = _group_norm(residual,
                                   block_params["proj_gn"]["scale"],
                                   block_params["proj_gn"]["bias"],
                                   config.num_groups)
        return jax.nn.relu(y + residual)

    if config.remat:
        block_fn = jax.checkpoint(block_fn, static_argnums=(2, 3))
    for stage, num_blocks in enumerate(config.stage_sizes):
        for block in range(num_blocks):
            name = f"s{stage}b{block}"
            stride = 2 if (stage > 0 and block == 0) else 1
            has_proj = f"{name}_proj" in params
            block_params = {
                key: params[f"{name}_{key}"]
                for key in ("conv1", "gn1", "conv2", "gn2", "conv3", "gn3")
            }
            if has_proj:
                block_params["proj"] = params[f"{name}_proj"]
                block_params["proj_gn"] = params[f"{name}_proj_gn"]
            x = block_fn(x, block_params, stride, has_proj)
    x = x.mean(axis=(1, 2))  # global average pool
    logits = (x @ params["fc_w"].astype(dtype)
              + params["fc_b"].astype(dtype))
    return logits.astype(jnp.float32)


def loss_fn(config: ResNetConfig, params: Dict[str, Any],
            images: jax.Array, labels: jax.Array) -> jax.Array:
    """Softmax cross-entropy; labels are int class ids (N,) or (N, 1)."""
    logits = apply(config, params, images)
    labels = labels.reshape(-1).astype(jnp.int32)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(
        logp, labels[:, None], axis=1))
