"""Per-epoch map/reduce shuffle engine with epoch pipelining.

Capability parity with the reference's shuffle engine (reference:
shuffle.py:21-263): per epoch, one map task per Parquet file uniformly
scatters its rows across ``num_reducers`` reducers; one reduce task per
reducer concatenates its chunks from every file and permutes them; reducer
outputs are routed round-robin-contiguously to trainers via a
``batch_consumer`` callback followed by a ``None`` sentinel; shuffles for up
to ``max_concurrent_epochs`` epochs run concurrently with consumption,
throttled so host memory stays bounded.

TPU-native design differences:

- Tasks are host threads on the TPU-VM (executor.py), not Ray tasks; data
  is pyarrow Tables, not pandas DataFrames — Arrow's C++ kernels (Parquet
  decode, take, concat) release the GIL and its buffers are the zero-copy
  data plane that plasma provided externally (SURVEY.md §2.3).
- Map->reduce dependencies resolve by submission order: per epoch all maps
  are submitted before any reduce, and the FIFO thread pool guarantees a
  blocked reduce only ever waits on maps that already hold or preceded its
  worker slot, so the pattern is deadlock-free at any pool size.
- Every random draw is keyed by (seed, epoch, task) — the reference's
  unseeded ``np.random.randint`` / ``df.sample`` (reference: shuffle.py:213,
  240) made epochs irreproducible; ours replay bit-identically, which is
  what makes loader checkpoint/resume possible.
- The reference's reduce bug that turns a 1-row batch into a column lookup
  (``if len(batch) == 1: batch = batch[0]``, reference: shuffle.py:241-242)
  is intentionally not replicated.
"""

from __future__ import annotations

import timeit
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import pyarrow as pa
import pyarrow.parquet as pq

from ray_shuffling_data_loader_tpu import executor as ex
from ray_shuffling_data_loader_tpu import stats as stats_mod
from ray_shuffling_data_loader_tpu.ops import partition as ops
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

# batch_consumer(rank, epoch, refs_or_None) — refs are TaskRefs resolving to
# pyarrow Tables (reference passes ObjectRefs of DataFrames,
# reference: dataset.py:213-224).
BatchConsumer = Callable[[int, int, Optional[Sequence[ex.TaskRef]]], None]


def shuffle_map(filename: str,
                num_reducers: int,
                seed: int,
                epoch: int,
                file_index: int,
                stats_collector=None) -> List[pa.Table]:
    """Read one file and scatter its rows into per-reducer tables
    (reference: shuffle.py:199-226)."""
    if stats_collector is not None:
        stats_collector.map_start(epoch)
    start = timeit.default_timer()
    table = pq.read_table(filename)
    end_read = timeit.default_timer()
    rng = ops.map_rng(seed, epoch, file_index)
    assignments = ops.assign_reducers(table.num_rows, num_reducers, rng)
    index_parts = ops.partition_indices(assignments, num_reducers)
    parts = [table.take(idx) for idx in index_parts]
    if stats_collector is not None:
        stats_collector.map_done(epoch, timeit.default_timer() - start,
                                 end_read - start)
    return parts


def shuffle_reduce(reduce_index: int,
                   seed: int,
                   epoch: int,
                   chunks: Sequence[pa.Table],
                   stats_collector=None) -> pa.Table:
    """Concatenate one chunk per file and permute the rows
    (reference: shuffle.py:229-247)."""
    if stats_collector is not None:
        stats_collector.reduce_start(epoch)
    start = timeit.default_timer()
    table = pa.concat_tables(chunks)
    perm = ops.permutation(table.num_rows,
                           ops.reduce_rng(seed, epoch, reduce_index))
    shuffled = table.take(perm)
    if stats_collector is not None:
        stats_collector.reduce_done(epoch, timeit.default_timer() - start)
    return shuffled


def _reduce_task(reduce_index: int, seed: int, epoch: int,
                 map_refs: Sequence[ex.TaskRef], stats_collector) -> pa.Table:
    """Executor wrapper: resolve this reducer's chunk from every map output.

    Equivalent of Ray resolving ``shuffle_reduce.remote(*refs)`` argument
    refs (reference: shuffle.py:182-187) — but we fetch only column slice
    ``reduce_index`` of each map result, zero-copy.
    """
    chunks = [ref.result()[reduce_index] for ref in map_refs]
    return shuffle_reduce(reduce_index, seed, epoch, chunks, stats_collector)


def consume(trainer_idx: int,
            batch_consumer: BatchConsumer,
            trial_start: float,
            stats_collector,
            epoch: int,
            batches: List[ex.TaskRef]) -> None:
    """Hand one trainer its epoch's reducer refs (reference: shuffle.py:250-263)."""
    if stats_collector is not None:
        stats_collector.consume_start(epoch)
    start = timeit.default_timer()
    trial_time_to_consume = start - trial_start
    batch_consumer(trainer_idx, epoch, batches)
    if stats_collector is not None:
        stats_collector.consume_done(epoch, timeit.default_timer() - start,
                                     trial_time_to_consume)


def shuffle_epoch(epoch: int,
                  filenames: Sequence[str],
                  batch_consumer: BatchConsumer,
                  num_reducers: int,
                  num_trainers: int,
                  pool: ex.Executor,
                  seed: int,
                  trial_start: float,
                  stats_collector=None) -> List[ex.TaskRef]:
    """Launch one epoch's map/reduce and route outputs to trainers
    (reference: shuffle.py:163-196). Returns the reducer TaskRefs."""
    if stats_collector is not None:
        stats_collector.epoch_start(epoch)
    map_refs = [
        pool.submit(shuffle_map, filename, num_reducers, seed, epoch,
                    file_index, stats_collector)
        for file_index, filename in enumerate(filenames)
    ]
    reduce_refs = [
        pool.submit(_reduce_task, reduce_index, seed, epoch, map_refs,
                    stats_collector)
        for reduce_index in range(num_reducers)
    ]
    for trainer_idx, batches in enumerate(
            ops.contiguous_splits(reduce_refs, num_trainers)):
        consume(trainer_idx, batch_consumer, trial_start, stats_collector,
                epoch, batches)
        # Epoch-end sentinel per trainer (reference: shuffle.py:195).
        batch_consumer(trainer_idx, epoch, None)
    return reduce_refs


def shuffle(filenames: Sequence[str],
            batch_consumer: BatchConsumer,
            num_epochs: int,
            num_reducers: int,
            num_trainers: int,
            max_concurrent_epochs: int = 2,
            seed: int = 0,
            num_workers: Optional[int] = None,
            collect_stats: bool = True,
            pool: Optional[ex.Executor] = None,
            start_epoch: int = 0
            ) -> Union[stats_mod.TrialStats, float]:
    """Multi-epoch pipelined shuffle driver (reference: shuffle.py:79-160).

    Keeps at most ``max_concurrent_epochs`` epochs' shuffles in flight:
    before launching epoch E, blocks on the oldest incomplete epoch's
    reducers and then drops their refs so Arrow buffers already consumed
    by trainers can be freed (reference: shuffle.py:103-140).

    ``start_epoch`` > 0 (checkpoint resume) skips shuffling the already-
    fully-consumed epochs; epoch PRNG keys depend only on (seed, epoch),
    so the produced epochs replay exactly.

    Returns ``TrialStats`` when ``collect_stats`` else the wall-clock
    duration in seconds (reference: shuffle.py:155-160).
    """
    if not 0 <= start_epoch <= num_epochs:
        raise ValueError(
            f"start_epoch {start_epoch} out of range [0, {num_epochs}]")
    stats_collector = None
    if collect_stats:
        if start_epoch:
            raise ValueError(
                "collect_stats with start_epoch > 0 is unsupported (stats "
                "collectors assume all epochs run)")
        stats_collector = stats_mod.TrialStatsCollector(
            num_epochs, num_maps=len(filenames), num_reduces=num_reducers,
            num_consumes=num_trainers)
        stats_collector.trial_start()
    start = timeit.default_timer()

    owns_pool = pool is None
    if pool is None:
        pool = ex.Executor(num_workers=num_workers)
    try:
        in_progress: Dict[int, List[ex.TaskRef]] = {}
        for epoch_idx in range(start_epoch, num_epochs):
            throttle_start = timeit.default_timer()
            while len(in_progress) >= max_concurrent_epochs:
                oldest_epoch = min(in_progress)
                refs = in_progress.pop(oldest_epoch)
                ex.wait(refs, num_returns=len(refs))
                for ref in refs:
                    ref.result()  # propagate map/reduce failures (instant)
                # Refs dropped here -> reducer Tables release once trainers
                # finish with them (reference: shuffle.py:131-132).
            throttle_duration = timeit.default_timer() - throttle_start
            if stats_collector is not None and throttle_duration > 1e-4:
                stats_collector.throttle_done(epoch_idx, throttle_duration)
            if throttle_duration > 1e-4:
                logger.info("epoch %d throttled for %.3fs", epoch_idx,
                            throttle_duration)
            in_progress[epoch_idx] = shuffle_epoch(
                epoch_idx, filenames, batch_consumer, num_reducers,
                num_trainers, pool, seed, start, stats_collector)
        # Final drain: wait for all remaining reducer tasks
        # (reference: shuffle.py:148-151).
        for epoch_idx in sorted(in_progress):
            refs = in_progress.pop(epoch_idx)
            ex.wait(refs, num_returns=len(refs))
            for ref in refs:
                ref.result()  # propagate map/reduce failures (instant)
    finally:
        if owns_pool:
            pool.shutdown()

    if stats_collector is not None:
        stats_collector.trial_done()
        return stats_collector.get_stats()
    return timeit.default_timer() - start


def shuffle_with_stats(
        filenames: Sequence[str],
        batch_consumer: BatchConsumer,
        num_epochs: int,
        num_reducers: int,
        num_trainers: int,
        max_concurrent_epochs: int = 2,
        seed: int = 0,
        num_workers: Optional[int] = None,
        utilization_sample_period: float = 5.0
) -> Tuple[stats_mod.TrialStats, List]:
    """Shuffle plus a concurrent memory-utilization sampler thread
    (reference: shuffle.py:21-55)."""
    store_stats: List = []
    done_event = stats_mod.start_store_stats_sampler(
        store_stats, sample_period_s=utilization_sample_period)
    try:
        trial_stats = shuffle(filenames, batch_consumer, num_epochs,
                              num_reducers, num_trainers,
                              max_concurrent_epochs, seed=seed,
                              num_workers=num_workers, collect_stats=True)
    finally:
        done_event.set()
    return trial_stats, store_stats


def shuffle_no_stats(filenames: Sequence[str],
                     batch_consumer: BatchConsumer,
                     num_epochs: int,
                     num_reducers: int,
                     num_trainers: int,
                     max_concurrent_epochs: int = 2,
                     seed: int = 0,
                     num_workers: Optional[int] = None
                     ) -> Tuple[float, List]:
    """Duration-only variant (reference: shuffle.py:58-76)."""
    duration = shuffle(filenames, batch_consumer, num_epochs, num_reducers,
                       num_trainers, max_concurrent_epochs, seed=seed,
                       num_workers=num_workers, collect_stats=False)
    return duration, []


def run_shuffle_in_background(
        filenames: Sequence[str],
        batch_consumer: BatchConsumer,
        num_epochs: int,
        num_reducers: int,
        num_trainers: int,
        max_concurrent_epochs: int = 2,
        seed: int = 0,
        num_workers: Optional[int] = None,
        collect_stats: bool = False,
        start_epoch: int = 0) -> ex.TaskRef:
    """Launch the whole multi-epoch shuffle as one background task.

    Stands in for the reference driver's ``ray.remote(shuffle).remote(...)``
    (reference: dataset.py:110-118): the returned TaskRef is the
    ``shuffle_result`` handle the dataset joins after the last epoch.
    """
    # A dedicated single-worker executor hosts the driver loop so it never
    # competes with map/reduce workers for a pool slot.
    driver_pool = ex.Executor(num_workers=1, thread_name_prefix="rsdl-driver")

    def _run():
        try:
            return shuffle(filenames, batch_consumer, num_epochs,
                           num_reducers, num_trainers, max_concurrent_epochs,
                           seed=seed, num_workers=num_workers,
                           collect_stats=collect_stats,
                           start_epoch=start_epoch)
        finally:
            driver_pool.shutdown(wait_for_tasks=False)

    return driver_pool.submit(_run)
