"""Per-epoch map/reduce shuffle engine with epoch pipelining.

Capability parity with the reference's shuffle engine (reference:
shuffle.py:21-263): per epoch, one map task per Parquet file uniformly
scatters its rows across ``num_reducers`` reducers; one reduce task per
reducer concatenates its chunks from every file and permutes them; reducer
outputs are routed round-robin-contiguously to trainers via a
``batch_consumer`` callback followed by a ``None`` sentinel; shuffles for up
to ``max_concurrent_epochs`` epochs run concurrently with consumption,
throttled so host memory stays bounded.

TPU-native design differences:

- Tasks are host threads on the TPU-VM (executor.py), not Ray tasks; data
  is pyarrow Tables, not pandas DataFrames — Arrow's C++ kernels (Parquet
  decode, take, concat) release the GIL and its buffers are the zero-copy
  data plane that plasma provided externally (SURVEY.md §2.3).
- Map->reduce dependencies resolve by submission order: per epoch all maps
  are submitted before any reduce, and the FIFO thread pool guarantees a
  blocked reduce only ever waits on maps that already hold or preceded its
  worker slot, so the pattern is deadlock-free at any pool size.
- Every random draw is keyed by (seed, epoch, task) — the reference's
  unseeded ``np.random.randint`` / ``df.sample`` (reference: shuffle.py:213,
  240) made epochs irreproducible; ours replay bit-identically, which is
  what makes loader checkpoint/resume possible.
- The reference's reduce bug that turns a 1-row batch into a column lookup
  (``if len(batch) == 1: batch = batch[0]``, reference: shuffle.py:241-242)
  is intentionally not replicated.
"""

from __future__ import annotations

import functools
import threading
import timeit
from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple,
                    Union)

import numpy as np
import pyarrow as pa

from ray_shuffling_data_loader_tpu import executor as ex
from ray_shuffling_data_loader_tpu import stats as stats_mod
from ray_shuffling_data_loader_tpu import storage as rt_storage
from ray_shuffling_data_loader_tpu.ops import partition as ops
from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
from ray_shuffling_data_loader_tpu.runtime import retry as rt_retry
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
# Retired into the storage package (PR 14) but re-exported here: the
# disk tier predates storage/ and callers construct it by this name.
from ray_shuffling_data_loader_tpu.storage.cache import (  # noqa: F401
    DiskTableCache, DiskTier, TieredStore)
# Not read directly anymore (dataset bytes flow through rt_storage), but
# kept as a re-export: tests and downstream callers reach fileio via the
# shuffle namespace.
from ray_shuffling_data_loader_tpu.utils import fileio  # noqa: F401
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger
from ray_shuffling_data_loader_tpu.utils.tracing import trace_span

logger = setup_custom_logger(__name__)

# batch_consumer(rank, epoch, refs_or_None) — refs are TaskRefs resolving to
# pyarrow Tables (reference passes ObjectRefs of DataFrames,
# reference: dataset.py:213-224).
BatchConsumer = Callable[[int, int, Optional[Sequence[ex.TaskRef]]], None]

# Optional table -> table hook applied by the map task right after the
# Parquet read (e.g. cast int64 -> int32 before any shuffling, halving all
# downstream memory traffic). Must be row-order preserving.
MapTransform = Callable[[pa.Table], pa.Table]

# Optional table -> table hook applied by the reduce task to its shuffled
# output (e.g. decode encoded image bytes into fixed-shape pixel columns —
# BASELINE config 3 runs image decode inside shuffle reducers so the decode
# cost is spread over the reducer pool and overlaps training). Must be
# row-order preserving; runs once per reducer per epoch.
ReduceTransform = Callable[[pa.Table], pa.Table]

# Fallback per-call thread count for the native fused scatter-gather when
# no pool-aware value was derived (direct shuffle_reduce calls). Modest so
# that concurrently-running reduce tasks don't oversubscribe the host; on a
# 1-core host this is 1.
import os as _os
_SCATTER_GATHER_THREADS = max(1, min(4, (_os.cpu_count() or 1)))

# Shared pool for per-column gather fan-out inside _fused_reduce. Lazy and
# process-wide: reduce tasks from every concurrent epoch feed it leaf work
# (no column task ever waits on another pool task, so it cannot deadlock at
# any width).
_column_pool = None
_column_pool_lock = threading.Lock()


def _column_gather_pool():
    global _column_pool
    if _column_pool is None:
        with _column_pool_lock:
            if _column_pool is None:
                import concurrent.futures as _cf
                _column_pool = _cf.ThreadPoolExecutor(
                    max_workers=max(2, min(16, (_os.cpu_count() or 1))),
                    thread_name_prefix="rsdl-gather-col")
    return _column_pool


def derive_gather_threads(concurrent_reduces: int, pool_workers: int,
                          host_share: int = 1) -> int:
    """Threads per reduce task's fused gather, sized to the host.

    The static ``min(4, cores)`` default underuses big TPU-VM hosts (a
    100-core host running 4 reduce tasks would leave 84 cores idle in the
    shuffle's hottest loop) and oversubscribes small ones (8 cores with 19
    concurrent reducers at 4 threads each). Divide the cores across the
    reduce tasks that can actually run at once (ROADMAP round-3 item:
    reduce-stage thread tuning).

    ``concurrent_reduces`` is the caller's bound on simultaneously-running
    reduce tasks — for the epoch-pipelined driver that is
    ``num_reducers * max_concurrent_epochs``, not one epoch's worth.
    ``host_share``: how many pipeline "hosts" share this machine (the
    localhost multi-host emulation runs world transports in one process;
    a real deployment owns its cores and passes 1).
    """
    cores = (_os.cpu_count() or 1) // max(1, host_share)
    concurrent = max(1, min(concurrent_reduces, pool_workers))
    return max(1, min(16, cores // concurrent))

def _transient_read_retryable(error: BaseException) -> bool:
    """Map-read in-place retry predicate: an IO blip (NFS/GCS hiccup)
    heals on retry; corrupt content (``ArrowInvalid``) and injected
    task faults do not and must surface to quarantine/lineage."""
    return isinstance(error, OSError) and not isinstance(
        error, rt_faults.InjectedFault)


def default_fault_policies() -> Dict[str, Any]:
    """Per-stage RetryPolicy objects, resolved from the runtime policy
    registry (``RSDL_RETRY_*`` globally, ``RSDL_MAP_READ_RETRY_*`` /
    ``RSDL_REDUCE_RETRY_*`` / ``RSDL_LINEAGE_RETRY_*`` per stage).
    Built once per shuffle driver and shared by every epoch."""
    return {
        "read": rt_retry.RetryPolicy.for_component(
            "map_read", retryable=_transient_read_retryable),
        "reduce": rt_retry.RetryPolicy.for_component("reduce"),
        "lineage": rt_retry.RetryPolicy.for_component("lineage"),
    }


# How long shuffle() waits for consumers to release tables when
# max_inflight_bytes is exceeded before proceeding with a warning.
# Policy-overridable (RSDL_SHUFFLE_BUDGET_WAIT_TIMEOUT_S / kwargs); this
# constant is the library baseline. The wait itself is event-driven: the
# buffer ledger wakes it on every release (runtime/release.py), not a
# poll cadence.
_BUDGET_POLL_TIMEOUT_S = 30.0


def _table_numpy_columns(table: pa.Table) -> Optional[Dict[str, np.ndarray]]:
    """{column -> 1-D ndarray} views of a table, or None if any column is
    non-primitive / nullable (those fall back to the Arrow concat+take
    reduce path)."""
    cols: Dict[str, np.ndarray] = {}
    for name in table.column_names:
        col = table.column(name)
        if col.null_count != 0:
            return None
        t = col.type
        if not (pa.types.is_integer(t) or pa.types.is_floating(t)
                or pa.types.is_boolean(t)):
            return None
        if col.num_chunks == 0:
            cols[name] = np.empty(0, dtype=t.to_pandas_dtype())
            continue
        if col.num_chunks == 1:
            combined = col.chunk(0)
        else:
            # Blessed: runs once per shard (MapShard.numpy_columns' locked
            # cache); cached tables are single-chunk, so steady state
            # never reaches it. rsdl-lint: disable=copy-in-hot-path
            combined = col.combine_chunks()
        # Blessed: zero-copy for the single-chunk primitive columns this
        # function admits; cached per shard. rsdl-lint: disable=copy-in-hot-path
        arr = combined.to_numpy(zero_copy_only=False)
        if arr.dtype == object:
            return None
        cols[name] = arr
    return cols


class FileTableCache:
    """Bounded, thread-safe cache of decoded (and map-transformed) tables.

    The reference re-reads and re-decodes every Parquet file every epoch
    (reference: shuffle.py:208) — Ray's stateless tasks can't do better, and
    the OS page cache only skips disk IO, not decompression/decode. Our map
    tasks are host-local, so steady-state epochs can skip the whole
    read+decode+cast stage. Insertion stops at the byte budget (no
    eviction: every cached file is hit once per epoch, so LRU churn would
    only add copies).
    """

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._bytes = 0
        self._tables: Dict[str, pa.Table] = {}
        self._lock = threading.Lock()

    def get(self, key: str) -> Optional[pa.Table]:
        with self._lock:
            return self._tables.get(key)

    def put(self, key: str, table: pa.Table) -> bool:
        """Insert if the byte budget allows; returns True if inserted."""
        with self._lock:
            if key in self._tables:
                return True
            nbytes = table.nbytes
            if self._bytes + nbytes > self.max_bytes:
                return False
            self._tables[key] = table
            self._bytes += nbytes
        return True

    @property
    def bytes_cached(self) -> int:
        with self._lock:
            return self._bytes


def default_file_cache() -> Optional[FileTableCache]:
    """Cache budgeted at 1/3 of currently-available host RAM (None if that
    cannot be determined)."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    kb = int(line.split()[1])
                    return FileTableCache(max_bytes=kb * 1024 // 3)
    except (OSError, ValueError, IndexError):
        pass
    return None


# DiskTableCache lived here through PR 13; it is now the legacy face of
# storage.cache.DiskTier (same constructor, same no-eviction/no-ledger
# semantics, plus per-entry CRC) and is re-exported above — the explicit
# tier hierarchy, ledger charging, and promotion live in
# storage.cache.TieredStore.


def default_disk_cache_bytes(cache_dir: Optional[str] = None) -> int:
    """Disk budget for ``file_cache="disk"``: half the free space of the
    scratch filesystem (decoded tables are ~2-3x their parquet size)."""
    import shutil as _shutil
    import tempfile as _tempfile
    try:
        free = _shutil.disk_usage(cache_dir or _tempfile.gettempdir()).free
        return free // 2
    except OSError:
        return 16 << 30


def resolve_file_cache(spec, epochs_remaining: int):
    """Resolve a ``file_cache`` argument to ``(cache, owned)``.

    ``spec`` is ``"auto"`` (RAM cache when >1 epoch will map each file),
    ``"disk"`` (fresh :class:`DiskTableCache`, budgeted by
    ``default_disk_cache_bytes``), ``"tiered"`` (a full
    :class:`storage.cache.TieredStore`: hot RAM LRU over a ledger-charged
    CRC'd disk tier over the installed storage source, with the
    prefetcher seam the plan scheduler warms next-epoch files through),
    ``None``, or an instance. ``owned`` is True when this call created a
    disk-backed cache the driver must close after the run (its scratch
    files are useless to anyone else: reducer outputs are gathered
    copies, never views of cached tables)."""
    if spec == "auto":
        return (default_file_cache() if epochs_remaining > 1 else None,
                False)
    if spec == "disk":
        if epochs_remaining <= 1:
            return None, False
        return DiskTableCache(max_bytes=default_disk_cache_bytes()), True
    if spec == "tiered":
        if epochs_remaining <= 1:
            return None, False
        ram = default_file_cache()
        hot_bytes = ram.max_bytes if ram is not None else 1 << 30
        return TieredStore(
            hot_bytes,
            disk=DiskTier(max_bytes=default_disk_cache_bytes()),
            source=rt_storage.get_source()), True
    return spec, False


class MapShard:
    """Lazy map output: the source table plus per-reducer row-index arrays.

    The reference's map task materializes ``num_reducers`` DataFrame
    partitions (reference: shuffle.py:215-220). Within a host that gather
    is pure waste — the reduce permutation gathers the same rows again — so
    the map task only *plans* the partition and the reduce task performs a
    single fused gather (see :func:`shuffle_reduce`). Materialized
    partitions are still available via indexing/iteration for the
    cross-host transport path.
    """

    __slots__ = ("table", "index_parts", "_np_cols", "_np_cols_known",
                 "_np_cols_lock")

    def __init__(self, table: pa.Table, index_parts: List[np.ndarray]):
        self.table = table
        self.index_parts = index_parts
        self._np_cols: Optional[Dict[str, np.ndarray]] = None
        self._np_cols_known = False
        self._np_cols_lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.index_parts)

    def __getitem__(self, reducer_index: int) -> "LazyChunk":
        return LazyChunk(self, reducer_index)

    def __iter__(self):
        return (self[r] for r in range(len(self.index_parts)))

    def numpy_columns(self) -> Optional[Dict[str, np.ndarray]]:
        """Cached numpy views of the source table (None if ineligible).

        Locked: all of this shard's reduce tasks race here at once, and an
        unsynchronized miss would make each of them combine_chunks() its own
        full copy of the source table.
        """
        if self._np_cols_known:
            return self._np_cols
        with self._np_cols_lock:
            if not self._np_cols_known:
                self._np_cols = _table_numpy_columns(self.table)
                self._np_cols_known = True
        return self._np_cols


class LazyChunk:
    """One reducer's slice of a map output, gathered only on demand."""

    __slots__ = ("shard", "reducer_index")

    def __init__(self, shard: MapShard, reducer_index: int):
        self.shard = shard
        self.reducer_index = reducer_index

    @property
    def num_rows(self) -> int:
        return len(self.shard.index_parts[self.reducer_index])

    @property
    def indices(self) -> np.ndarray:
        return self.shard.index_parts[self.reducer_index]

    def materialize(self) -> pa.Table:
        return self.shard.table.take(self.indices)


def plan_map_partition(num_rows: int, num_reducers: int, seed: int,
                       epoch: int, file_index: int) -> List[np.ndarray]:
    """The map task's row->reducer partition plan, policy-selected.

    ``partition_plan="fused"`` (default) runs the one-kernel counter-based
    plan (``ops.plan_partition``: the native kernel emits partition indices
    straight from its hash stream; the NumPy fallback is bit-identical).
    ``"philox"`` keeps the legacy two-stage draw+sort pipeline. Both
    executor backends and every recovery recompute resolve the same knob,
    so a given ``(seed, epoch, file)`` always replays the same plan.
    """
    if rt_policy.resolve("shuffle", "partition_plan") == "philox":
        rng = ops.map_rng(seed, epoch, file_index)
        assignments = ops.assign_reducers(num_rows, num_reducers, rng)
        return ops.partition_indices(assignments, num_reducers)
    return ops.plan_partition(num_rows, num_reducers, seed, epoch,
                              file_index, nthreads=_SCATTER_GATHER_THREADS)


class FusedMapShard:
    """Map output of the streaming decode->partition->gather pipeline.

    Unlike :class:`MapShard` (source table + per-reducer index arrays,
    gather deferred to the reduce), the fused pipeline has ALREADY placed
    every row in its reducer's region while the Parquet record batches
    streamed through — ``table`` holds the rows GROUPED by reducer, and
    each reducer's chunk is a zero-copy slice ``[offsets[r], offsets[r+1])``
    of it. The reduce body treats those slices as already-in-order sources
    (the ``idx=None`` arm of :func:`_fused_reduce`) — rows were scattered
    in increasing global row order, i.e. exactly the stable order the
    legacy plan's gather produces, so both paths emit bit-identical
    reducer outputs. The ``table`` / indexing / iteration /
    ``materialize()`` surface matches :class:`MapShard`'s so every
    consumer (cross-host ship, recovery, tests) works on either shape.
    """

    __slots__ = ("table", "offsets", "columns")

    def __init__(self, table: pa.Table, offsets: np.ndarray,
                 columns: Dict[str, np.ndarray]):
        self.table = table
        self.offsets = offsets
        self.columns = columns  # the grouped numpy buffers backing table

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(self, reducer_index: int) -> "FusedChunk":
        return FusedChunk(self, reducer_index)

    def __iter__(self):
        return (self[r] for r in range(len(self)))


class FusedChunk:
    """One reducer's zero-copy slice of a fused (grouped) map output."""

    __slots__ = ("shard", "reducer_index")

    def __init__(self, shard: FusedMapShard, reducer_index: int):
        self.shard = shard
        self.reducer_index = reducer_index

    @property
    def _bounds(self) -> "tuple[int, int]":
        offsets = self.shard.offsets
        return int(offsets[self.reducer_index]), \
            int(offsets[self.reducer_index + 1])

    @property
    def num_rows(self) -> int:
        lo, hi = self._bounds
        return hi - lo

    @property
    def indices(self) -> np.ndarray:
        # Rows are pre-grouped, so this chunk's rows of the shard table
        # are simply the contiguous run — materialized lazily for the
        # (diagnostic/test) callers that inspect the gather plan.
        lo, hi = self._bounds
        return np.arange(lo, hi, dtype=np.int64)

    def materialize(self) -> pa.Table:
        lo, hi = self._bounds
        return self.shard.table.slice(lo, hi - lo)


#: Record-batch granularity of the streaming map pipeline. Large enough
#: that the per-batch Python overhead (dest assignment, column loop)
#: amortizes; small enough that decode->scatter stays cache-resident and
#: peak memory holds ~one batch plus the grouped output.
_FUSED_STREAM_BATCH_ROWS = 1 << 16


def _fused_pipeline_enabled() -> bool:
    return rt_policy.resolve("shuffle", "shuffle_fused_pipeline") is not False


def _fused_stream_columns(filename: str, num_reducers: int, seed: int,
                          epoch: int, file_index: int,
                          map_transform: Optional[MapTransform]):
    """Stream a Parquet file's record batches straight into per-reducer
    grouped column buffers: fused decode->partition->gather, no
    intermediate decoded-table materialization.

    Returns ``(out_cols, offsets, names)`` — flat per-column arrays
    grouped by reducer plus the region offsets — or ``None`` whenever the
    input falls outside the fast path's contract (non-primitive or
    nullable columns, a transform that is not row-elementwise, >= 2**31
    rows, mid-stream schema drift): the caller falls back to the legacy
    read-then-plan path, whose output is bit-identical.

    The partition stream is the same ``(seed, epoch, file_index)``
    splitmix64 stream as :func:`plan_map_partition`'s fused plan —
    per-reducer counts come from the hash alone (no data), and each
    batch's rows scatter to ``assign_dest_batch`` slots that reproduce the
    legacy counting sort's stable layout.
    """
    from ray_shuffling_data_loader_tpu import native
    if map_transform is not None and not getattr(
            map_transform, "row_elementwise", False):
        return None
    pf = rt_storage.open_parquet(filename, epoch=epoch, task=file_index)
    try:
        num_rows = pf.metadata.num_rows
        if num_rows <= 0 or num_rows >= 2**31:
            return None
        counts = ops.partition_counts(num_rows, num_reducers, seed, epoch,
                                      file_index,
                                      nthreads=_SCATTER_GATHER_THREADS)
        offsets = np.zeros(num_reducers + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        cursors = offsets[:-1].copy()
        use_native = native.available()
        out_cols: Optional[Dict[str, np.ndarray]] = None
        names: Optional[List[str]] = None
        row0 = 0
        for batch in pf.iter_batches(batch_size=_FUSED_STREAM_BATCH_ROWS):
            tbl = pa.Table.from_batches([batch])
            if map_transform is not None:
                tbl = map_transform(tbl)
                if tbl.num_rows != batch.num_rows:
                    return None
            cols = _table_numpy_columns(tbl)
            if cols is None:
                return None
            if out_cols is None:
                names = list(cols)
                out_cols = {name: np.empty(num_rows, dtype=cols[name].dtype)
                            for name in names}
            elif (list(cols) != names
                  or any(cols[n].dtype != out_cols[n].dtype for n in names)):
                return None
            n = tbl.num_rows
            dest = ops.assign_dest_batch(n, num_reducers, seed, epoch,
                                         file_index, row0, cursors)
            for name in names:
                src = cols[name]
                out = out_cols[name]
                if (use_native and dest.dtype == np.int32
                        and src.flags.c_contiguous
                        and src.dtype.itemsize in (1, 2, 4, 8)):
                    native.scatter_gather(src, None, dest, out,
                                          nthreads=_SCATTER_GATHER_THREADS)
                else:
                    out[dest] = src
            row0 += n
        if out_cols is None or row0 != num_rows:
            return None  # torn metadata: let the legacy reader diagnose it
        return out_cols, offsets, names
    finally:
        try:
            pf.close()
        except AttributeError:  # older pyarrow: reader closes with GC
            pass


def _fused_stream_map(filename: str, num_reducers: int, seed: int,
                      epoch: int, file_index: int,
                      map_transform: Optional[MapTransform]
                      ) -> Optional[FusedMapShard]:
    """:func:`_fused_stream_columns` packaged as per-reducer zero-copy
    table chunks (the thread backend's shard shape); ``None`` when the
    file is outside the fast path's contract."""
    streamed = _fused_stream_columns(filename, num_reducers, seed, epoch,
                                     file_index, map_transform)
    if streamed is None:
        return None
    out_cols, offsets, names = streamed
    from ray_shuffling_data_loader_tpu import native
    table = pa.table({name: out_cols[name] for name in names})
    native.account_table(table)
    return FusedMapShard(table, offsets, out_cols)


def _read_map_table(filename: str, epoch: int, file_index: int,
                    read_retry: Optional[rt_retry.RetryPolicy],
                    inject: bool = True) -> pa.Table:
    """The map task's dataset read, as named fault sites plus an
    in-place retry for transient IO errors (an NFS/GCS/remote blip
    heals on retry; a corrupt file does not, so ``ArrowInvalid`` is not
    retried and surfaces to the quarantine policy in
    :func:`shuffle_map`). The bytes come from the installed
    :mod:`storage` source — local disk, HTTP, or the simulated object
    store — which is also where the ``storage_read``/``storage_stall``
    chaos sites live.

    ``faults.inject`` sits OUTSIDE the retried read on purpose: an
    injected fault simulates a *lost task*, and must surface to the
    lineage-recovery machinery under test rather than be absorbed here.
    ``inject=False`` skips the map_read fault site — used when the
    caller already fired it for this task (the streaming pipeline's
    ineligible-file fallback) so one map task never consumes two
    map_read injections (the storage sites are exactly-once per key on
    their own).
    """
    if inject:
        rt_faults.inject("map_read", epoch=epoch, task=file_index)
    return rt_storage.read_table(filename, epoch=epoch, task=file_index,
                                 retry=read_retry)


def shuffle_map(filename: str,
                num_reducers: int,
                seed: int,
                epoch: int,
                file_index: int,
                stats_collector=None,
                map_transform: Optional[MapTransform] = None,
                file_cache: Optional[FileTableCache] = None,
                on_bad_file: str = "raise",
                read_retry: Optional[rt_retry.RetryPolicy] = None):
    """Read one file and plan the scatter of its rows across reducers
    (reference: shuffle.py:199-226 — but the per-reducer gather is deferred
    to the reduce task, which fuses it with the shuffle permutation).

    Returns a :class:`MapShard`, or — when the file is corrupt/unreadable
    after ``read_retry`` and ``on_bad_file="skip"`` — a structured
    :class:`runtime.faults.QuarantinedFile` report that the reduce gather
    drops (recorded in ``stats.fault_stats()``, never silent). With the
    default ``on_bad_file="raise"`` a bad file fails the map task; lineage
    recovery retries it, and only exhausted recovery poisons the run.
    """
    if on_bad_file not in ("raise", "skip"):
        raise ValueError(
            f"on_bad_file must be 'raise' or 'skip', got {on_bad_file!r}")
    if stats_collector is not None:
        stats_collector.map_start(epoch)
    start = timeit.default_timer()
    with trace_span(f"shuffle_map e{epoch} f{file_index}"):
        # Streaming fast path: cache-less reads only — the decoded table is
        # never materialized, so there is nothing to publish into a
        # cross-epoch cache (cached runs keep the legacy read: their
        # steady-state epochs pay no decode at all, and the reduce's single
        # fused gather is already one pass).
        if file_cache is None and _fused_pipeline_enabled():
            rt_faults.inject("map_read", epoch=epoch, task=file_index)
            fused_fn = functools.partial(
                _fused_stream_map, filename, num_reducers, seed, epoch,
                file_index, map_transform)
            try:
                shard = (fused_fn() if read_retry is None
                         else read_retry.call(fused_fn,
                                              describe=f"stream {filename}"))
            except (OSError, pa.ArrowInvalid) as e:
                if on_bad_file != "skip":
                    raise
                report = rt_faults.QuarantinedFile(
                    filename=filename, epoch=epoch, file_index=file_index,
                    error=f"{type(e).__name__}: {e}")
                stats_mod.fault_stats().record_quarantine(report)
                logger.error(
                    "quarantined unreadable input file %s (epoch %d, "
                    "file %d): %s; shuffling the remaining files "
                    "(on_bad_file='skip')", filename, epoch, file_index, e)
                if stats_collector is not None:
                    stats_collector.map_done(
                        epoch, timeit.default_timer() - start,
                        timeit.default_timer() - start)
                return report
            if shard is not None:
                end_read = timeit.default_timer()
                rt_telemetry.record("map_read", epoch=epoch,
                                    task=file_index, dur_s=end_read - start)
                if stats_collector is not None:
                    stats_collector.map_done(
                        epoch, timeit.default_timer() - start,
                        end_read - start)
                return shard
            # Ineligible for streaming: legacy read below (the map_read
            # fault site already fired once for this task, so skip the
            # legacy reader's injection).
            inject_fault = False
        else:
            inject_fault = True
        table = file_cache.get(filename) if file_cache is not None else None
        if table is None:
            # Local path or remote URI (gs://, s3://, ... — the reference
            # reads via smart_open, reference: shuffle.py:7,208); the cache
            # above keys on the full URI string either way.
            try:
                table = _read_map_table(filename, epoch, file_index,
                                        read_retry, inject=inject_fault)
            except (OSError, pa.ArrowInvalid) as e:
                if on_bad_file != "skip":
                    raise
                report = rt_faults.QuarantinedFile(
                    filename=filename, epoch=epoch, file_index=file_index,
                    error=f"{type(e).__name__}: {e}")
                stats_mod.fault_stats().record_quarantine(report)
                logger.error(
                    "quarantined unreadable input file %s (epoch %d, "
                    "file %d): %s; shuffling the remaining files "
                    "(on_bad_file='skip')", filename, epoch, file_index, e)
                if stats_collector is not None:
                    stats_collector.map_done(
                        epoch, timeit.default_timer() - start,
                        timeit.default_timer() - start)
                return report
            if map_transform is not None:
                table = map_transform(table)
            if file_cache is not None:
                # Blessed: paid once per CACHED file — single-chunk columns
                # make every later epoch's numpy views zero-copy.
                # rsdl-lint: disable=copy-in-hot-path
                table = table.combine_chunks()
                file_cache.put(filename, table)
            # Charge the decoded table to the buffer ledger for its
            # lifetime — whether it now lives in the cache or only in this
            # epoch's MapShard, 'wrapper alive' is 'bytes in flight'.
            from ray_shuffling_data_loader_tpu import native
            native.account_table(table)
        end_read = timeit.default_timer()
        # Flight-recorder stage event: kind reuses the fault-site name,
        # so a chaos run's map_read faults join this event by
        # (kind, epoch, task).
        rt_telemetry.record("map_read", epoch=epoch, task=file_index,
                            dur_s=end_read - start)
        index_parts = plan_map_partition(table.num_rows, num_reducers,
                                         seed, epoch, file_index)
        shard = MapShard(table, index_parts)
    if stats_collector is not None:
        stats_collector.map_done(epoch, timeit.default_timer() - start,
                                 end_read - start)
    return shard


def _fused_reduce(reduce_index: int, seed: int, epoch: int,
                  sources: Sequence[Tuple[Dict[str, np.ndarray],
                                          Optional[np.ndarray], int]],
                  column_names: Sequence[str],
                  gather_threads: Optional[int] = None) -> pa.Table:
    """Single-pass scatter-gather: out[i] = concat(chunks)[perm[i]].

    Each source is ``(columns, row_indices_or_None, num_rows)``; ``None``
    indices mean the source rows are already this reducer's chunk in order.
    Bit-identical to ``pa.concat_tables(chunks).take(perm)``.
    """
    counts = [n for _, _, n in sources]
    total = sum(counts)
    perm = ops.permutation(total, ops.reduce_rng(seed, epoch, reduce_index))
    # inverse permutation: concat-order row j lands at output position inv[j].
    # int32 indices: the scatter-gather's dominant memory traffic is the
    # index arrays themselves (2 index reads per row per column), so
    # halving index width outruns the one-time casts. idx values address the
    # SOURCE table (not this reducer's output), so the width must cover the
    # largest source row count as well as `total`.
    max_source_rows = max(
        (next(iter(cols.values())).size for cols, _, _ in sources if cols),
        default=0)
    index_dtype = (np.int32 if max(total, max_source_rows) < 2**31
                   else np.int64)
    inv = np.empty(total, dtype=index_dtype)
    inv[perm] = np.arange(total, dtype=index_dtype)
    sources = [(cols, None if idx is None
                else idx.astype(index_dtype, copy=False), n)
               for cols, idx, n in sources]
    from ray_shuffling_data_loader_tpu import native
    use_native = native.available() and index_dtype == np.int32
    threads = gather_threads or _SCATTER_GATHER_THREADS
    names = list(column_names)
    # Column fan-out: columns are independent gathers, so run them on the
    # shared column pool and split this task's thread budget across the
    # columns in flight — total concurrency stays at `threads`, but the
    # per-column Python loop (slice bookkeeping, the numpy fallback arms)
    # no longer serializes the whole reduce. Small outputs stay inline:
    # below the native kernel's own threading floor the handoff costs more
    # than it saves.
    fan_out = min(len(names), threads) if total >= (1 << 16) else 1
    col_threads = max(1, threads // fan_out)

    def _gather_column(name: str) -> np.ndarray:
        dtype = sources[0][0][name].dtype
        out = np.empty(total, dtype=dtype)
        offset = 0
        for cols, idx, n in sources:
            dest = inv[offset:offset + n]
            src = cols[name]
            if (use_native and src.flags.c_contiguous
                    and dtype.itemsize in (1, 2, 4, 8)):
                native.scatter_gather(src, idx, dest, out,
                                      nthreads=col_threads)
            elif idx is None:
                out[dest] = src
            else:
                out[dest] = src[idx]
            offset += n
        return out

    if fan_out > 1:
        pool = _column_gather_pool()
        futures = [pool.submit(_gather_column, name) for name in names[1:]]
        out_cols = {names[0]: _gather_column(names[0])}
        for name, future in zip(names[1:], futures):
            out_cols[name] = future.result()
    else:
        out_cols = {name: _gather_column(name) for name in names}
    return pa.table(out_cols)


def shuffle_reduce(reduce_index: int,
                   seed: int,
                   epoch: int,
                   chunks: Sequence[Union[pa.Table, LazyChunk]],
                   stats_collector=None,
                   reduce_transform: Optional[ReduceTransform] = None,
                   gather_threads: Optional[int] = None) -> pa.Table:
    """Concatenate one chunk per file and permute the rows
    (reference: shuffle.py:229-247).

    Chunks may be materialized ``pa.Table``s (the cross-host path) or
    :class:`LazyChunk`s (host-local map outputs). When every chunk's
    columns are primitive and null-free the concat+permute+gather collapses
    into ONE numpy scatter-gather pass per column — the output is
    bit-identical to the materialize-concat-take path, at roughly half the
    memory traffic.
    """
    if stats_collector is not None:
        stats_collector.reduce_start(epoch)
    start = timeit.default_timer()
    with trace_span(f"shuffle_reduce e{epoch} r{reduce_index}"):
        shuffled = _shuffle_reduce_body(reduce_index, seed, epoch, chunks,
                                        reduce_transform, gather_threads)
    if stats_collector is not None:
        stats_collector.reduce_done(epoch, timeit.default_timer() - start)
    return shuffled


def _promote_offset_type(t: pa.DataType) -> pa.DataType:
    """64-bit-offset (``large_*``) form of ``t``, recursing into nested
    value types: ``list<string>`` becomes ``large_list<large_string>``
    (a promoted outer list with 32-bit child offsets would re-raise
    ArrowInvalid on the retried take when the CHILD data exceeds 2 GiB).
    Fixed-size lists keep their width but promote their children; struct
    fields promote independently."""
    if pa.types.is_binary(t):
        return pa.large_binary()
    if pa.types.is_string(t):
        return pa.large_string()
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        return pa.large_list(_promote_offset_type(t.value_type))
    if pa.types.is_fixed_size_list(t):
        return pa.list_(_promote_offset_type(t.value_type), t.list_size)
    if pa.types.is_struct(t):
        return pa.struct([
            field.with_type(_promote_offset_type(field.type)) for field in t
        ])
    return t


def _promote_large_offsets(table: pa.Table) -> pa.Table:
    """Cast 32-bit-offset variable-width columns (binary/string/list,
    including nested children) to their 64-bit ``large_*`` forms so a
    single reducer output may exceed 2 GiB of variable-width data."""
    fields = []
    changed = False
    for field in table.schema:
        t = _promote_offset_type(field.type)
        if t != field.type:
            changed = True
        fields.append(field.with_type(t))
    if not changed:
        return table
    return table.cast(pa.schema(fields, metadata=table.schema.metadata))


def _shuffle_reduce_body(reduce_index, seed, epoch, chunks,
                         reduce_transform, gather_threads=None):
    shuffled = None
    sources = []
    schema = None
    for chunk in chunks:
        if isinstance(chunk, LazyChunk):
            cols = chunk.shard.numpy_columns()
            if cols is None:
                break
            chunk_schema = chunk.shard.table.schema
            sources.append((cols, chunk.indices, chunk.num_rows))
        elif isinstance(chunk, FusedChunk):
            # Pre-grouped rows: this reducer's run is a contiguous slice
            # of the shard's numpy buffers, already in the stable order
            # the legacy gather would produce — the idx=None arm.
            lo, hi = chunk._bounds
            cols = {name: arr[lo:hi]
                    for name, arr in chunk.shard.columns.items()}
            chunk_schema = chunk.shard.table.schema
            sources.append((cols, None, hi - lo))
        else:
            cols = _table_numpy_columns(chunk)
            if cols is None:
                break
            chunk_schema = chunk.schema
            sources.append((cols, None, chunk.num_rows))
        if schema is None:
            schema = chunk_schema
        elif schema != chunk_schema:
            break
    else:
        if schema is not None:
            shuffled = _fused_reduce(reduce_index, seed, epoch, sources,
                                     schema.names, gather_threads)
    if shuffled is None and chunks:
        # Fallback: nested / nullable / mixed-schema columns.
        tables = [
            c.materialize() if isinstance(c, (LazyChunk, FusedChunk)) else c
            for c in chunks
        ]
        # permissive promotion: a map-side transform (or a partially
        # promoted cross-host stream) may hand this reducer chunks whose
        # schemas differ only in offset width; unifying them here keeps
        # the fallback alive in exactly the regime it serves.
        table = pa.concat_tables(tables, promote_options="permissive")
        perm = ops.permutation(table.num_rows,
                               ops.reduce_rng(seed, epoch, reduce_index))
        try:
            shuffled = table.take(perm)
        except pa.ArrowInvalid:
            # >2 GiB of variable-width data in ONE reducer output (e.g.
            # 1e6-image corpora with few reducers): the gather's chunk
            # concatenation overflows 32-bit offsets. Promote to 64-bit
            # offset types and retry — Arrow IPC, the transport, and the
            # consumers all handle large_* columns.
            table = _promote_large_offsets(table)
            shuffled = table.take(perm)
    elif shuffled is None:
        shuffled = pa.table({})
    # Applied even to 0-row outputs: a schema-changing transform (e.g.
    # image decode) must keep every reducer's schema identical or the
    # iterator's carry-buffer concat breaks on the mixed schemas.
    if reduce_transform is not None and shuffled.num_columns:
        shuffled = reduce_transform(shuffled)
    return shuffled


def recompute_reducer_output(filenames: Sequence[str], num_reducers: int,
                             seed: int, epoch: int, reduce_index: int,
                             map_transform: Optional[MapTransform] = None,
                             reduce_transform: Optional[ReduceTransform]
                             = None,
                             on_bad_file: str = "raise") -> pa.Table:
    """Rebuild one reducer output from scratch lineage: re-read every
    input file, re-plan its scatter, and re-run the fused reduce — a pure
    function of ``(seed, epoch, reduce_index)`` and the files, so the
    result is bit-identical to the original. This is the spill tier's
    corruption-recovery path (spill.py): deliberately self-contained (no
    map-shard refs captured) so an armed :class:`spill.SpilledTable`
    handle pins only this closure's small arguments, never an epoch's
    decoded tables."""
    chunks = []
    for file_index, filename in enumerate(filenames):
        shard = shuffle_map(filename, num_reducers, seed, epoch,
                            file_index, None, map_transform, None,
                            on_bad_file, None)
        if isinstance(shard, rt_faults.QuarantinedFile):
            continue
        chunks.append(shard[reduce_index])
    return shuffle_reduce(reduce_index, seed, epoch, chunks, None,
                          reduce_transform)


class EpochLineage:
    """Recompute lost map outputs from their ``(seed, epoch, file)`` lineage.

    Ray reconstructs a lost object by re-running the task recorded in its
    lineage; here every map task is a pure function of
    ``(seed, epoch, file_index)`` (the determinism contract checkpoint.py
    already exploits), so the lineage IS those three integers plus the
    map configuration this object captures. When a reduce gather observes
    a failed map ref it calls :meth:`recover`: the first reducer to
    observe the failure recomputes the map task **inline on its own
    worker thread** (never re-submitted to the pool — all workers may be
    reduce tasks blocked on this very output, and a pool-queued recompute
    behind them would deadlock; the FIFO submission-order argument in the
    module docstring only covers the original submission). Every other
    reducer waits on the first one's result, so a lost map is recomputed
    exactly once per epoch no matter how many reducers need it.

    Recovery is bounded by a :class:`runtime.retry.RetryPolicy`; a
    recovery that exhausts its attempts raises (and is cached, so later
    reducers fail fast instead of re-running a known-dead recompute) —
    those are the only map failures that reach the ``ShuffleFailure``
    poison pill. Recomputed shards are bit-identical to the lost ones
    (seeded RNG, row-order-preserving transforms), so the consumed batch
    stream is unchanged by recovery.
    """

    class _Cell:
        __slots__ = ("done", "result", "error")

        def __init__(self):
            self.done = threading.Event()
            self.result = None
            self.error: Optional[BaseException] = None

    def __init__(self, filenames: Sequence[str], num_reducers: int,
                 seed: int, epoch: int, stats_collector=None,
                 map_transform: Optional[MapTransform] = None,
                 file_cache: Optional[FileTableCache] = None,
                 retry_policy: Optional[rt_retry.RetryPolicy] = None,
                 on_bad_file: str = "raise",
                 read_retry: Optional[rt_retry.RetryPolicy] = None):
        self._filenames = list(filenames)
        self._num_reducers = num_reducers
        self._seed = seed
        self._epoch = epoch
        self._stats_collector = stats_collector
        self._map_transform = map_transform
        self._file_cache = file_cache
        self._retry = (retry_policy if retry_policy is not None
                       else rt_retry.RetryPolicy.for_component("lineage"))
        self._on_bad_file = on_bad_file
        self._read_retry = read_retry
        self._lock = threading.Lock()
        self._cells: Dict[int, EpochLineage._Cell] = {}
        self.recomputes = 0

    def recover(self, file_index: int, cause: BaseException):
        """Return the recomputed output of map task ``file_index``
        (a MapShard or QuarantinedFile), recomputing it at most once."""
        with self._lock:
            cell = self._cells.get(file_index)
            claimed = cell is None
            if claimed:
                cell = self._cells[file_index] = EpochLineage._Cell()
        if claimed:
            self._recompute(file_index, cell, cause)
        else:
            cell.done.wait()
        if cell.error is not None:
            # Re-raise the recompute's own failure (same type as the
            # original — the task is deterministic), chained to the first
            # observed one: consumers keep matching on the real exception
            # class (ValueError from a bad transform, FileNotFoundError
            # from a missing file), with lineage exhaustion in the chain.
            raise cell.error from cause
        return cell.result

    def _recompute(self, file_index: int, cell: "EpochLineage._Cell",
                   cause: BaseException) -> None:
        start = timeit.default_timer()
        logger.warning(
            "map task %d (epoch %d) failed (%s); recomputing from lineage",
            file_index, self._epoch, cause)
        try:
            cell.result = self._retry.call(
                shuffle_map, self._filenames[file_index],
                self._num_reducers, self._seed, self._epoch, file_index,
                self._stats_collector, self._map_transform,
                self._file_cache, self._on_bad_file, self._read_retry,
                describe=f"map recompute e{self._epoch} f{file_index}")
        except BaseException as e:  # noqa: BLE001 - cached + re-raised
            stats_mod.fault_stats().record_exhausted("lineage")
            cell.error = e
        else:
            latency = timeit.default_timer() - start
            with self._lock:
                self.recomputes += 1
            stats_mod.fault_stats().record_recompute("lineage", latency)
            logger.info(
                "recomputed map task %d (epoch %d) from lineage in %.3fs",
                file_index, self._epoch, latency)
        finally:
            cell.done.set()


def _reduce_task(reduce_index: int, seed: int, epoch: int,
                 map_refs: Sequence[ex.TaskRef], stats_collector,
                 reduce_transform: Optional[ReduceTransform] = None,
                 spill_manager=None,
                 gather_threads: Optional[int] = None,
                 lineage: Optional[EpochLineage] = None,
                 retry_policy: Optional[rt_retry.RetryPolicy] = None,
                 spill_recompute=None) -> pa.Table:
    """Executor wrapper: resolve this reducer's chunk from every map output.

    Equivalent of Ray resolving ``shuffle_reduce.remote(*refs)`` argument
    refs (reference: shuffle.py:182-187) — but the chunks stay lazy
    (index arrays into the map tables) until the fused reduce gathers them.

    Fault handling (this is where lineage recovery hooks in): a failed
    map ref is recomputed via ``lineage.recover`` instead of propagating;
    a :class:`runtime.faults.QuarantinedFile` marker (``on_bad_file=
    "skip"``) drops that file's chunk; and the gather+shuffle itself is
    re-run under ``retry_policy`` on failure — safe because the whole
    body is a pure function of ``(seed, epoch, reduce_index)`` and the
    (lazy, repeatable) map outputs. Only exhausted recovery escapes.
    """

    def _gather_and_shuffle() -> pa.Table:
        # The telemetry span covers the WHOLE reduce task body (fault
        # site, ref gather, fused shuffle) — that is the unit the
        # bottleneck attribution bills to the "reduce" stage, and the
        # unit a reduce_gather chaos rule (fail or delayN) perturbs, so
        # the two correlate by (kind, epoch, task).
        with rt_telemetry.span("reduce_gather", epoch=epoch,
                               task=reduce_index):
            rt_faults.inject("reduce_gather", epoch=epoch,
                             task=reduce_index)
            chunks = []
            for file_index, ref in enumerate(map_refs):
                try:
                    shard = ref.result()
                except Exception as e:  # noqa: BLE001 - lineage recovers
                    if lineage is None:
                        raise
                    shard = lineage.recover(file_index, e)
                if isinstance(shard, rt_faults.QuarantinedFile):
                    continue  # dropped file: shuffle the surviving inputs
                chunks.append(shard[reduce_index])
            return shuffle_reduce(reduce_index, seed, epoch, chunks,
                                  stats_collector, reduce_transform,
                                  gather_threads)

    if retry_policy is None:
        shuffled = _gather_and_shuffle()
    else:
        def _recovered(failed_attempts: int, elapsed_s: float) -> None:
            stats_mod.fault_stats().record_recompute("reduce", elapsed_s)

        shuffled = retry_policy.call(
            _gather_and_shuffle,
            describe=f"reduce e{epoch} r{reduce_index}",
            on_recovery=_recovered)
    return account_and_maybe_spill(shuffled, spill_manager,
                                   recompute=spill_recompute,
                                   epoch=epoch, task=reduce_index,
                                   seed=seed)


def account_and_maybe_spill(shuffled: pa.Table, spill_manager,
                            recompute=None, epoch: Optional[int] = None,
                            task: Optional[int] = None,
                            seed: Optional[int] = None) -> pa.Table:
    """Post-reduce memory policy, shared by the single-host and distributed
    reduce wrappers so their semantics cannot diverge: charge the output's
    in-flight bytes to the buffer ledger (plasma's store-utilization role;
    the max_inflight_bytes throttle reads the same counter), then spill it
    if a spill manager is active and the pipeline is over budget — the
    SpilledTable handle replaces the table, so the in-memory copy is
    released as soon as the reduce task returns. ``recompute`` (single-
    host path: :func:`recompute_reducer_output` bound to this reducer's
    lineage) arms the handle's corrupt-spill recovery; the cross-host
    path passes None — its inputs crossed the wire, so a corrupt spill
    there stays a loud failure.

    The output is also stamped with its lineage as ``rsdl.trace``
    schema metadata (``"seed:epoch:task"``) — the causal trace context
    (runtime/trace.py). Schema metadata survives slicing, Arrow IPC
    (spill files, the queue wire, the transport) and concatenation, so
    whichever process ends up holding this table can name the exact
    reduce span that built it; the queue service copies the task id
    into its v2 frame headers from here."""
    if epoch is not None and task is not None:
        from ray_shuffling_data_loader_tpu.runtime import latency as rt_lat
        meta = dict(shuffled.schema.metadata or {})
        meta[b"rsdl.trace"] = f"{seed if seed is not None else 0}:" \
                              f"{epoch}:{task}".encode()
        # Birth stamp (runtime/latency.py): the delivery-latency plane's
        # t=0 for this payload. Both clocks + the producing pid ride
        # along so any downstream process (queue shard, trainer, device
        # loop) computes a skew-proof age; like rsdl.trace, the stamp
        # survives slicing, IPC, spill and the queue wire.
        meta[rt_lat.BIRTH_META_KEY] = rt_lat.encode_stamp(
            rt_lat.now_stamp())
        shuffled = shuffled.replace_schema_metadata(meta)
    from ray_shuffling_data_loader_tpu import native
    native.account_table(shuffled)
    if spill_manager is not None:
        shuffled = spill_manager.maybe_spill(shuffled, recompute=recompute,
                                             epoch=epoch, task=task)
    return shuffled


def consume(trainer_idx: int,
            batch_consumer: BatchConsumer,
            trial_start: float,
            stats_collector,
            epoch: int,
            batches: List[ex.TaskRef]) -> None:
    """Hand one trainer its epoch's reducer refs (reference: shuffle.py:250-263)."""
    if stats_collector is not None:
        stats_collector.consume_start(epoch)
    start = timeit.default_timer()
    trial_time_to_consume = start - trial_start
    batch_consumer(trainer_idx, epoch, batches)
    if stats_collector is not None:
        stats_collector.consume_done(epoch, timeit.default_timer() - start,
                                     trial_time_to_consume)


def shuffle_epoch(epoch: int,
                  filenames: Sequence[str],
                  batch_consumer: BatchConsumer,
                  num_reducers: int,
                  num_trainers: int,
                  pool: ex.Executor,
                  seed: int,
                  trial_start: float,
                  stats_collector=None,
                  map_transform: Optional[MapTransform] = None,
                  file_cache: Optional[FileTableCache] = None,
                  reduce_transform: Optional[ReduceTransform] = None,
                  spill_manager=None,
                  gather_threads: Optional[int] = None,
                  on_bad_file: str = "raise",
                  fault_policies: Optional[Dict[str, Any]] = None,
                  window: Optional[Dict[str, Any]] = None
                  ) -> List[ex.TaskRef]:
    """Launch one epoch's map/reduce and route outputs to trainers
    (reference: shuffle.py:163-196). Returns the reducer TaskRefs.

    The epoch is executed as an explicit :class:`plan.ir.EpochPlan`
    (files -> map partitions -> reduce slices -> queue routes) driven by
    the plan scheduler (plan/scheduler.py): dependency-ordered dispatch
    onto the pool, optional speculative re-execution of stragglers
    (``RSDL_PLAN_SPECULATION``) and work-stealing placement
    (``RSDL_PLAN_STEALING``) — on both executor backends.

    ``fault_policies`` carries the per-stage RetryPolicy objects built
    once by the driver (keys ``read``/``reduce``/``lineage``); when
    omitted they resolve from the runtime policy registry here — so a
    directly-driven epoch still recovers lost maps from lineage.
    """
    from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
    if stats_collector is not None:
        stats_collector.epoch_start(epoch)
    plan = plan_ir.build_epoch_plan(filenames, num_reducers, num_trainers,
                                    seed, epoch, window=window)
    if getattr(pool, "backend", "thread") == "process":
        reduce_refs = _shuffle_epoch_process(
            plan, pool, stats_collector, map_transform, reduce_transform,
            spill_manager, gather_threads, on_bad_file)
    else:
        reduce_refs = _shuffle_epoch_thread(
            plan, pool, stats_collector, map_transform, file_cache,
            reduce_transform, spill_manager, gather_threads, on_bad_file,
            fault_policies)
    # Queue routes come FROM the plan: each route node names its trainer
    # rank, queue index and contiguous reducer span (the arithmetic the
    # inline ops.contiguous_splits call used to re-derive).
    for route in sorted(plan.routes(), key=lambda n: n.key.task):
        rank = route.key.task
        batches = [reduce_refs[i] for i in route.meta["reducers"]]
        consume(rank, batch_consumer, trial_start, stats_collector,
                epoch, batches)
        # Epoch-end sentinel per trainer (reference: shuffle.py:195).
        batch_consumer(rank, epoch, None)
    return reduce_refs


def _shuffle_epoch_thread(plan, pool, stats_collector, map_transform,
                          file_cache, reduce_transform, spill_manager,
                          gather_threads, on_bad_file, fault_policies
                          ) -> List[ex.TaskRef]:
    """Thread-backend epoch engine: the plan's map/reduce nodes dispatch
    onto the thread pool in dependency order. Reduce tasks keep their
    :class:`EpochLineage` recovery (a failed map ref is recomputed inline
    by the first reduce that observes it — recompute counts and failure
    semantics are unchanged by the plan engine underneath). Speculative
    backup attempts run under ``telemetry.speculative()`` with no stats
    collector, so duplicated work never double-counts anywhere."""
    from ray_shuffling_data_loader_tpu.plan import scheduler as plan_sched
    epoch, seed = plan.epoch, plan.seed
    num_reducers = plan.num_reducers
    filenames_list = list(plan.filenames)
    policies = fault_policies if fault_policies is not None \
        else default_fault_policies()
    if gather_threads is None:
        gather_threads = derive_gather_threads(num_reducers,
                                               pool.num_workers)
    lineage = EpochLineage(filenames_list, num_reducers, seed, epoch,
                           stats_collector, map_transform, file_cache,
                           retry_policy=policies.get("lineage"),
                           on_bad_file=on_bad_file,
                           read_retry=policies.get("read"))

    def _spill_recompute_for(reduce_index: int):
        if spill_manager is None:
            return None
        return functools.partial(
            recompute_reducer_output, filenames_list, num_reducers, seed,
            epoch, reduce_index, map_transform, reduce_transform,
            on_bad_file)

    holder: Dict[str, Any] = {}

    def _run_map(node, attempt: int):
        file_index = node.key.task
        if attempt == 0:
            return shuffle_map(node.meta["file"], num_reducers, seed,
                               epoch, file_index, stats_collector,
                               map_transform, file_cache, on_bad_file,
                               policies.get("read"))
        with rt_telemetry.speculative(attempt):
            return shuffle_map(node.meta["file"], num_reducers, seed,
                               epoch, file_index, None, map_transform,
                               file_cache, on_bad_file,
                               policies.get("read"))

    def _run_reduce(node, attempt: int):
        reduce_index = node.key.task
        map_refs = [holder["scheduler"].ref_for(dep) for dep in node.deps]
        if attempt == 0:
            return _reduce_task(reduce_index, seed, epoch, map_refs,
                                stats_collector, reduce_transform,
                                spill_manager, gather_threads, lineage,
                                policies.get("reduce"),
                                _spill_recompute_for(reduce_index))
        with rt_telemetry.speculative(attempt):
            return _reduce_task(reduce_index, seed, epoch, map_refs,
                                None, reduce_transform, spill_manager,
                                gather_threads, lineage,
                                policies.get("reduce"),
                                _spill_recompute_for(reduce_index))

    # Plan-driven cache warming (storage/prefetch.py): when the cache is
    # a TieredStore, idle scheduler lanes warm the files the NEXT epoch
    # re-reads (the plan's file list is the same every epoch). Below
    # steal/speculation priority; canceled when real work lands.
    prefetcher = None
    maker = getattr(file_cache, "make_prefetcher", None)
    if maker is not None and rt_policy.resolve("storage",
                                               "storage_prefetch"):
        prefetcher = maker(plan)
    scheduler = plan_sched.PlanScheduler(
        plan, pool,
        dispatchers={
            "map": lambda node, attempt: pool.submit(_run_map, node,
                                                     attempt),
            "reduce": lambda node, attempt: pool.submit(_run_reduce, node,
                                                        attempt),
        },
        prefetcher=prefetcher)
    holder["scheduler"] = scheduler
    scheduler.start()
    return scheduler.refs("reduce")


def _shuffle_epoch_process(plan, pool, stats_collector, map_transform,
                           reduce_transform, spill_manager, gather_threads,
                           on_bad_file):
    """Process-backend epoch launch: delegate the PLAN to the pool's data
    plane (procpool.process_epoch) with the workload hooks pickled once.
    The spill-recompute lineage closure is driver-side (identical to the
    thread path), so a corrupt spilled segment recovers the same way on
    either backend."""
    import pickle as _pickle
    from ray_shuffling_data_loader_tpu import procpool
    epoch, seed = plan.epoch, plan.seed
    num_reducers = plan.num_reducers
    filenames_list = list(plan.filenames)
    if gather_threads is None:
        gather_threads = derive_gather_threads(num_reducers,
                                               pool.num_workers)

    def _spill_recompute_factory(reduce_index: int):
        return functools.partial(
            recompute_reducer_output, filenames_list, num_reducers, seed,
            epoch, reduce_index, map_transform, reduce_transform,
            on_bad_file)

    return procpool.process_epoch(
        plan, pool, stats_collector,
        _pickle.dumps(map_transform) if map_transform is not None else None,
        _pickle.dumps(reduce_transform)
        if reduce_transform is not None else None,
        spill_manager, gather_threads, on_bad_file,
        _spill_recompute_factory if spill_manager is not None else None)


def shuffle(filenames: Sequence[str],
            batch_consumer: BatchConsumer,
            num_epochs: int,
            num_reducers: int,
            num_trainers: int,
            max_concurrent_epochs: int = 2,
            seed: int = 0,
            num_workers: Optional[int] = None,
            collect_stats: bool = True,
            pool: Optional[ex.Executor] = None,
            start_epoch: int = 0,
            map_transform: Optional[MapTransform] = None,
            file_cache: Union[FileTableCache, None, str] = "auto",
            reduce_transform: Optional[ReduceTransform] = None,
            task_retries: int = 0,
            max_inflight_bytes: Optional[int] = None,
            spill_dir: Optional[str] = None,
            on_bad_file: Optional[str] = None,
            executor_backend: Optional[str] = None
            ) -> Union[stats_mod.TrialStats, float]:
    """Multi-epoch pipelined shuffle driver (reference: shuffle.py:79-160).

    Keeps at most ``max_concurrent_epochs`` epochs' shuffles in flight:
    before launching epoch E, blocks on the oldest incomplete epoch's
    reducers and then drops their refs so Arrow buffers already consumed
    by trainers can be freed (reference: shuffle.py:103-140).

    ``max_inflight_bytes`` bounds TRANSIENT pipeline memory (in-flight map
    and reducer tables as accounted by the buffer ledger, file-cache bytes
    excluded): before launching a new epoch, waits — first by draining
    older epochs, then blocked on ledger release events (every consumer
    table release wakes the wait, runtime/release.py) — until under
    budget. The explicit analog of the reference operators sizing the
    plasma store and disabling spill (reference: benchmarks/cluster.yaml:175),
    with plasma's release-wakes-producer semantics. The budget must exceed
    one epoch's working set; if consumers do not release within
    ``_BUDGET_POLL_TIMEOUT_S`` (policy key ``budget_wait_timeout_s``) the
    launch proceeds with a warning rather than deadlocking.

    ``spill_dir`` (with ``max_inflight_bytes``) enables plasma's spill
    role: reducer outputs produced while over budget are written to Arrow
    IPC files under a scratch subdir and lazily memory-mapped back by the
    consumer (spill.py) — budgets smaller than one epoch's working set
    then make progress instead of warning.

    ``start_epoch`` > 0 (checkpoint resume) skips shuffling the already-
    fully-consumed epochs; epoch PRNG keys depend only on (seed, epoch),
    so the produced epochs replay exactly.

    Failure semantics (runtime/faults.py, runtime/retry.py): a failed
    map task is recomputed from its ``(seed, epoch, file)`` lineage by
    the first reduce gather that observes it; a failed reduce body is
    re-run in-task; both under bounded, jittered RetryPolicies — and
    only exhausted recovery propagates to the caller (and from there to
    the ``ShuffleFailure`` poison pill). ``on_bad_file`` (default
    ``"raise"``, policy key ``RSDL_SHUFFLE_ON_BAD_FILE``) set to
    ``"skip"`` quarantines a corrupt/unreadable input file into a
    structured ``QuarantinedFile`` report and shuffles the remaining
    files instead of failing the epoch.

    Returns ``TrialStats`` when ``collect_stats`` else the wall-clock
    duration in seconds (reference: shuffle.py:155-160).
    """
    from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
    if not 0 <= start_epoch <= num_epochs:
        raise ValueError(
            f"start_epoch {start_epoch} out of range [0, {num_epochs}]")
    stats_collector = None
    if collect_stats:
        if start_epoch:
            raise ValueError(
                "collect_stats with start_epoch > 0 is unsupported (stats "
                "collectors assume all epochs run)")
        stats_collector = stats_mod.TrialStatsCollector(
            num_epochs, num_maps=len(filenames), num_reduces=num_reducers,
            num_consumes=num_trainers)
        stats_collector.trial_start()
    duration = shuffle_epochs(
        plan_ir.static_epoch_specs(filenames, num_epochs, start_epoch),
        batch_consumer, num_reducers, num_trainers,
        max_concurrent_epochs=max_concurrent_epochs, seed=seed,
        num_workers=num_workers, pool=pool,
        stats_collector=stats_collector, map_transform=map_transform,
        file_cache=file_cache, reduce_transform=reduce_transform,
        task_retries=task_retries, max_inflight_bytes=max_inflight_bytes,
        spill_dir=spill_dir, on_bad_file=on_bad_file,
        executor_backend=executor_backend,
        epochs_hint=num_epochs - start_epoch)
    if stats_collector is not None:
        stats_collector.trial_done()
        return stats_collector.get_stats()
    return duration


def shuffle_epochs(epoch_specs,
                   batch_consumer: BatchConsumer,
                   num_reducers: int,
                   num_trainers: int,
                   max_concurrent_epochs: int = 2,
                   seed: int = 0,
                   num_workers: Optional[int] = None,
                   pool: Optional[ex.Executor] = None,
                   stats_collector=None,
                   map_transform: Optional[MapTransform] = None,
                   file_cache: Union[FileTableCache, None, str] = "auto",
                   reduce_transform: Optional[ReduceTransform] = None,
                   task_retries: int = 0,
                   max_inflight_bytes: Optional[int] = None,
                   spill_dir: Optional[str] = None,
                   on_bad_file: Optional[str] = None,
                   executor_backend: Optional[str] = None,
                   epochs_hint: Optional[int] = None,
                   on_epoch_done: Optional[Callable[[int], None]] = None
                   ) -> float:
    """The generalized pipelined driver: shuffle every epoch an
    *iterator* of :class:`plan.ir.EpochSpec` yields, keeping at most
    ``max_concurrent_epochs`` in flight.

    This is :func:`shuffle` with the epoch schedule inverted out: the
    static trial passes :func:`plan.ir.static_epoch_specs`; a streaming
    window assembler (``streaming/window.py``) yields specs unboundedly
    as windows close, and may BLOCK in ``__next__`` waiting for input —
    the pipeline then idles with all launched epochs still draining.

    ``epochs_hint`` sizes the decoded-file cache and the gather-thread
    overlap for finite schedules; ``None`` (unbounded stream, every file
    shuffled exactly once) disables the cache and sizes overlap at the
    concurrency cap. ``on_epoch_done(epoch)`` fires after an epoch's
    reducer refs fully drain — the streaming runner's serve-watermark
    hook. Returns the wall-clock duration in seconds.
    """
    # Causal-trace context: every id this run's spans carry derives from
    # (seed, epoch, task); stamping the seed puts it into recorder dumps
    # so offline merges re-derive the same ids (runtime/trace.py).
    rt_telemetry.set_trace_seed(seed)
    start = timeit.default_timer()

    owns_pool = pool is None
    if pool is None:
        # Backend selection (kwarg > RSDL_EXECUTOR_BACKEND > auto): the
        # process pool is the multicore data plane; the thread pool stays
        # the fallback whenever shared memory / picklable hooks are not
        # available (procpool.resolve_backend).
        from ray_shuffling_data_loader_tpu import procpool
        backend = procpool.resolve_backend(
            override=executor_backend, num_workers=num_workers,
            transforms=(map_transform, reduce_transform))
        if backend == "process":
            pool = procpool.ProcessPoolExecutor(num_workers=num_workers,
                                                task_retries=task_retries)
        else:
            pool = ex.Executor(num_workers=num_workers,
                               task_retries=task_retries)
    process_backend = getattr(pool, "backend", "thread") == "process"
    if process_backend:
        # The pool's shm segment arena IS the decoded-file cache in
        # process mode (cross-epoch table segments); a driver-side table
        # cache would just duplicate the resident set. The pool exposes
        # `bytes_cached`, so the transient-byte budget discounts cache
        # growth exactly like a FileTableCache.
        file_cache, owns_file_cache = None, False
        budget_cache = pool
    else:
        # Caching only pays when a file is mapped more than once. An
        # unbounded stream (epochs_hint None) maps each window's files
        # exactly once, so it resolves as a single-pass trial.
        file_cache, owns_file_cache = resolve_file_cache(
            file_cache, epochs_hint if epochs_hint is not None else 1)
        budget_cache = file_cache
        if hasattr(file_cache, "set_transform"):
            # The cache stores TRANSFORMED tables (the map stage puts
            # them post-transform); the prefetch warmer must apply the
            # same hook or a warmed hit would change the stream.
            file_cache.set_transform(map_transform)
    from ray_shuffling_data_loader_tpu.spill import make_budget_state
    _over_budget, spill_manager = make_budget_state(
        budget_cache, max_inflight_bytes, spill_dir)
    # Epoch pipelining keeps up to max_concurrent_epochs epochs' reduce
    # tasks in flight on this one pool — size gather threads for that
    # total, not one epoch's worth (but no more epochs than actually run;
    # an unbounded stream saturates the concurrency cap).
    overlap = max(1, max_concurrent_epochs) if epochs_hint is None \
        else max(1, min(max_concurrent_epochs, epochs_hint))
    gather_threads = derive_gather_threads(
        num_reducers * overlap, pool.num_workers)
    from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
    on_bad_file = rt_policy.resolve("shuffle", "on_bad_file",
                                    override=on_bad_file)
    fault_policies = default_fault_policies()

    try:
        in_progress: Dict[int, List[ex.TaskRef]] = {}
        for spec in epoch_specs:
            epoch_idx = spec.epoch
            throttle_start = timeit.default_timer()
            while in_progress and (len(in_progress) >= max_concurrent_epochs
                                   or _over_budget()):
                oldest_epoch = min(in_progress)
                refs = in_progress.pop(oldest_epoch)
                ex.wait(refs, num_returns=len(refs))
                for ref in refs:
                    ref.result()  # propagate map/reduce failures (instant)
                # Refs dropped here -> reducer Tables release once trainers
                # finish with them (reference: shuffle.py:131-132). The
                # frame's loop variables would otherwise pin the drained
                # epoch's last reducer table through the budget wait below.
                refs = ref = None
                if on_epoch_done is not None:
                    on_epoch_done(oldest_epoch)
            if _over_budget() and spill_manager is None:
                # All prior epochs drained; wait for consumers to release
                # tables (bounded — never deadlock the pipeline on a
                # too-small budget). With a spill manager the launch
                # proceeds instead: over-budget reducer outputs go to disk.
                # Event-driven: every last-ref ledger decref (and free-list
                # trim) wakes this wait immediately (runtime/release.py) —
                # plasma's release semantics, replacing the old periodic
                # process-wide gc.collect() cadence.
                from ray_shuffling_data_loader_tpu.runtime import (
                    policy as rt_policy, release as rt_release)
                timeout_s = rt_policy.resolve(
                    "shuffle", "budget_wait_timeout_s",
                    default=_BUDGET_POLL_TIMEOUT_S)
                if not rt_release.wait_while(
                        _over_budget, timeout_s=timeout_s,
                        heartbeat_s=rt_policy.resolve(
                            "shuffle", "release_heartbeat_s")):
                    logger.warning(
                        "epoch %d launching over max_inflight_bytes=%d "
                        "(consumers did not release within %.0fs)",
                        epoch_idx, max_inflight_bytes, timeout_s)
            throttle_duration = timeit.default_timer() - throttle_start
            if stats_collector is not None and throttle_duration > 1e-4:
                stats_collector.throttle_done(epoch_idx, throttle_duration)
            if throttle_duration > 1e-4:
                logger.info("epoch %d throttled for %.3fs", epoch_idx,
                            throttle_duration)
            # An elastic world retopologizes per epoch spec: a window
            # sealed on a new membership view carries its own reducer
            # count (plan.ir.EpochSpec.num_reducers); the driver default
            # covers every fixed-world spec. Trainer count never moves,
            # so the queue-route keys stay stable across resizes.
            spec_reducers = (spec.num_reducers
                             if getattr(spec, "num_reducers", None)
                             else num_reducers)
            in_progress[epoch_idx] = shuffle_epoch(
                epoch_idx, spec.filenames, batch_consumer, spec_reducers,
                num_trainers, pool, seed, start, stats_collector,
                map_transform, file_cache, reduce_transform, spill_manager,
                gather_threads, on_bad_file, fault_policies,
                window=spec.window)
        # Final drain: wait for all remaining reducer tasks
        # (reference: shuffle.py:148-151).
        for epoch_idx in sorted(in_progress):
            refs = in_progress.pop(epoch_idx)
            ex.wait(refs, num_returns=len(refs))
            for ref in refs:
                ref.result()  # propagate map/reduce failures (instant)
            refs = ref = None
            if on_epoch_done is not None:
                on_epoch_done(epoch_idx)
    finally:
        if owns_pool:
            pool.shutdown()
        if owns_file_cache:
            # Reducer outputs are gathered COPIES, never views of cached
            # tables, and all refs were drained above — the scratch files
            # have no remaining readers.
            file_cache.close()
        if spill_manager is not None:
            # Scratch-dir deletion is reference-managed (consumers may
            # still be draining spilled batches from the queue).
            spill_manager.report()
        if owns_pool:
            # End-of-trial hygiene: give the pool's recycled buffers back
            # to the OS instead of pinning up to the freelist cap between
            # trials. Gated like pool.shutdown(): a caller-supplied pool
            # signals deliberate cross-trial reuse, where warm buffers are
            # the point.
            from ray_shuffling_data_loader_tpu import native
            native.trim_freelist()

    return timeit.default_timer() - start


def shuffle_with_stats(
        filenames: Sequence[str],
        batch_consumer: BatchConsumer,
        num_epochs: int,
        num_reducers: int,
        num_trainers: int,
        max_concurrent_epochs: int = 2,
        seed: int = 0,
        num_workers: Optional[int] = None,
        utilization_sample_period: float = 5.0,
        map_transform: Optional[MapTransform] = None,
        file_cache: Union[FileTableCache, None, str] = "auto",
        reduce_transform: Optional[ReduceTransform] = None,
        task_retries: int = 0,
        max_inflight_bytes: Optional[int] = None,
        spill_dir: Optional[str] = None,
        on_bad_file: Optional[str] = None
) -> Tuple[stats_mod.TrialStats, List]:
    """Shuffle plus a concurrent memory-utilization sampler thread
    (reference: shuffle.py:21-55). Forwards the workload hooks
    (map/reduce transforms, file cache, retries) so the stats-collecting
    benchmark path can measure e.g. the decode-in-reducer ImageNet config."""
    store_stats: List = []
    done_event = stats_mod.start_store_stats_sampler(
        store_stats, sample_period_s=utilization_sample_period)
    try:
        trial_stats = shuffle(filenames, batch_consumer, num_epochs,
                              num_reducers, num_trainers,
                              max_concurrent_epochs, seed=seed,
                              num_workers=num_workers, collect_stats=True,
                              map_transform=map_transform,
                              file_cache=file_cache,
                              reduce_transform=reduce_transform,
                              task_retries=task_retries,
                              max_inflight_bytes=max_inflight_bytes,
                              spill_dir=spill_dir,
                              on_bad_file=on_bad_file)
    finally:
        done_event.set()
    return trial_stats, store_stats


def shuffle_no_stats(filenames: Sequence[str],
                     batch_consumer: BatchConsumer,
                     num_epochs: int,
                     num_reducers: int,
                     num_trainers: int,
                     max_concurrent_epochs: int = 2,
                     seed: int = 0,
                     num_workers: Optional[int] = None,
                     map_transform: Optional[MapTransform] = None,
                     file_cache: Union[FileTableCache, None, str] = "auto",
                     reduce_transform: Optional[ReduceTransform] = None,
                     task_retries: int = 0,
                     max_inflight_bytes: Optional[int] = None,
                     spill_dir: Optional[str] = None,
                     on_bad_file: Optional[str] = None
                     ) -> Tuple[float, List]:
    """Duration-only variant (reference: shuffle.py:58-76)."""
    duration = shuffle(filenames, batch_consumer, num_epochs, num_reducers,
                       num_trainers, max_concurrent_epochs, seed=seed,
                       num_workers=num_workers, collect_stats=False,
                       map_transform=map_transform, file_cache=file_cache,
                       reduce_transform=reduce_transform,
                       task_retries=task_retries,
                       max_inflight_bytes=max_inflight_bytes,
                       spill_dir=spill_dir, on_bad_file=on_bad_file)
    return duration, []


def run_shuffle_in_background(
        filenames: Sequence[str],
        batch_consumer: BatchConsumer,
        num_epochs: int,
        num_reducers: int,
        num_trainers: int,
        max_concurrent_epochs: int = 2,
        seed: int = 0,
        num_workers: Optional[int] = None,
        collect_stats: bool = False,
        start_epoch: int = 0,
        map_transform: Optional[MapTransform] = None,
        file_cache: Union[FileTableCache, None, str] = "auto",
        reduce_transform: Optional[ReduceTransform] = None,
        task_retries: int = 0,
        max_inflight_bytes: Optional[int] = None,
        spill_dir: Optional[str] = None,
        on_bad_file: Optional[str] = None,
        on_failure: Optional[Callable[[BaseException], None]] = None
        ) -> ex.TaskRef:
    """Launch the whole multi-epoch shuffle as one background task.

    Stands in for the reference driver's ``ray.remote(shuffle).remote(...)``
    (reference: dataset.py:110-118): the returned TaskRef is the
    ``shuffle_result`` handle the dataset joins after the last epoch.

    ``on_failure`` is invoked (once, from the driver thread) if the shuffle
    dies, BEFORE the error is stored in the returned ref — the dataset layer
    uses it to poison-pill trainer queues so blocked consumers fail fast
    instead of hanging (the reference has no equivalent: a dead Ray shuffle
    task leaves trainers blocked on the queue actor forever).
    """
    # A dedicated single-worker executor hosts the driver loop so it never
    # competes with map/reduce workers for a pool slot.
    driver_pool = ex.Executor(num_workers=1, thread_name_prefix="rsdl-driver")

    def _run():
        try:
            return shuffle(filenames, batch_consumer, num_epochs,
                           num_reducers, num_trainers, max_concurrent_epochs,
                           seed=seed, num_workers=num_workers,
                           collect_stats=collect_stats,
                           start_epoch=start_epoch,
                           map_transform=map_transform,
                           file_cache=file_cache,
                           reduce_transform=reduce_transform,
                           task_retries=task_retries,
                           max_inflight_bytes=max_inflight_bytes,
                           spill_dir=spill_dir, on_bad_file=on_bad_file)
        except BaseException as e:  # noqa: BLE001 - forwarded to consumers
            if on_failure is not None:
                try:
                    on_failure(e)
                except Exception:  # noqa: BLE001
                    logger.exception("shuffle on_failure hook itself failed")
            raise
        finally:
            driver_pool.shutdown(wait_for_tasks=False)

    return driver_pool.submit(_run)


def run_shuffle_epochs_in_background(
        epoch_specs,
        batch_consumer: BatchConsumer,
        num_reducers: int,
        num_trainers: int,
        max_concurrent_epochs: int = 2,
        seed: int = 0,
        num_workers: Optional[int] = None,
        file_cache: Union[FileTableCache, None, str] = "auto",
        task_retries: int = 0,
        max_inflight_bytes: Optional[int] = None,
        spill_dir: Optional[str] = None,
        on_bad_file: Optional[str] = None,
        epochs_hint: Optional[int] = None,
        on_epoch_done: Optional[Callable[[int], None]] = None,
        on_failure: Optional[Callable[[BaseException], None]] = None
        ) -> ex.TaskRef:
    """:func:`run_shuffle_in_background` for an epoch-spec schedule: the
    driver loop consumes ``epoch_specs`` (an iterable/iterator of
    :class:`plan.ir.EpochSpec` — a streaming window schedule, possibly
    unbounded) on a dedicated single-worker executor. Same ``on_failure``
    poison-pill contract as the static launcher."""
    driver_pool = ex.Executor(num_workers=1, thread_name_prefix="rsdl-driver")

    def _run():
        try:
            return shuffle_epochs(
                epoch_specs, batch_consumer, num_reducers, num_trainers,
                max_concurrent_epochs=max_concurrent_epochs, seed=seed,
                num_workers=num_workers, file_cache=file_cache,
                task_retries=task_retries,
                max_inflight_bytes=max_inflight_bytes, spill_dir=spill_dir,
                on_bad_file=on_bad_file, epochs_hint=epochs_hint,
                on_epoch_done=on_epoch_done)
        except BaseException as e:  # noqa: BLE001 - forwarded to consumers
            if on_failure is not None:
                try:
                    on_failure(e)
                except Exception:  # noqa: BLE001
                    logger.exception("shuffle on_failure hook itself failed")
            raise
        finally:
            driver_pool.shutdown(wait_for_tasks=False)

    return driver_pool.submit(_run)
