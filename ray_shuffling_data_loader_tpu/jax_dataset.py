"""JAX/TPU binding: shuffled batches as device-resident ``jax.Array``s.

L4 equivalent of the reference's Torch binding (reference:
torch_dataset.py:12-238): a column spec (names + shapes + dtypes for
features, plus a label column) is normalized with the same rules, and each
iterator batch is converted column-by-column into arrays shaped
``(batch, *shape)`` (default ``(batch, 1)``).

TPU-native design: instead of CPU torch tensors that the trainer later
copies to GPU (reference: torch_dataset.py:206-238 + the trainer's
``.cuda()`` at ray_torch_shuffle.py:189-192), conversion lands batches
directly in device memory as sharded ``jax.Array``s: a background prefetch
thread converts Arrow columns to NumPy (zero-copy where possible) and
``jax.device_put``s the *next* batches onto the device mesh while the
current step runs — double-buffering host->HBM copies behind compute.
Batch-axis sharding uses ``NamedSharding(mesh, P("data", ...))`` so a
multi-chip DP trainer receives its shard without any gather.

The iterator also records the north-star stall metric — time blocked
waiting for a batch (reference: ray_torch_shuffle.py:186-218) — in
``batch_wait_stats``.
"""

from __future__ import annotations

import concurrent.futures as cf
import itertools
import queue as _queue
import threading
import timeit
from typing import Any, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
from ray_shuffling_data_loader_tpu.dataset import (ShufflingDataset,
                                                   slice_batches)
from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
from ray_shuffling_data_loader_tpu.runtime import latency as rt_latency
from ray_shuffling_data_loader_tpu.runtime import retry as rt_retry
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.stats import BatchWaitStats
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger
from ray_shuffling_data_loader_tpu.utils.tracing import trace_span

logger = setup_custom_logger(__name__)


def _normalize_jax_data_spec(feature_columns=None,
                             feature_shapes=None,
                             feature_types=None,
                             label_column=None,
                             label_shape=None,
                             label_type=None):
    """Normalize the column spec with the reference's rules
    (reference: torch_dataset.py:146-204): scalars become lists, shapes
    must match feature count, dtypes default to float32.
    """
    import jax.numpy as jnp

    if not isinstance(feature_columns, list):
        feature_columns = [feature_columns]

    if feature_shapes:
        if not isinstance(feature_shapes, list):
            feature_shapes = [feature_shapes]
        if len(feature_columns) != len(feature_shapes):
            raise ValueError(
                "The feature_shapes size must match the feature_columns")
        feature_shapes = [
            tuple(s) if isinstance(s, (list, tuple))
            else (None if s is None else (s,))
            for s in feature_shapes
        ]
    else:
        feature_shapes = [None] * len(feature_columns)

    if feature_types:
        if not isinstance(feature_types, list):
            feature_types = [feature_types]
        if len(feature_columns) != len(feature_types):
            raise ValueError(
                "The feature_types size must match the feature_columns")
        feature_types = [np.dtype(t) for t in feature_types]
    else:
        feature_types = [np.dtype(jnp.float32)] * len(feature_columns)

    if label_type is None:
        label_type = np.dtype(jnp.float32)
    else:
        label_type = np.dtype(label_type)

    return (feature_columns, feature_shapes, feature_types, label_column,
            label_shape, label_type)


def _column_to_numpy(column: pa.ChunkedArray, dtype: np.dtype) -> np.ndarray:
    """Arrow column -> contiguous ndarray, zero-copy when types align.

    Handles the reference's object-column cases (ndarray / list / tuple
    cells, reference: torch_dataset.py:211-223): Arrow list columns become
    stacked 2-D arrays.
    """
    if column.num_chunks == 1:
        combined = column.chunk(0)
    else:
        # Blessed: reducer outputs arrive single-chunk (fused gather), so
        # this arm only runs for carry-buffer concatenations at batch
        # boundaries. rsdl-lint: disable=copy-in-hot-path
        combined = column.combine_chunks()
    if (pa.types.is_fixed_size_list(combined.type)
            and pa.types.is_primitive(combined.type.value_type)
            and combined.null_count == 0):
        # Fast path for image pixels / token sequences: the child values
        # buffer IS the (rows * list_size) array — flatten() respects the
        # slice offset, so the reshape is zero-copy.
        width = combined.type.list_size
        # Blessed conversion boundary: the device transfer needs a host
        # ndarray; flatten() is zero-copy here (primitive child buffer).
        # rsdl-lint: disable=copy-in-hot-path
        flat = combined.flatten().to_numpy(zero_copy_only=False)
        arr = flat.reshape(-1, width)
    elif pa.types.is_list(combined.type) \
            or pa.types.is_large_list(combined.type) \
            or pa.types.is_fixed_size_list(combined.type):
        # Blessed: ragged lists have no zero-copy ndarray form — the
        # stack IS the conversion. rsdl-lint: disable=copy-in-hot-path
        arr = np.stack(combined.to_numpy(zero_copy_only=False))
    else:
        # Blessed: primitive null-free columns come back zero-copy; the
        # permissive flag only covers the object-cell fallback below.
        # rsdl-lint: disable=copy-in-hot-path
        arr = combined.to_numpy(zero_copy_only=False)
        if arr.dtype == object:
            first = arr[0] if len(arr) else None
            if isinstance(first, np.ndarray):
                arr = np.stack(arr)
            elif isinstance(first, (list, tuple)):
                arr = np.asarray([list(x) for x in arr])
            else:
                raise TypeError(
                    f"Column cell type {type(first)} is not supported. It "
                    "must be a numeric type or an object of (ndarray, list, "
                    "tuple)")
    return np.ascontiguousarray(arr.astype(dtype, copy=False))


def make_cast_transform(feature_columns: Sequence[Any],
                        feature_types: Sequence[np.dtype],
                        label_column: Any,
                        label_type: np.dtype):
    """Map-time column-cast hook: cast spec'd numeric columns to their final
    dtypes right after the Parquet read, BEFORE any shuffling.

    The reference converts dtypes per batch on the trainer
    (reference: torch_dataset.py:206-238); casting at the map stage instead
    (e.g. int64 -> int32) halves the memory traffic of every downstream
    stage — partition, permute-gather, re-batch, host->device DMA. Columns
    that are not primitive numerics, are nullable, or are not in the spec
    pass through untouched. The cast is an unchecked ``ndarray.astype``
    (same semantics as the reference's ``torch.as_tensor(..., dtype=)``).
    """
    targets = {}
    for col, dtype in zip(feature_columns, feature_types):
        targets[col] = np.dtype(dtype)
    targets[label_column] = np.dtype(label_type)
    return CastTransform(targets)


class CastTransform:
    """The map-time cast hook as a picklable callable: the process-pool
    executor ships transforms to its workers by pickle, and a closure
    would silently force the thread backend for every cast-at-map
    workload (procpool.resolve_backend's picklability gate). State is
    one ``{column -> np.dtype}`` dict."""

    __slots__ = ("targets",)

    #: Per-row independent and row-count preserving: the fused streaming
    #: map pipeline (shuffle._fused_stream_columns) may apply this per
    #: record batch instead of per file — same bytes either way.
    row_elementwise = True

    def __init__(self, targets):
        self.targets = dict(targets)

    def __call__(self, table: pa.Table) -> pa.Table:
        columns = []
        changed = False
        for field in table.schema:
            col = table.column(field.name)
            target = self.targets.get(field.name)
            if (target is not None and col.null_count == 0
                    and (pa.types.is_integer(field.type)
                         or pa.types.is_floating(field.type))
                    and np.issubdtype(target, np.number)
                    and pa.from_numpy_dtype(target) != field.type):
                if col.num_chunks == 1:
                    combined = col.chunk(0)
                else:
                    # Blessed: fresh-from-parquet tables are chunked per
                    # row group; the cast below re-materializes anyway.
                    # rsdl-lint: disable=copy-in-hot-path
                    combined = col.combine_chunks()
                # Blessed: the dtype-changing cast IS this hook's job (the
                # guard above skips same-dtype columns), and copy=False
                # keeps the would-be-no-op arm free.
                # rsdl-lint: disable=copy-in-hot-path
                col = pa.array(combined.to_numpy(
                    zero_copy_only=False).astype(target, copy=False))
                changed = True
            columns.append(col)
        if not changed:
            return table
        return pa.table(columns, names=table.column_names)


def convert_to_arrays(table: pa.Table,
                      feature_columns: List[Any],
                      feature_shapes: List[Optional[Tuple[int, ...]]],
                      feature_types: List[np.dtype],
                      label_column: Any,
                      label_shape: Optional[int],
                      label_type: np.dtype
                      ) -> Tuple[List[np.ndarray], np.ndarray]:
    """Arrow batch -> (per-feature arrays, label array), each reshaped to
    ``(batch, *shape)`` / ``(batch, 1)`` (reference: torch_dataset.py:206-238).
    """
    features = []
    for col, shape, dtype in zip(feature_columns, feature_shapes,
                                 feature_types):
        arr = _column_to_numpy(table.column(col), dtype)
        if shape is not None:
            arr = arr.reshape(-1, *shape)
        elif arr.ndim == 1:
            arr = arr.reshape(-1, 1)
        features.append(arr)
    label = _column_to_numpy(table.column(label_column), label_type)
    if label_shape:
        label = label.reshape(-1, label_shape)
    elif label.ndim == 1:
        label = label.reshape(-1, 1)
    return features, label


class _BatchConverter:
    """Self-contained Arrow-batch -> device-batch pipeline stage.

    Holds ONLY the column spec and transfer config — deliberately no
    reference to the dataset wrapper, so the persistent producer thread
    (which runs this) never pins the wrapper and a dropped
    ``JaxShufflingDataset`` can be garbage-collected (its finalizer then
    stops the producer).
    """

    def __init__(self, feature_columns, feature_shapes, feature_types,
                 label_column, label_shape, label_type, stack_features,
                 mesh, data_axis, device_put, device_rebatch=False,
                 device_rebatch_auto=False,
                 max_table_bytes=512 * 1024 * 1024,
                 watchdog=None, bulk_transfer_deadline_s=30.0,
                 stall_action="degrade"):
        self._feature_columns = feature_columns
        self._feature_shapes = feature_shapes
        self._feature_types = feature_types
        self._label_column = label_column
        self._label_shape = label_shape
        self._label_type = label_type
        self._stack_features = stack_features
        self._mesh = mesh
        self._data_axis = data_axis
        self._device_put = device_put
        self._device_concat = None  # jitted column concat, built lazily
        # Device-rebatch mode: whole reducer tables are transferred in bulk
        # and batch slicing happens on the accelerator (see
        # JaxShufflingDataset docstring). These two fields configure the
        # producer's table path; the per-batch path ignores them.
        self.device_rebatch = device_rebatch
        # True when rebatch was resolved from "auto" rather than requested
        # explicitly: specs the bulk path can't reproduce then fall back to
        # per-batch transfers instead of failing a previously-working job.
        self.device_rebatch_auto = device_rebatch_auto
        self.max_table_bytes = max_table_bytes
        # Liveness supervision of the bulk path (runtime/watchdog.py): a
        # chunk device_put/carve that misses the deadline is reported and
        # — under the default "degrade" stall action — permanently drops
        # this converter to the per-batch path (see _on_bulk_stall).
        self.watchdog = watchdog
        self.bulk_transfer_deadline_s = bulk_transfer_deadline_s
        self.stall_action = stall_action
        self.fallback_engaged = False  # a stall degraded the bulk path
        # Double-buffered device staging (RSDL_DEVICE_DOUBLE_BUFFER,
        # default on): the per-batch producer dispatches batch N's
        # host->device transfer on a staging thread while it converts
        # batch N+1 — upload overlaps host work, FIFO order preserved.
        # The owning JaxShufflingDataset overrides this from its resolved
        # runtime_policy; the resolve here covers direct converter use.
        from ray_shuffling_data_loader_tpu.runtime import (policy as
                                                           rt_policy)
        self.double_buffer = bool(
            rt_policy.resolve("jax_dataset", "device_double_buffer"))
        self._slicer = {}  # batch_size -> jitted batch slicer, built lazily
        # Transient device-transfer failures (tunnel hiccup, injected
        # `device_transfer` fault) are retried in place: the source arrays
        # are host-resident numpy, so a re-put is pure. Predicate is
        # IO-shaped only — a shape/dtype error is a bug and surfaces.
        self._transfer_retry = rt_retry.RetryPolicy.for_component(
            "jax_dataset", retryable=rt_retry.transient_retryable)
        self._transfer_seq = 0  # producer-thread-only; keys chaos draws
        # Delivery-latency probe (runtime/latency.py), installed by the
        # owning JaxShufflingDataset: convert() notes the source table's
        # birth, transfer completions close the delivered->device and
        # birth->device hops. None = no probe (direct converter use).
        self.latency_probe = None

    def _device_put_retried(self, thunk):
        """One (bulk or per-batch) device_put: named fault site + bounded
        retry; a recovery after failure is recorded in fault_stats. The
        per-converter transfer sequence keys the chaos site, so a rate
        rule (``device_transfer@0.02``) draws independently per transfer
        and a targeted rule can hit exactly one."""

        def _put():
            self._transfer_seq += 1
            # Attempt marker (no duration — not a stage sample): carries
            # the same task key the device_transfer fault site draws on,
            # so an injected transfer fault joins telemetry by
            # (kind, epoch, task) like every other site. The stage's
            # latency samples come from the epoch-tagged transfer spans.
            rt_telemetry.record("device_transfer", task=self._transfer_seq,
                                attempt=True)
            rt_faults.inject("device_transfer", task=self._transfer_seq)
            return thunk()

        def _recovered(failed_attempts: int, elapsed_s: float) -> None:
            from ray_shuffling_data_loader_tpu import stats as stats_mod
            stats_mod.fault_stats().record_recompute(
                "device_transfer", elapsed_s)

        return self._transfer_retry.call(_put, describe="device_put",
                                         on_recovery=_recovered)

    def _note_device_done(self) -> None:
        """One device-transfer completion on the latency plane (no-op
        without a probe). ``device_put`` is async — the span closed here
        is dispatch-complete, the same boundary the ``device_transfer``
        telemetry stage measures."""
        if self.latency_probe is not None:
            self.latency_probe.device_done()

    def _on_bulk_stall(self, report) -> None:
        """Watchdog escalation hook — runs on the MONITOR thread (the
        producer is, by definition, stuck inside the supervised call).
        Caps in-flight bulk bytes so any future chunk is smaller, and
        under the "degrade" action flips this converter to the per-batch
        path; the producer reroutes the moment the stuck call returns.
        """
        from ray_shuffling_data_loader_tpu import stats as stats_mod
        if report.escalation == 1:
            self.max_table_bytes = max(1, self.max_table_bytes // 2)
        if self.stall_action == "degrade" and self.device_rebatch:
            self.device_rebatch = False
            self.fallback_engaged = True
            reason = (f"{report.name} stalled {report.waited_s:.2f}s "
                      f"(deadline {report.deadline_s:.2f}s"
                      f"{', ' + report.detail if report.detail else ''}); "
                      "degrading to per-batch transfers")
            stats_mod.watchdog_stats().record_fallback(
                "jax_dataset.device_rebatch", reason)
            logger.warning("%s", reason)

    def _sharding(self, ndim: int):
        from jax.sharding import NamedSharding, PartitionSpec as P
        if self._mesh is None:
            return None
        return NamedSharding(
            self._mesh, P(self._data_axis, *([None] * (ndim - 1))))

    def convert(self, table: pa.Table):
        if self.latency_probe is not None:
            self.latency_probe.table_arrived(table)
        return convert_to_arrays(
            table, self._feature_columns, self._feature_shapes,
            self._feature_types, self._label_column, self._label_shape,
            self._label_type)

    def transfer(self, arrays_label):
        """Host arrays -> device arrays (sharded if a mesh was given).

        With ``stack_features``, per-column host arrays are transferred
        individually (zero-copy views of the Arrow buffers) and stacked by
        one jitted ``jnp.concatenate`` on device — the host-side strided
        interleave this replaces was a top host cost of the ingest path.
        """
        import jax
        features, label = arrays_label
        if not self._device_put:
            if self._stack_features:
                features = (features[0] if len(features) == 1
                            else np.concatenate(features, axis=1))
            self._note_device_done()
            return features, label
        # ONE device_put for the whole batch pytree: the runtime batches
        # the per-column copies into a single transfer (through the PJRT
        # client once, not once per column — on a tunneled device that is
        # the difference between 1 and 20 round-trips per batch).
        if self._mesh is None:
            out_features, out_label = self._device_put_retried(
                lambda: jax.device_put((features, label)))
        else:
            out_features, out_label = self._device_put_retried(
                lambda: jax.device_put(
                    (features, label),
                    ([self._sharding(a.ndim) for a in features],
                     self._sharding(label.ndim))))
        if self._stack_features:
            if len(out_features) == 1:
                out_features = out_features[0]
            else:
                if self._device_concat is None:
                    import jax.numpy as jnp
                    self._device_concat = jax.jit(
                        lambda cols: jnp.concatenate(cols, axis=1))
                out_features = self._device_concat(out_features)
        self._note_device_done()
        return out_features, out_label

    def transfer_table(self, arrays_label, n_batches: int, batch_size: int):
        """Bulk host->device transfer of a whole (multi-batch) chunk.

        One ``device_put`` moves every column's full span — a few ~MB
        transfers per reducer output instead of one dispatch per batch per
        column. Stacking/reshaping is deferred to :meth:`slice_batch`, which
        runs on device.

        Without a mesh, arrays go up as flat ``(n_batches * batch_size,
        ...)`` spans. With a mesh, each array is reshaped host-side
        (zero-copy) to ``(n_batches, batch_size, ...)`` and transferred with
        the BATCH dimension (axis 1) sharded over ``data_axis`` — every
        device receives its slice of every batch, so the per-batch carve in
        :meth:`slice_batch` is a free axis-0 index with no resharding.
        """
        import jax
        features, label = arrays_label
        if not self._device_put:
            self._note_device_done()
            return features, label
        if self._mesh is None:
            item = self._device_put_retried(
                lambda: jax.device_put((features, label)))
            self._note_device_done()
            return item
        from jax.sharding import NamedSharding, PartitionSpec as P

        def chunked(a):
            return a.reshape(n_batches, batch_size, *a.shape[1:])

        def sharding(a):
            return NamedSharding(
                self._mesh, P(None, self._data_axis,
                              *([None] * (a.ndim - 2))))

        features = [chunked(f) for f in features]
        label = chunked(label)
        item = self._device_put_retried(
            lambda: jax.device_put(
                (features, label),
                ([sharding(f) for f in features], sharding(label))))
        self._note_device_done()
        return item

    def slice_batch(self, dev_table, batch_index: int, batch_size: int):
        """Carve batch ``batch_index`` out of a bulk device chunk: one
        jitted program per chunk length (the index is a traced scalar, and
        chunk lengths are bounded at ``_MAX_CHUNK_BATCHES`` batches, so the
        compile set is small and reused across tables and epochs),
        producing the same ``(features, label)`` pytree the per-batch path
        yields. Flat (mesh-less) chunks dynamic-slice rows; mesh chunks are
        ``(n_batches, batch, ...)`` with the batch axis sharded, so the
        carve is a free axis-0 index that keeps the sharding. On TPU this
        rides HBM bandwidth; the host does no per-batch copy at all.
        """
        import jax
        chunked = self._mesh is not None
        slicer = self._slicer.get(batch_size)
        if slicer is None:
            from jax import lax
            import jax.numpy as jnp
            stack = self._stack_features

            def _slice(features, label, idx):
                if chunked:
                    fs = [lax.dynamic_index_in_dim(f, idx, 0, keepdims=False)
                          for f in features]
                    lb = lax.dynamic_index_in_dim(label, idx, 0,
                                                  keepdims=False)
                else:
                    fs = [lax.dynamic_slice_in_dim(
                              f, idx * batch_size, batch_size, axis=0)
                          for f in features]
                    lb = lax.dynamic_slice_in_dim(
                        label, idx * batch_size, batch_size, axis=0)
                if stack:
                    fs = fs[0] if len(fs) == 1 else jnp.concatenate(fs, axis=1)
                return fs, lb

            slicer = self._slicer[batch_size] = jax.jit(_slice)
        features, label = dev_table
        return slicer(features, label, np.int32(batch_index))


def _persistent_producer(dataset: ShufflingDataset,
                         converter: _BatchConverter,
                         out: "_queue.Queue",
                         stop: threading.Event,
                         lock: threading.Lock,
                         pending_skips: dict,
                         started_epochs: set) -> None:
    """Producer loop for ALL epochs (persistent_prefetch).

    Module-level on purpose: it references the underlying ShufflingDataset
    and small shared state objects but NOT the JaxShufflingDataset wrapper,
    so an abandoned wrapper is collectable and its weakref finalizer (which
    sets ``stop`` and drains ``out``) releases this thread even when the
    consumer never called close().
    """

    def put(item) -> bool:
        while not stop.is_set():
            try:
                out.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    try:
        # plan.ir.epoch_range: a bounded range for a classic trial, an
        # unbounded count when the dataset consumes a stream
        # (num_epochs=None) — the producer keeps entering epochs as
        # windows close server-side.
        for epoch in plan_ir.epoch_range(dataset.start_epoch,
                                         dataset.num_epochs):
            with lock:
                started_epochs.add(epoch)
                skip = pending_skips.pop(epoch, 0)
            dataset.set_epoch(epoch, skip_batches=skip)
            if converter.device_rebatch:
                if not _produce_epoch_tables(dataset, converter, epoch, put,
                                             queue_depth=out.qsize):
                    return
            elif converter.double_buffer:
                if not _produce_epoch_batches_staged(dataset, converter,
                                                     epoch, put):
                    return
            else:
                for table in dataset:
                    with trace_span("batch_convert", kind="convert",
                                    epoch=epoch):
                        arrays = converter.convert(table)
                    with trace_span("batch_transfer",
                                    kind="device_transfer", epoch=epoch):
                        batch = converter.transfer(arrays)
                    if not put(("batch", epoch, batch)):
                        return
            if not put(("end", epoch, None)):
                return
    except BaseException as e:  # noqa: BLE001 - forwarded to consumer
        put(e)


def _staged_transfer(converter: _BatchConverter, arrays, epoch):
    """One per-batch host->device transfer on the staging thread — the
    same retried/fault-injected ``converter.transfer`` (and the same
    telemetry span) the serial path runs inline."""
    with trace_span("batch_transfer", kind="device_transfer", epoch=epoch):
        return converter.transfer(arrays)


def _produce_epoch_batches_staged(dataset, converter: _BatchConverter,
                                  epoch: int, put) -> bool:
    """Per-batch producer loop with double-buffered device staging
    (``RSDL_DEVICE_DOUBLE_BUFFER``, default on): batch N's transfer is
    dispatched on a one-thread staging pool while this thread fetches
    and converts batch N+1, overlapping host->device upload with host
    decode/convert. Exactly one transfer is in flight and results are
    awaited FIFO, so delivery order, chaos draws and retry semantics are
    those of the serial path. Returns False when the consumer is gone."""
    with cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="rsdl-device-stage") as pool:
        pending = None
        for table in dataset:
            with trace_span("batch_convert", kind="convert", epoch=epoch):
                arrays = converter.convert(table)
            fut = pool.submit(_staged_transfer, converter, arrays, epoch)
            if pending is not None and not put(
                    ("batch", epoch, pending.result())):
                return False
            pending = fut
        if pending is not None and not put(
                ("batch", epoch, pending.result())):
            return False
    return True


# Upper bound on batches per bulk device chunk: caps both the jit slicer's
# compiled-shape set (chunk lengths are 1.._MAX_CHUNK_BATCHES batches) and
# per-chunk HBM bytes.
_MAX_CHUNK_BATCHES = 8


def _supervised_transfer_table(converter: _BatchConverter, arrays_label,
                               nb: int, bs: int, queue_depth):
    """One bulk chunk transfer under watchdog supervision.

    A wedged ``device_put`` (dying tunnel, stuck PJRT client) blocks
    this thread indefinitely; the watchdog's monitor detects the missed
    deadline WHILE it is stuck, files the stall (with the prefetch-queue
    depth — 0 means the consumer is blocked waiting on this very chunk),
    and fires the converter's escalation hook. When the call finally
    returns, a "raise" stall action surfaces here; "degrade" reroutes in
    the caller's loop via ``converter.device_rebatch``.
    """
    wd = converter.watchdog
    if wd is None:
        return converter.transfer_table(arrays_label, nb, bs)
    detail_fn = None
    if queue_depth is not None:
        detail_fn = lambda: (  # noqa: E731
            f"chunk={nb} batches, prefetch_queue_depth={queue_depth()} "
            "(0 = consumer blocked)")
    with wd.watch("jax_dataset.bulk_transfer",
                  deadline_s=converter.bulk_transfer_deadline_s,
                  on_stall=converter._on_bulk_stall,
                  detail_fn=detail_fn) as handle:
        item = converter.transfer_table(arrays_label, nb, bs)
    if handle.stalled and converter.stall_action == "raise":
        raise RuntimeError(
            f"bulk device transfer stalled: ran {handle.report.waited_s:.2f}s"
            f" against a {handle.report.deadline_s:.2f}s deadline "
            "(stall_action='raise')")
    return item


def _produce_epoch_tables(dataset: ShufflingDataset,
                          converter: _BatchConverter,
                          epoch: int,
                          put,
                          queue_depth=None) -> bool:
    """Device-rebatch producer for one epoch: bulk table transfers.

    Consumes RAW reducer tables (``ShufflingDataset.iter_tables``) instead
    of host-sliced batches. Each table's batch-aligned middle is moved to
    the device in multi-batch chunks (one dispatch per column per ~8
    batches, not per batch) and carved into batches on-device by the
    consumer. Rows that don't align with the batch grid — the tail of one
    table plus the head of the next — are stitched host-side into ordinary
    per-batch items, so the batch sequence is identical to the host
    re-batching path (same carry arithmetic as ``ShufflingDataset.__iter__``,
    reference: dataset.py:170-202).

    Workloads where a single batch exceeds ``converter.max_table_bytes``
    (fat rows, e.g. decoded images) fall back to per-batch transfers.
    """
    bs = dataset.batch_size
    carry: List[Tuple[List[np.ndarray], np.ndarray]] = []
    carry_rows = 0

    def flush_carry():
        pieces_f = [np.concatenate([p[0][i] for p in carry], axis=0)
                    for i in range(len(carry[0][0]))]
        pieces_l = np.concatenate([p[1] for p in carry], axis=0)
        with trace_span("batch_transfer", kind="device_transfer",
                        epoch=epoch):
            return converter.transfer((pieces_f, pieces_l))

    tables = dataset.iter_tables()
    emitted = False  # anything put() or carried yet this epoch
    for table in tables:
        with trace_span("table_convert", kind="convert", epoch=epoch):
            features, label = converter.convert(table)
        n = table.num_rows
        if any(f.shape[0] != n for f in features) or label.shape[0] != n:
            # A spec whose reshape repacks the sample dimension (e.g. a flat
            # column with feature_shape=(4,)) groups rows differently per
            # converted span, so bulk conversion cannot reproduce the host
            # path's per-batch grouping. When rebatch was an "auto" default
            # (not explicitly requested), fall back to the per-batch
            # convert+transfer path — same batch grid via slice_batches, so
            # the stream is identical to the host path. Only an explicit
            # device_rebatch=True fails loudly.
            if converter.device_rebatch_auto and not emitted:
                logger.warning(
                    "device_rebatch (auto) disabled: the column spec "
                    "repacks the sample dimension; using per-batch "
                    "transfers")
                # Permanent for this dataset: the spec-to-shape ratio is
                # constant across tables and epochs, so later epochs take
                # the per-batch path directly instead of rediscovering the
                # mismatch (and re-logging) every epoch.
                converter.device_rebatch = False
                for batch_table in slice_batches(
                        itertools.chain([table], tables), bs,
                        dataset.drop_last):
                    with trace_span("batch_convert", kind="convert",
                                    epoch=epoch):
                        arrays = converter.convert(batch_table)
                    with trace_span("batch_transfer",
                                    kind="device_transfer", epoch=epoch):
                        batch = converter.transfer(arrays)
                    if not put(("batch", epoch, batch)):
                        return False
                return True
            raise ValueError(
                "device_rebatch requires specs whose converted arrays keep "
                "one sample per table row; a feature_shape/label_shape "
                "repacks the sample dimension here. Construct with "
                "device_rebatch=False for this spec.")
        if n:
            emitted = True
        offset = 0
        if carry_rows:
            take = min(bs - carry_rows, n)
            carry.append(([f[:take] for f in features], label[:take]))
            carry_rows += take
            offset = take
            if carry_rows == bs:
                if not put(("batch", epoch, flush_carry())):
                    return False
                carry, carry_rows = [], 0
        full_batches = (n - offset) // bs
        if full_batches:
            row_bytes = (sum(a.nbytes for a in features) + label.nbytes) // n
            batch_bytes = max(1, row_bytes * bs)
            # Chunked bulk transfers, at most _MAX_CHUNK_BATCHES batches per
            # chunk and at most max_table_bytes per chunk. Fixed chunk sizes
            # keep the jitted slicer's shape set bounded (<= one compile per
            # chunk length, reused across tables and epochs) and bound
            # per-item HBM residency: the pipeline holds at most
            # ~(prefetch_size + 2) chunks on device at once. The per-chunk
            # cap is re-read every chunk: a watchdog stall halves it (and,
            # under the default "degrade" action, clears device_rebatch so
            # the rest of this table — and every later table — moves
            # per-batch instead of trusting the path that just wedged).
            done = 0  # full batches already emitted from this table
            while done < full_batches and converter.device_rebatch:
                k = min(_MAX_CHUNK_BATCHES, converter.max_table_bytes
                        // batch_bytes)
                if k < 1:
                    # Fat rows (a single batch exceeds the cap): per-batch
                    # transfers bound device residency.
                    break
                nb = min(k, full_batches - done)
                lo = offset + done * bs
                hi = lo + nb * bs
                with trace_span("table_transfer", kind="device_transfer",
                                epoch=epoch):
                    item = _supervised_transfer_table(
                        converter,
                        ([f[lo:hi] for f in features], label[lo:hi]),
                        nb, bs, queue_depth)
                if not put(("table", epoch, (item, nb))):
                    return False
                done += nb
            for b in range(done, full_batches):
                lo = offset + b * bs
                with trace_span("batch_transfer", kind="device_transfer",
                                epoch=epoch):
                    batch = converter.transfer(
                        ([f[lo:lo + bs] for f in features],
                         label[lo:lo + bs]))
                if not put(("batch", epoch, batch)):
                    return False
            offset += full_batches * bs
        if offset < n:
            carry.append(([f[offset:] for f in features], label[offset:]))
            carry_rows += n - offset
    if carry_rows and not dataset.drop_last:
        if not put(("batch", epoch, flush_carry())):
            return False
    return True


def _release_producer(stop: threading.Event, out: "_queue.Queue") -> None:
    """Finalizer for a dropped JaxShufflingDataset: stop the producer and
    drop its buffered device batches."""
    stop.set()
    try:
        while True:
            out.get_nowait()
    except _queue.Empty:
        pass


class JaxShufflingDataset:
    """Shuffled batches as device-resident, optionally mesh-sharded
    ``jax.Array``s, with prefetch double-buffering.

    Constructor mirrors ``TorchShufflingDataset``
    (reference: torch_dataset.py:43-78) plus the TPU knobs:

    Args:
        mesh: optional ``jax.sharding.Mesh``; batches are laid out with the
            leading (batch) axis sharded over ``data_axis``.
        data_axis: mesh axis name for the batch dimension.
        prefetch_size: how many converted+transferred batches to keep ahead
            of the consumer (2 = classic double buffering).
        drop_last: fixed shapes are strongly recommended on TPU (a ragged
            tail batch triggers one extra XLA compile), so this defaults to
            True — unlike the reference.
        stack_features: yield features as ONE ``(batch, num_features)``
            device array instead of a list of ``(batch, 1)`` arrays.
            Requires identical feature dtypes and scalar/1-wide shapes —
            this is the layout DLRM-style models consume anyway. With
            ``device_put`` the stack happens ON DEVICE (columns are
            transferred zero-copy and concatenated by one fused XLA op), so
            the host never pays the strided ``np.concatenate`` pass — on
            TPU the concat rides HBM bandwidth instead of host memory.
        cast_at_map: cast spec'd columns to their final dtypes at the map
            stage (before shuffling) instead of per batch — see
            :func:`make_cast_transform`. Only effective when this dataset
            launches the shuffle (rank 0 without an external
            ``batch_queue``).
        reduce_transform: optional ``pa.Table -> pa.Table`` hook run by each
            reduce task on its shuffled output — e.g. image decode inside
            the reducers (``workloads.imagenet.decode_transform``). Only
            effective when this dataset launches the shuffle.
        persistent_prefetch: keep ONE producer thread alive across epochs
            (default). The producer rolls straight from epoch N's last
            batch into epoch N+1's convert+transfer, so the epoch boundary
            costs the consumer ~zero wait instead of a full
            convert+transfer pipeline refill. Requires epochs to be
            iterated sequentially from ``start_epoch`` (the universal
            pattern; ``set_epoch`` raises otherwise). Set False to restore
            a fresh producer per epoch (any epoch order, at the price of
            the boundary bubble).
        file_cache: forwarded to the shuffle driver (rank-0 launch path
            only): a ``shuffle.FileTableCache``, ``"auto"`` (budgeted from
            host RAM), or ``None`` to disable cross-epoch caching of
            decoded files.
        max_inflight_bytes: byte budget for transient shuffle memory
            (in-flight map + reducer tables); see ``shuffle.shuffle``.
        spill_dir: with ``max_inflight_bytes``, spill over-budget reducer
            outputs to Arrow IPC files here instead of throttling
            (plasma's spill role; see spill.py).
        device_rebatch: move whole reducer outputs to the device in bulk
            (one ``device_put`` per multi-batch chunk, a few MB per column)
            and carve batches ON DEVICE with one jitted program, instead of
            one host convert+transfer per batch. Cuts host->device
            dispatches per epoch by ~an order of magnitude — on a
            high-latency device link this is the dominant producer cost —
            and the per-batch carve rides HBM bandwidth. Batch contents are
            identical to the host path (grid-unaligned rows at reducer
            boundaries are stitched host-side). With a mesh, chunks are
            reshaped host-side (zero-copy) to ``(n_batches, batch, ...)``
            and transferred with the batch axis sharded over ``data_axis``,
            so the carve is a free axis-0 index with no resharding —
            requires ``batch_size`` divisible by the data-axis device
            count. ``"auto"`` (default) enables it when
            ``persistent_prefetch`` and ``device_put`` are on (and the
            divisibility holds) on non-CPU backends.
        max_device_input_bytes: combined HBM budget for the input
            pipeline in device_rebatch mode. The pipeline holds at most
            ~``(prefetch_size + 2)`` chunks on device, so the per-chunk
            cap is derived as ``max_device_input_bytes /
            (prefetch_size + 2)`` — raising ``prefetch_size`` shrinks
            chunks instead of multiplying HBM residency. Default 1 GiB.
        max_device_table_bytes: explicit per-chunk byte cap for
            device_rebatch (chunks also cap at 8 batches); overrides the
            derivation from ``max_device_input_bytes`` when set.
            Workloads where one batch alone exceeds the cap (fat rows —
            e.g. decoded images) fall back to per-batch transfers.
        runtime_policy: explicit overrides for the runtime
            health/degradation policy (``runtime/policy.py`` keys:
            ``watchdog``, ``bulk_transfer_deadline_s``, ``stall_action``,
            ``device_rebatch``, ...). Defaults resolve through
            ``RSDL_JAX_DATASET_<KEY>`` / ``RSDL_<KEY>`` env vars, so
            e.g. ``RSDL_DEVICE_REBATCH=0`` makes the per-batch path the
            library default for ``device_rebatch="auto"`` constructions.
            The bulk path runs under a progress watchdog: a chunk
            ``device_put``/carve that misses ``bulk_transfer_deadline_s``
            files a structured stall report into
            ``stats.watchdog_stats()``, halves the in-flight bulk byte
            cap, and — under the default ``stall_action="degrade"`` —
            permanently drops this dataset to per-batch transfers with a
            logged reason instead of hanging ("warn" records only,
            "raise" fails the producer).
    """

    def __init__(self,
                 filenames: Sequence[str],
                 num_epochs: Optional[int],
                 num_trainers: int,
                 batch_size: int,
                 rank: int,
                 feature_columns: List[Any] = None,
                 feature_shapes: Optional[List[Any]] = None,
                 feature_types: Optional[List[Any]] = None,
                 label_column: Any = None,
                 label_shape: Optional[int] = None,
                 label_type: Optional[Any] = None,
                 drop_last: bool = True,
                 num_reducers: Optional[int] = None,
                 max_concurrent_epochs: int = 2,
                 batch_queue=None,
                 shuffle_result=None,
                 max_batch_queue_size: int = 0,
                 seed: int = 0,
                 num_workers: Optional[int] = None,
                 queue_name: str = "MultiQueue",
                 mesh=None,
                 data_axis: str = "data",
                 prefetch_size: int = 2,
                 device_put: bool = True,
                 start_epoch: int = 0,
                 stack_features: bool = False,
                 cast_at_map: bool = True,
                 reduce_transform=None,
                 persistent_prefetch: bool = True,
                 file_cache="auto",
                 max_inflight_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 device_rebatch="auto",
                 max_device_input_bytes: int = 1 << 30,
                 max_device_table_bytes: Optional[int] = None,
                 runtime_policy: Optional[dict] = None):
        (self._feature_columns, self._feature_shapes, self._feature_types,
         self._label_column, self._label_shape, self._label_type) = (
             _normalize_jax_data_spec(feature_columns, feature_shapes,
                                      feature_types, label_column,
                                      label_shape, label_type))
        if stack_features:
            if len(set(self._feature_types)) != 1:
                raise ValueError(
                    "stack_features requires identical feature dtypes, got "
                    f"{self._feature_types}")
            for shape in self._feature_shapes:
                if shape is not None and tuple(shape) != (1,):
                    raise ValueError(
                        "stack_features requires scalar (or (1,)-shaped) "
                        f"feature columns, got shape {shape}")
        self._stack_features = stack_features
        # Resolve/validate device_rebatch BEFORE constructing the underlying
        # dataset: the rank-0 path below launches the named queue and the
        # background shuffle, which must not leak if this config is invalid.
        def _mesh_divisible():
            if mesh is None:
                return True
            n_data = int(np.prod([s for n, s in zip(mesh.axis_names,
                                                    mesh.devices.shape)
                                  if n == data_axis] or [1]))
            return batch_size % max(1, n_data) == 0

        # Runtime health/degradation policy (runtime/policy.py): explicit
        # runtime_policy kwargs > RSDL_JAX_DATASET_* env > RSDL_* env >
        # library defaults. RSDL_DEVICE_REBATCH=0 — the old bench-only
        # mitigation, promoted — makes the per-batch path the library
        # default for every "auto" construction.
        from ray_shuffling_data_loader_tpu.runtime import (policy as
                                                           rt_policy)
        self._runtime_policy = rt_policy.resolve_all(
            "jax_dataset", **(runtime_policy or {}))
        if (device_rebatch == "auto"
                and self._runtime_policy["device_rebatch"] is False):
            device_rebatch = False
        device_rebatch_auto = device_rebatch == "auto"
        if device_rebatch == "auto":
            # Bulk transfers need the persistent producer (the table path
            # lives there), a real device_put (otherwise there is nothing to
            # gain and tests expect host arrays), and — with a mesh — a
            # batch size divisible by the data axis (chunks transfer with
            # the batch axis sharded). On a CPU backend the "transfer" is a
            # host memcpy, so bulk moves only add copies — keep the
            # per-batch path there.
            device_rebatch = (persistent_prefetch and device_put
                              and _mesh_divisible())
            if device_rebatch:
                import jax
                device_rebatch = jax.default_backend() != "cpu"
        elif device_rebatch:
            if not persistent_prefetch or not device_put:
                raise ValueError(
                    "device_rebatch requires persistent_prefetch=True and "
                    "device_put=True")
            if not _mesh_divisible():
                raise ValueError(
                    "device_rebatch with a mesh requires batch_size "
                    "divisible by the data-axis device count (bulk chunks "
                    "transfer with the batch axis sharded)")
        map_transform = None
        if cast_at_map and label_column is not None:
            map_transform = make_cast_transform(
                self._feature_columns, self._feature_types,
                self._label_column, self._label_type)
        self._dataset = ShufflingDataset(
            filenames, num_epochs, num_trainers, batch_size, rank,
            drop_last=drop_last, num_reducers=num_reducers,
            max_concurrent_epochs=max_concurrent_epochs,
            batch_queue=batch_queue, shuffle_result=shuffle_result,
            max_batch_queue_size=max_batch_queue_size, seed=seed,
            num_workers=num_workers, queue_name=queue_name,
            start_epoch=start_epoch, map_transform=map_transform,
            reduce_transform=reduce_transform, file_cache=file_cache,
            max_inflight_bytes=max_inflight_bytes, spill_dir=spill_dir)
        self._mesh = mesh
        self._data_axis = data_axis
        self._prefetch_size = max(1, prefetch_size)
        self._device_put = device_put
        if max_device_table_bytes is None:
            # Keep TOTAL device-resident input bytes at the documented
            # budget regardless of queue depth: the pipeline holds at most
            # ~(prefetch_size + 2) chunks at once (ADVICE r3).
            max_device_table_bytes = max(
                1, max_device_input_bytes // (self._prefetch_size + 2))
        # The watchdog supervises only the bulk path (there is nothing to
        # time out per-batch: each transfer is small and the consumer's
        # queue.get is already interruptible via close()).
        wd = None
        if self._runtime_policy["watchdog"] and bool(device_rebatch):
            from ray_shuffling_data_loader_tpu.runtime import (watchdog as
                                                               rt_watchdog)
            wd = rt_watchdog.get_watchdog()
        self._converter = _BatchConverter(
            self._feature_columns, self._feature_shapes, self._feature_types,
            self._label_column, self._label_shape, self._label_type,
            stack_features, mesh, data_axis, device_put,
            device_rebatch=bool(device_rebatch),
            device_rebatch_auto=device_rebatch_auto,
            max_table_bytes=max_device_table_bytes,
            watchdog=wd,
            bulk_transfer_deadline_s=(
                self._runtime_policy["bulk_transfer_deadline_s"]),
            stall_action=self._runtime_policy["stall_action"])
        # Close the delivery-latency loop at the device boundary: the
        # probe observes delivered->device and birth->device per source
        # table and refreshes this rank's freshness gauge (the
        # freshness_stall detector's series).
        self._converter.latency_probe = rt_latency.LatencyProbe(
            queue=str(self._dataset.rank))
        self._converter.double_buffer = bool(
            self._runtime_policy["device_double_buffer"])
        self.batch_wait_stats = BatchWaitStats()
        # Persistent-prefetch state (one producer thread for ALL epochs).
        self._persistent = persistent_prefetch
        self._lock = threading.Lock()
        self._out: Optional[_queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._pending_skips: dict = {}   # epoch -> skip_batches (pre-start)
        self._scheduled_skips: dict = {}  # epoch -> skip already producer-side
        self._started_epochs: set = set()  # epochs the producer entered
        self._consumer_skip = 0          # device batches to drop client-side
        self._next_epoch = self._dataset.start_epoch  # next to consume
        self._epoch_set = False          # set_epoch called since last iter
        self._closed = False             # close() is terminal
        self._active_gen = None          # live persistent-epoch generator

    def set_epoch(self, epoch: int, skip_batches: int = 0) -> None:
        if not self._persistent:
            self._dataset.set_epoch(epoch, skip_batches=skip_batches)
            return
        if skip_batches < 0:
            raise ValueError(f"skip_batches must be >= 0, got {skip_batches}")
        # Validate BEFORE destroying any in-flight iterator, so an illegal
        # call leaves the current epoch resumable. A suspended (mid-epoch)
        # iterator counts its epoch as consumed once we finalize it below,
        # so the expected argument is one past it.
        import inspect
        gen_state = (inspect.getgeneratorstate(self._active_gen)
                     if self._active_gen is not None else None)
        if gen_state == inspect.GEN_RUNNING:
            raise RuntimeError(
                "set_epoch called while another thread is iterating "
                "this dataset")
        expected = (self._next_epoch + 1
                    if gen_state == inspect.GEN_SUSPENDED
                    else self._next_epoch)
        if epoch != expected:
            raise ValueError(
                f"persistent_prefetch requires sequential epochs: expected "
                f"set_epoch({expected}), got set_epoch({epoch}). "
                "Construct with persistent_prefetch=False for out-of-order "
                "epoch iteration.")
        if self._active_gen is not None:
            # Finalize a previous epoch's iterator NOW (a consumer that
            # broke out mid-epoch and moved on without close()-ing the
            # iterator must not depend on GC timing): closing it runs the
            # generator's finally, which marks that epoch consumed.
            try:
                self._active_gen.close()
            except ValueError:
                # TOCTOU with the state probe above: the other thread
                # resumed the generator in between.
                raise RuntimeError(
                    "set_epoch called while another thread is iterating "
                    "this dataset")
            self._active_gen = None
        assert epoch == self._next_epoch, (epoch, self._next_epoch)
        # The lock guards only the skip MAPS shared with the producer
        # thread; _consumer_skip is consumer-thread-owned (set_epoch and
        # the iterator run on the same thread) and is assigned outside.
        with self._lock:
            if epoch in self._started_epochs:
                # Producer already ran (or is running) this epoch's convert+
                # transfer; drop the first N finished batches client-side —
                # minus whatever a previous set_epoch call for this epoch
                # already had the producer skip at the Arrow level.
                already = self._scheduled_skips.get(epoch, 0)
                consumer_skip = max(0, skip_batches - already)
            else:
                # Cheap path: the producer will skip at the Arrow-slice
                # level, before any conversion or transfer. Keep the two
                # maps in lockstep so a repeated/reduced skip request
                # neither double-drops nor leaves a stale pending skip.
                if skip_batches:
                    self._pending_skips[epoch] = skip_batches
                else:
                    self._pending_skips.pop(epoch, None)
                self._scheduled_skips[epoch] = skip_batches
                consumer_skip = 0
        self._consumer_skip = consumer_skip
        self._epoch_set = True

    @property
    def batch_size(self) -> int:
        return self._dataset.batch_size

    @property
    def seed(self) -> int:
        return self._dataset.seed

    @property
    def num_epochs(self) -> Optional[int]:
        """Epoch count; None means unbounded streaming consumption."""
        return self._dataset.num_epochs

    def _convert(self, table: pa.Table):
        return self._converter.convert(table)

    def _transfer(self, arrays_label):
        return self._converter.transfer(arrays_label)

    def __iter__(self) -> Iterator[Tuple[List[Any], Any]]:
        """Yield ``(features, label)`` device batches.

        A background thread runs convert+device_put ``prefetch_size`` batches
        ahead; ``jax.device_put`` is async (returns before the copy lands),
        so the host->device DMA for batch N+1 overlaps the consumer's
        compute on batch N. With ``persistent_prefetch`` (default) that
        thread lives for ALL epochs: when epoch N's tables run out it rolls
        straight into epoch N+1, so the consumer's first batch of the new
        epoch is typically already on device.
        """
        if self._device_put:
            # Force backend init on the calling thread: some PJRT plugins
            # (e.g. the tunneled TPU client) deadlock if their first
            # initialization happens on a worker thread.
            import jax
            jax.local_devices()
        if self._persistent:
            gen = self._iter_persistent()
            self._active_gen = gen
            return gen
        return self._iter_single_epoch()

    # -- persistent (cross-epoch) producer ---------------------------------

    def _iter_persistent(self) -> Iterator[Tuple[List[Any], Any]]:
        if self._closed:
            raise RuntimeError(
                "JaxShufflingDataset was closed; the persistent producer "
                "cannot restart (it has already consumed the shuffle "
                "queue). Construct a new dataset to iterate again.")
        if not self._epoch_set:
            raise ValueError(
                "You must set the epoch on this dataset via set_epoch() at "
                "the beginning of each epoch, before iterating over this "
                "dataset (e.g. via enumerate(ds)).")
        self._epoch_set = False
        epoch = self._next_epoch
        if self._thread is None:
            import weakref
            self._out = _queue.Queue(maxsize=self._prefetch_size)
            # The producer references the underlying ShufflingDataset and
            # converter but NOT self — so a wrapper dropped without close()
            # is garbage-collected and this finalizer releases the thread
            # (and its device-resident buffered batches).
            self._thread = threading.Thread(
                target=_persistent_producer,
                args=(self._dataset, self._converter, self._out, self._stop,
                      self._lock, self._pending_skips, self._started_epochs),
                daemon=True, name="rsdl-jax-prefetch")
            weakref.finalize(self, _release_producer, self._stop, self._out)
            self._thread.start()
        resume_t = None  # when the consumer last resumed after a yield
        try:
            while True:
                wait_start = timeit.default_timer()
                if resume_t is not None:
                    # The gap between the previous batch's yield and this
                    # get() is the consumer's own work — the train_step
                    # stage of the bottleneck decomposition.
                    rt_telemetry.record("train_step", epoch=epoch,
                                        dur_s=wait_start - resume_t,
                                        t=wait_start)
                item = self._out.get()
                wait_s = timeit.default_timer() - wait_start
                self.batch_wait_stats.record(wait_s)
                rt_telemetry.record("batch_wait", epoch=epoch, dur_s=wait_s)
                if isinstance(item, BaseException):
                    raise item
                kind, item_epoch, payload = item
                if item_epoch < epoch:
                    # Remnants of an epoch abandoned mid-iteration; batches
                    # were converted in vain but correctness needs them gone.
                    continue
                assert item_epoch == epoch, (item_epoch, epoch)
                if kind == "end":
                    rt_telemetry.epoch_complete(epoch, source="jax")
                    break
                if kind == "table":
                    # Bulk device table: carve batches on-device. Later
                    # batches of the same item record zero wait — accurate:
                    # they are already in HBM. The FIRST carve of each item
                    # is watchdog-supervised (it dispatches the jitted
                    # slicer — the carve half of the bulk path's liveness
                    # contract); a deadline miss files a stall and, under
                    # "degrade", stops the producer sending further bulk
                    # items.
                    dev_table, n_batches = payload
                    start = 0
                    if self._consumer_skip:
                        start = min(self._consumer_skip, n_batches)
                        self._consumer_skip -= start
                    bs = self._dataset.batch_size
                    wd = self._converter.watchdog
                    for b in range(start, n_batches):
                        if b > start:
                            now = timeit.default_timer()
                            self.batch_wait_stats.record(0.0)
                            rt_telemetry.record("batch_wait", epoch=epoch,
                                                dur_s=0.0, t=now)
                            if resume_t is not None:
                                rt_telemetry.record(
                                    "train_step", epoch=epoch,
                                    dur_s=now - resume_t, t=now)
                            batch = self._converter.slice_batch(
                                dev_table, b, bs)
                        elif wd is not None:
                            with wd.watch(
                                    "jax_dataset.bulk_carve",
                                    deadline_s=(self._converter
                                                .bulk_transfer_deadline_s),
                                    on_stall=self._converter._on_bulk_stall):
                                batch = self._converter.slice_batch(
                                    dev_table, b, bs)
                        else:
                            batch = self._converter.slice_batch(
                                dev_table, b, bs)
                        yield batch
                        resume_t = timeit.default_timer()
                    continue
                if self._consumer_skip:
                    self._consumer_skip -= 1
                    continue
                yield payload
                resume_t = timeit.default_timer()
        finally:
            # Runs on normal completion AND on mid-epoch abandonment
            # (GeneratorExit from iterator.close() / going out of scope):
            # an abandoned epoch counts as consumed — the producer has
            # already pulled its batches off the shuffle queue — so the
            # legal next call is set_epoch(epoch + 1). Leftover in-flight
            # batches of this epoch are dropped by the item_epoch < epoch
            # guard above. A leftover skip must not eat the next epoch.
            self._consumer_skip = 0
            self._next_epoch = epoch + 1
            # Break the wrapper->generator->frame->wrapper reference
            # cycle: with it intact, a finished epoch's last device batch
            # (held by this frame) is only released at a full cycle
            # collection — the delayed-free class the release-event budget
            # wait (runtime/release.py) exists to eliminate.
            self._active_gen = None

    def close(self) -> None:
        """Stop the persistent producer and drop buffered device batches.

        Only needed when abandoning the dataset before its last epoch was
        fully iterated; the producer exits on its own after the final
        epoch. Idempotent, and terminal: iterating after close() raises
        (the producer has already drained the underlying shuffle queue, so
        a restarted one would replay or block forever).
        """
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            # Join BEFORE draining: the producer notices the stop event
            # within one bounded-put poll (0.1s) and exits, so nothing
            # refills the queue between the drain and the poison below.
            self._thread.join(timeout=5)
            self._thread = None
        if self._out is not None:
            try:
                while True:
                    self._out.get_nowait()
            except _queue.Empty:
                pass
            try:
                # A live consumer — blocked in the iterator's get() or
                # about to call next() — gets this instead of hanging on
                # the drained queue or ending its epoch loop with silent
                # truncation. (Deliberately no generator.close() here: its
                # effect would depend on whether the consumer happened to
                # be suspended or blocked at this instant; the poison item
                # raises consistently in both timings.)
                self._out.put_nowait(
                    RuntimeError("JaxShufflingDataset was closed while the "
                                 "epoch was still being iterated"))
            except _queue.Full:
                pass  # unreachable after the join+drain above
        self._active_gen = None

    # -- per-epoch producer (persistent_prefetch=False) --------------------

    def _iter_single_epoch(self) -> Iterator[Tuple[List[Any], Any]]:
        out: _queue.Queue = _queue.Queue(maxsize=self._prefetch_size)
        SENTINEL = object()
        stop = threading.Event()

        def _put(item) -> bool:
            """Bounded put that gives up when the consumer is gone."""
            while not stop.is_set():
                try:
                    out.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def producer():
            epoch = getattr(self._dataset, "_epoch", None)
            try:
                if self._converter.double_buffer:
                    # Double-buffered staging (same shape as the
                    # persistent producer's): transfer N overlaps
                    # convert N+1, delivered FIFO.
                    if not _produce_epoch_batches_staged(
                            self._dataset, self._converter, epoch,
                            lambda item: _put(item[2])):
                        return
                else:
                    for table in self._dataset:
                        with trace_span("batch_convert", kind="convert",
                                        epoch=epoch):
                            arrays = self._convert(table)
                        with trace_span("batch_transfer",
                                        kind="device_transfer",
                                        epoch=epoch):
                            batch = self._transfer(arrays)
                        if not _put(batch):
                            return
                _put(SENTINEL)
            except BaseException as e:  # noqa: BLE001 - forwarded to consumer
                _put(e)

        thread = threading.Thread(target=producer, daemon=True,
                                  name="rsdl-jax-prefetch")
        thread.start()
        epoch = getattr(self._dataset, "_epoch", None)
        resume_t = None
        try:
            while True:
                wait_start = timeit.default_timer()
                if resume_t is not None:
                    rt_telemetry.record("train_step", epoch=epoch,
                                        dur_s=wait_start - resume_t,
                                        t=wait_start)
                item = out.get()
                wait_s = timeit.default_timer() - wait_start
                self.batch_wait_stats.record(wait_s)
                rt_telemetry.record("batch_wait", epoch=epoch, dur_s=wait_s)
                if item is SENTINEL:
                    if epoch is not None:
                        rt_telemetry.epoch_complete(epoch, source="jax")
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
                resume_t = timeit.default_timer()
        finally:
            # Consumer done or abandoned mid-epoch: release the producer
            # (it would otherwise block forever on the bounded queue,
            # pinning device-resident batches) and drop buffered batches.
            stop.set()
            try:
                while True:
                    out.get_nowait()
            except _queue.Empty:
                pass
            thread.join(timeout=5)
