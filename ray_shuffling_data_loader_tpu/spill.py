"""Disk spill tier for reducer outputs (plasma's spill role, made explicit).

Ray's plasma store spills objects to disk under memory pressure; the
reference's operators size the store and disable that spilling outright
(reference: benchmarks/cluster.yaml:175, examples/horovod/cluster.yaml:98)
because an unpredictable spill mid-trial wrecks throughput. Here the
policy is explicit and local: when a shuffle runs with ``spill_dir`` set
and its transient buffer-ledger bytes exceed ``max_inflight_bytes``,
freshly-produced reducer outputs are written to Arrow IPC files and
replaced by lazy :class:`SpilledTable` handles; the consumer loads each
handle once — memory-mapped, so reload is a page-in, not a decode — right
before re-batching. Without ``spill_dir`` the budget only throttles epoch
launches (shuffle.py), which is the reference's "no spill" operating
point.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import time
import timeit
import weakref
from typing import Callable, Optional

import pyarrow as pa

from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)


class SpillCorruption(RuntimeError):
    """A spill file's bytes no longer match the CRC recorded at write
    time (bad disk, torn write, bit rot)."""


def _file_crc(path: str) -> int:
    """CRC-32 (zlib-compatible) of a file's bytes, streamed (the file was
    just written, so this reads from page cache). Runs on the native
    hardware/slice-by-8 kernel when available — ``native.crc32`` chains
    running values exactly like ``zlib.crc32``."""
    from ray_shuffling_data_loader_tpu import native
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = native.crc32(chunk, crc)
    return crc & 0xFFFFFFFF

# Process-wide spill totals across every SpillManager, for assertions
# and monitoring that must not depend on log level or manager lifetime
# (a manager may already be finalized when its consumer checks).
_totals_lock = threading.Lock()
_total_spill_count = 0
_total_spilled_bytes = 0


def process_spill_totals() -> "tuple[int, int]":
    """``(spill_count, spilled_bytes)`` accumulated by every
    :class:`SpillManager` in this process since import. Monotonic;
    snapshot before/after a run to measure that run's spill activity."""
    with _totals_lock:
        return _total_spill_count, _total_spilled_bytes


class SpilledTable:
    """Lazy handle to one reducer output on disk.

    ``load()`` verifies the file's CRC against the one recorded at write
    time (end-to-end frame integrity: a spill that sat on a dying scratch
    volume must not silently feed damaged rows to training), memory-maps
    the IPC file, unlinks it (the mapping keeps the pages alive on
    POSIX), accounts the bytes to the buffer ledger like any in-flight
    table, and caches the result so repeated loads are safe.

    A corrupt or unreadable spill is **recomputed from lineage** when the
    writer supplied a ``recompute`` closure (the single-host reduce path
    does — a reducer output is a pure function of ``(seed, epoch,
    reducer)`` and the input files): the bad file is quarantined into a
    structured ``QuarantinedFile`` report and the recompute, bounded by
    the spill RetryPolicy, yields a bit-identical table. Without lineage
    (the cross-host path, whose inputs crossed the wire) the failure
    stays loud — there is no second copy.

    The handle holds its :class:`SpillManager` alive: the scratch
    directory is removed by the manager's finalizer only after the LAST
    outstanding handle is gone, so a slow consumer still draining the
    batch queue after the shuffle driver returned can always load.
    """

    __slots__ = ("_path", "num_rows", "_table", "_lock", "_manager",
                 "_crc", "_recompute", "_epoch", "_task", "__weakref__")

    def __init__(self, path: str, num_rows: int, manager: "SpillManager",
                 crc: Optional[int] = None,
                 recompute: Optional[Callable[[], pa.Table]] = None,
                 epoch: Optional[int] = None, task: Optional[int] = None):
        self._path = path
        self.num_rows = num_rows
        self._table: Optional[pa.Table] = None
        self._lock = threading.Lock()
        self._manager = manager
        self._crc = crc
        self._recompute = recompute
        self._epoch = epoch
        self._task = task
        # A handle dropped without ever being loaded (abandoned run)
        # deletes its file; idempotent with load()'s unlink.
        weakref.finalize(self, _unlink_quiet, path)

    def _read_back(self) -> pa.Table:
        # Fault site: a spilled output that cannot be read back is lost
        # data — recovered from lineage below when possible, loud
        # otherwise.
        rt_faults.inject("spill_read", epoch=self._epoch, task=self._task)
        if self._crc is not None and _file_crc(self._path) != self._crc:
            raise SpillCorruption(
                f"spill file {self._path} failed its CRC check "
                f"(bytes changed since the write)")
        with pa.memory_map(self._path) as source:
            return pa.ipc.open_file(source).read_all()

    def load(self) -> pa.Table:
        from ray_shuffling_data_loader_tpu import stats as stats_mod
        from ray_shuffling_data_loader_tpu.runtime import retry as rt_retry
        from ray_shuffling_data_loader_tpu.utils.tracing import trace_span
        with self._lock:
            if self._table is None:
                with trace_span("spill_load", kind="spill_read",
                                epoch=self._epoch, task=self._task):
                    try:
                        self._table = self._read_back()
                    except (OSError, pa.ArrowInvalid, SpillCorruption,
                            rt_faults.InjectedFault) as e:
                        if self._recompute is None:
                            raise
                        # Quarantine + lineage recompute: the corrupt
                        # file is reported (never silent), then the
                        # reducer output is rebuilt from its pure
                        # (seed, epoch, reducer) lineage — bit-identical
                        # by the determinism contract.
                        report = rt_faults.QuarantinedFile(
                            filename=self._path,
                            epoch=self._epoch if self._epoch is not None
                            else -1,
                            file_index=self._task if self._task is not None
                            else -1,
                            error=f"{type(e).__name__}: {e}")
                        stats_mod.fault_stats().record_quarantine(report)
                        logger.error(
                            "spill read-back failed (%s); quarantined %s "
                            "and recomputing reducer output from lineage",
                            e, self._path)
                        start = timeit.default_timer()
                        retry = rt_retry.RetryPolicy.for_component("spill")
                        self._table = retry.call(
                            self._recompute,
                            describe=f"spill recompute e{self._epoch} "
                                     f"r{self._task}")
                        assert self._table.num_rows == self.num_rows, (
                            self._table.num_rows, self.num_rows)
                        stats_mod.fault_stats().record_recompute(
                            "spill", timeit.default_timer() - start)
                _unlink_quiet(self._path)
                from ray_shuffling_data_loader_tpu import native
                native.account_table(self._table)
            return self._table


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


class SpillManager:
    """Per-shuffle spill policy + scratch directory.

    ``over_budget`` is the shuffle driver's own transient-bytes predicate,
    so spill and epoch-launch throttling read the same meter. The scratch
    directory's lifetime is reference-managed: every handle pins the
    manager, and the manager's finalizer removes the directory — so
    teardown happens after the last consumer, not when the driver exits.
    """

    def __init__(self, spill_dir: str,
                 over_budget: Optional[Callable[[], bool]]):
        os.makedirs(spill_dir, exist_ok=True)
        self._dir = tempfile.mkdtemp(prefix="rsdl-spill-", dir=spill_dir)
        self._over_budget = over_budget
        self._seq = 0
        self._lock = threading.Lock()
        self.spill_count = 0
        self.spilled_bytes = 0
        weakref.finalize(self, shutil.rmtree, self._dir, True)

    def maybe_spill(self, table: pa.Table, recompute=None,
                    epoch: Optional[int] = None,
                    task: Optional[int] = None):
        """Spill ``table`` if the pipeline is over its transient budget;
        returns the table itself or a :class:`SpilledTable` handle.

        ``recompute`` (a zero-arg closure rebuilding this exact table
        from its deterministic lineage) arms the handle's
        corrupt-read-back recovery; ``epoch``/``task`` key the handle's
        fault site and quarantine report."""
        # Snapshot: report() may detach the predicate concurrently (driver
        # finishing while a caller-owned pool still runs reduce tasks).
        over_budget = self._over_budget
        if table.num_rows == 0 or over_budget is None or not over_budget():
            return table
        from ray_shuffling_data_loader_tpu.utils.tracing import trace_span
        with self._lock:
            path = os.path.join(self._dir, f"reduce_{self._seq}.arrow")
            self._seq += 1
        try:
            # Fault site INSIDE the telemetry span: an injected write
            # failure still records a spill_write event with this task
            # key, so chaos and telemetry stay joinable even when the
            # write degrades to in-memory.
            with trace_span("spill_write", kind="spill_write",
                            task=self._seq - 1):
                rt_faults.inject("spill_write", task=self._seq - 1)
                with pa.OSFile(path, "wb") as sink:
                    with pa.ipc.new_file(sink, table.schema) as writer:
                        writer.write_table(table)
        except (OSError, rt_faults.InjectedFault) as e:
            # Graceful degradation: a failed spill write (disk full, dying
            # scratch volume, injected fault) keeps the in-memory table —
            # the pipeline runs hotter than its budget but loses nothing.
            logger.warning(
                "spill write failed (%s); keeping reducer output in "
                "memory (over-budget until consumers release)", e)
            _unlink_quiet(path)
            return table
        size = os.path.getsize(path)
        # CRC recorded at write time, verified at load: the read-back is
        # the only copy, so integrity must be end-to-end, not assumed.
        try:
            crc = _file_crc(path)
        except OSError as e:
            logger.warning("spill CRC read failed (%s); keeping reducer "
                           "output in memory", e)
            _unlink_quiet(path)
            return table
        with self._lock:
            self.spill_count += 1
            self.spilled_bytes += size
        global _total_spill_count, _total_spilled_bytes
        with _totals_lock:
            _total_spill_count += 1
            _total_spilled_bytes += size
        from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
        rt_metrics.counter("rsdl_spills_total",
                           "reducer outputs spilled to disk").inc()
        rt_metrics.counter("rsdl_spilled_bytes_total",
                           "bytes of reducer output spilled").inc(size)
        return SpilledTable(path, table.num_rows, self, crc=crc,
                            recompute=recompute, epoch=epoch, task=task)

    def report(self) -> None:
        """Log spill totals and detach the budget predicate.

        Called when the shuffle driver finishes. Dropping the predicate
        matters: it closes over the driver's FileTableCache, and every
        outstanding :class:`SpilledTable` pins this manager for scratch-dir
        lifetime — without the detach, one undrained spilled batch would
        keep the whole decoded-file cache in memory. The scratch dir
        itself is removed by the finalizer once the last handle is gone.
        """
        if self.spill_count:
            logger.info("spilled %d reducer outputs (%.1f MB) to disk",
                        self.spill_count, self.spilled_bytes / 1e6)
        self._over_budget = None


def unwrap(table_or_handle):
    """Materialize a possibly-spilled table (consumer-side hook)."""
    if isinstance(table_or_handle, SpilledTable):
        return table_or_handle.load()
    return table_or_handle


def make_budget_state(file_cache, max_inflight_bytes: Optional[int],
                      spill_dir: Optional[str]):
    """``(over_budget, spill_manager_or_None)`` for a shuffle driver.

    Shared by the single-host and distributed drivers so the
    transient-bytes definition stays identical: ledger growth since THIS
    call, minus the given file cache's growth (duck-typed via
    ``bytes_cached``; the ledger is process-global, so other pipelines'
    static usage cancels out and only their concurrent growth is
    attributed here). How to react to the predicate — drain-and-poll vs
    launch-and-spill — stays in the callers.
    """
    from ray_shuffling_data_loader_tpu import native

    def cache_bytes() -> int:
        return getattr(file_cache, "bytes_cached", 0)

    _start_ledger = native.buffer_ledger()
    ledger_at_start = (_start_ledger.bytes_in_use()
                       + _start_ledger.freelist_bytes())
    cache_at_start = cache_bytes()
    # The free-list trim releases warm buffers process-wide (other
    # pipelines' included) and re-paying mmap + first-touch faults on every
    # recv defeats recycling, so under SUSTAINED budget pressure trim at
    # most once per cooldown window instead of on every over-budget probe.
    # (A trim that does fire notifies runtime.release, so other budget
    # waiters re-check immediately.)
    from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
    trim_cooldown_s = rt_policy.resolve("spill", "trim_cooldown_s")
    last_trim = [float("-inf")]

    def over_budget() -> bool:
        if max_inflight_bytes is None:
            return False
        ledger = native.buffer_ledger()

        def transient() -> int:
            # Freelist bytes are real RSS the pool is holding for reuse, so
            # the budget must see them — but they are reclaimable, so give
            # them back before declaring the pipeline over budget.
            return (ledger.bytes_in_use() + ledger.freelist_bytes()
                    - ledger_at_start - (cache_bytes() - cache_at_start))

        if transient() <= max_inflight_bytes:
            return False
        now = time.monotonic()
        if (ledger.freelist_bytes()
                and now - last_trim[0] >= trim_cooldown_s):
            last_trim[0] = now
            ledger.trim_freelist()
            return transient() > max_inflight_bytes
        # Inside the cooldown the freelist is still reclaimable — don't
        # declare over-budget (and spill/stall) on bytes a trim would
        # release; judge only the non-reclaimable share.
        return (transient() - ledger.freelist_bytes()
                > max_inflight_bytes)

    manager = None
    if spill_dir is not None and max_inflight_bytes is not None:
        manager = SpillManager(spill_dir, over_budget)
    elif spill_dir is not None:
        logger.warning(
            "spill_dir=%r ignored: spilling triggers on the transient-byte "
            "budget, and max_inflight_bytes is not set", spill_dir)
    return over_budget, manager
