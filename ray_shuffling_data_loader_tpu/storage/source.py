"""The ``StorageSource`` contract: where dataset bytes actually live.

The reference reads its training corpus from object storage via
``smart_open`` (reference: shuffle.py:7,208) — ingest latency there is
remote-GET latency, not disk. This repo historically only read local
files; this module makes the byte origin an explicit, swappable
backend so the same pipeline runs against local disk, a plain HTTP
server, or a hermetic *simulated* object store with the latency and
failure shape of the real thing:

``LocalSource``
    The historical behavior: :func:`utils.fileio.read_parquet` (local
    mmap fast path, pyarrow/fsspec filesystems for URIs).
``HTTPRangeSource``
    Stdlib ``http.client`` range reads against any static file server
    — no SDK dependency. Transient failures retry through the PR 3
    :class:`runtime.retry.RetryPolicy` (component ``storage``).
``SimulatedObjectStore``
    Local files served through a policy-tunable remote-latency model:
    first-byte latency, sustained bandwidth, multiplicative jitter and
    a transient error rate, every draw a pure function of
    ``(seed, path, attempt)`` — a fixed seed reproduces the identical
    stall/error sequence on any host, which is what lets the 1-CPU
    bench and the tests exercise cold remote ingest hermetically.

Every fetch funnels through the module-level :func:`read_table` /
:func:`open_parquet` in ``storage/__init__.py``, which is also where
the ``storage_read`` / ``storage_stall`` chaos sites fire — the
injection sits OUTSIDE the in-place IO retry on purpose, so an
injected fault surfaces to the lineage-recovery machinery under test
instead of being absorbed as weather (the ``map_read`` precedent).
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
from ray_shuffling_data_loader_tpu.runtime import retry as rt_retry
from ray_shuffling_data_loader_tpu.utils import fileio
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)


class StorageSource:
    """Where dataset bytes come from. Implementations are thread-safe
    (map tasks fetch concurrently) and deterministic: ``read_table``
    of the same path returns bit-identical tables on every call —
    the property that makes cache fall-through (a corrupt disk-tier
    entry refetched from remote) invisible to the delivered stream.
    """

    #: Tier label used in logs/metrics ("local", "http", "sim").
    name: str = "source"

    def read_table(self, path: str) -> pa.Table:
        """Fetch and decode one Parquet object."""
        raise NotImplementedError

    def open_parquet(self, path: str) -> pq.ParquetFile:
        """A :class:`pq.ParquetFile` over the object, for streaming
        record-batch readers (the fused map pipeline)."""
        raise NotImplementedError

    def read_bytes(self, path: str, offset: int = 0,
                   length: Optional[int] = None) -> bytes:
        """Raw byte range of the object (length None = to EOF)."""
        raise NotImplementedError

    def size(self, path: str) -> int:
        """Object size in bytes, 0 if it does not exist."""
        raise NotImplementedError


class LocalSource(StorageSource):
    """Direct filesystem (and pyarrow/fsspec URI) reads — the
    historical read path, byte-for-byte."""

    name = "local"

    def read_table(self, path: str) -> pa.Table:
        return fileio.read_parquet(path)

    def open_parquet(self, path: str) -> pq.ParquetFile:
        fs, inner = fileio.parse_uri(path)
        if fs is None:
            return pq.ParquetFile(inner)
        return pq.ParquetFile(fs.open_input_file(inner))

    def read_bytes(self, path: str, offset: int = 0,
                   length: Optional[int] = None) -> bytes:
        fs, inner = fileio.parse_uri(path)
        if fs is None:
            with open(inner, "rb") as f:
                f.seek(offset)
                return f.read() if length is None else f.read(length)
        with fs.open_input_file(inner) as f:
            f.seek(offset)
            return f.read() if length is None else f.read(length)

    def size(self, path: str) -> int:
        return fileio.file_size(path)


class HTTPRangeSource(StorageSource):
    """Range reads from any HTTP(S) file server via stdlib
    ``http.client`` — the minimal object-store protocol (GET +
    ``Range:``), no SDK. One pooled connection per thread; transient
    socket/5xx failures retry through the ``storage`` RetryPolicy.

    ``base_url`` is the prefix objects are resolved against, so the
    pipeline's filenames stay relative (``shard_0.parquet``) and the
    same run script points at local disk or a server by swapping the
    source.
    """

    name = "http"

    def __init__(self, base_url: str,
                 retry: Optional[rt_retry.RetryPolicy] = None):
        import urllib.parse
        parsed = urllib.parse.urlsplit(base_url)
        if parsed.scheme not in ("http", "https"):
            raise ValueError(f"HTTPRangeSource wants http(s), "
                             f"got {base_url!r}")
        self._scheme = parsed.scheme
        self._netloc = parsed.netloc
        self._prefix = parsed.path.rstrip("/")
        self._retry = retry or rt_retry.RetryPolicy.for_component(
            "storage", retryable=rt_retry.transient_retryable)
        self._local = threading.local()
        self._remote_bytes = rt_metrics.counter(
            "rsdl_storage_remote_bytes_read_total",
            "bytes fetched from the remote storage tier")

    def _conn(self):
        import http.client
        conn = getattr(self._local, "conn", None)
        if conn is None:
            cls = (http.client.HTTPSConnection
                   if self._scheme == "https"
                   else http.client.HTTPConnection)
            conn = cls(self._netloc, timeout=60)
            self._local.conn = conn
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            finally:
                self._local.conn = None

    def _url_path(self, path: str) -> str:
        return f"{self._prefix}/{path.lstrip('/')}"

    def _request(self, method: str, path: str,
                 headers: Optional[Dict[str, str]] = None):
        conn = self._conn()
        try:
            conn.request(method, self._url_path(path),
                         headers=headers or {})
            resp = conn.getresponse()
        except (OSError, ConnectionError) as e:
            self._drop_conn()  # stale keep-alive: next attempt redials
            raise OSError(f"http {method} {path}: {e}") from e
        if resp.status >= 500:
            resp.read()
            raise OSError(f"http {method} {path}: server error "
                          f"{resp.status}")
        if resp.status >= 400:
            resp.read()
            raise FileNotFoundError(
                f"http {method} {path}: {resp.status}")
        return resp

    def _fetch(self, path: str, offset: int,
               length: Optional[int]) -> bytes:
        headers = {}
        if offset or length is not None:
            end = "" if length is None else str(offset + length - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        resp = self._request("GET", path, headers)
        data = resp.read()
        self._remote_bytes.inc(len(data))
        return data

    def read_bytes(self, path: str, offset: int = 0,
                   length: Optional[int] = None) -> bytes:
        return self._retry.call(self._fetch, path, offset, length,
                                describe=f"http range {path}")

    def read_table(self, path: str) -> pa.Table:
        data = self.read_bytes(path)
        return pq.read_table(pa.BufferReader(data))

    def open_parquet(self, path: str) -> pq.ParquetFile:
        # Whole-object fetch: the streaming reader then iterates local
        # buffers. Per-column-chunk range reads would save bytes on
        # projected reads, but the map stage always reads every column.
        data = self.read_bytes(path)
        return pq.ParquetFile(pa.BufferReader(data))

    def size(self, path: str) -> int:
        def head() -> int:
            resp = self._request("HEAD", path)
            resp.read()
            return int(resp.headers.get("Content-Length", 0))
        try:
            return self._retry.call(head, describe=f"http head {path}")
        except FileNotFoundError:
            return 0


class SimulatedObjectStore(StorageSource):
    """Local files behind a deterministic remote-latency model.

    Every fetch pays a first-byte latency plus ``size / bandwidth``
    transfer time, both scaled by a seeded multiplicative jitter, and
    may raise a transient ``OSError`` at the configured error rate
    (absorbed by the storage RetryPolicy exactly like a real remote
    blip). All draws are pure functions of ``(seed, path, attempt)``
    via sha256 — no RNG state, so a fixed seed reproduces the byte-
    identical timing/error sequence on any host, which is what makes
    the bench's remote leg and the chaos soak comparable across runs.

    Knobs resolve through :mod:`runtime.policy`
    (``RSDL_STORAGE_SIM_FIRST_BYTE_MS`` / ``_MB_PER_S`` /
    ``_JITTER_PCT`` / ``_ERROR_RATE`` / ``_SEED``); constructor
    kwargs override.
    """

    name = "sim"

    def __init__(self, inner: Optional[StorageSource] = None,
                 first_byte_ms: Optional[float] = None,
                 mb_per_s: Optional[float] = None,
                 jitter_pct: Optional[float] = None,
                 error_rate: Optional[float] = None,
                 seed: Optional[int] = None,
                 sleep=time.sleep):
        def res(key, override):
            return rt_policy.resolve("storage", key, override=override)
        self._inner = inner or LocalSource()
        self.first_byte_ms = res("storage_sim_first_byte_ms",
                                 first_byte_ms)
        self.mb_per_s = res("storage_sim_mb_per_s", mb_per_s)
        self.jitter_pct = res("storage_sim_jitter_pct", jitter_pct)
        self.error_rate = res("storage_sim_error_rate", error_rate)
        self.seed = res("storage_sim_seed", seed)
        self._sleep = sleep
        self._lock = threading.Lock()
        self._attempts: Dict[str, int] = {}
        self.bytes_read = 0
        self._remote_bytes = rt_metrics.counter(
            "rsdl_storage_remote_bytes_read_total",
            "bytes fetched from the remote storage tier")

    def _draw(self, path: str, attempt: int, salt: str) -> float:
        """Uniform [0, 1) from a stable hash — the faults.py idiom."""
        digest = hashlib.sha256(
            f"{self.seed}:{salt}:{path}:{attempt}".encode()).digest()
        return int.from_bytes(digest[:8], "big") / float(1 << 64)

    def _next_attempt(self, path: str) -> int:
        with self._lock:
            attempt = self._attempts.get(path, 0)
            self._attempts[path] = attempt + 1
            return attempt

    def _simulate(self, path: str, nbytes: int) -> None:
        attempt = self._next_attempt(path)
        if (self.error_rate > 0
                and self._draw(path, attempt, "err") < self.error_rate):
            raise OSError(
                f"simulated object-store error for {path!r} "
                f"(attempt {attempt}, rate {self.error_rate:g})")
        jitter = 1.0 + (self.jitter_pct / 100.0) * (
            2.0 * self._draw(path, attempt, "lat") - 1.0)
        delay = self.first_byte_ms / 1000.0
        if self.mb_per_s > 0:
            delay += nbytes / (self.mb_per_s * 1e6)
        delay *= max(0.0, jitter)
        if delay > 0:
            self._sleep(delay)
        with self._lock:
            self.bytes_read += nbytes
        self._remote_bytes.inc(nbytes)

    def read_table(self, path: str) -> pa.Table:
        self._simulate(path, self._inner.size(path))
        return self._inner.read_table(path)

    def open_parquet(self, path: str) -> pq.ParquetFile:
        # The whole object crosses the simulated wire (HTTP source
        # parity), then the streaming reader iterates local buffers.
        data = self.read_bytes(path)
        return pq.ParquetFile(pa.BufferReader(data))

    def read_bytes(self, path: str, offset: int = 0,
                   length: Optional[int] = None) -> bytes:
        data = self._inner.read_bytes(path, offset, length)
        self._simulate(path, len(data))
        return data

    def size(self, path: str) -> int:
        return self._inner.size(path)

    def reset(self) -> None:
        """Forget attempt counters — replays the exact draw sequence
        (an A/B leg re-running the same files at the same seed)."""
        with self._lock:
            self._attempts.clear()
            self.bytes_read = 0
