"""The explicit cache hierarchy: hot RAM over disk over remote.

BENCH_r07 put the cold path at 2.79M rows/s against 5.03M cached — a
96.6% stall refetching and re-decoding bytes the host already saw.
Before this module the repo had three independent caches (the RAM
``FileTableCache``, an ad-hoc ``DiskTableCache``, the process
backend's shm segment arena) that accounted memory independently and
knew nothing about each other. This module makes the hierarchy
explicit and puts every tier on the ONE ``native.buffer_ledger()``:

``hot``     decoded tables in RAM (LRU within a byte budget; bytes are
            ledger-charged via ``native.account_table`` when decoded)
``disk``    decoded tables as uncompressed Arrow IPC files on local
            scratch (:class:`DiskTier` — the retired ``DiskTableCache``
            plus per-entry CRC32 and LRU eviction), memory-mapped back
            on hit and promoted to hot
``remote``  the :class:`storage.source.StorageSource` itself — a miss
            here is a real fetch, counted as such

Integrity: every disk entry records a ``native.crc32`` checksum at
write time (the spill-file discipline) and is re-verified on every
read; a mismatch — or any IO/decode failure — evicts the entry and
falls through to the next tier, so a flipped bit on scratch disk costs
one remote refetch and is otherwise invisible: sources are
deterministic, refetch-decode is bit-identical.

:class:`TieredStore` speaks the ``FileTableCache`` protocol
(``get``/``put``/``bytes_cached``/``close``), so it drops into
``shuffle()``'s existing ``file_cache=`` seam unchanged, and exposes
``warm()`` + ``make_prefetcher()`` for the plan scheduler's idle-lane
prefetch (:mod:`storage.prefetch`).
"""

from __future__ import annotations

import collections
import hashlib
import os
import tempfile
import threading
from typing import Dict, Optional, Tuple

import pyarrow as pa

from ray_shuffling_data_loader_tpu import native
from ray_shuffling_data_loader_tpu import tenancy as rt_tenancy
from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

_CRC_CHUNK = 1 << 20


def _file_crc(path: str) -> int:
    """Streaming CRC32 of a file (the spill.py discipline: 1 MiB
    chunks through the pluggable ``native.crc32`` kernel)."""
    crc = 0
    with open(path, "rb", buffering=0) as f:
        while True:
            chunk = f.read(_CRC_CHUNK)
            if not chunk:
                break
            crc = native.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _tier_counters(tier: str) -> Tuple[object, object, object, object]:
    """(hits, misses, evictions, corrupt) counters for one tier."""
    return (rt_metrics.counter("rsdl_storage_hits_total", tier=tier),
            rt_metrics.counter("rsdl_storage_misses_total", tier=tier),
            rt_metrics.counter("rsdl_storage_evictions_total", tier=tier),
            rt_metrics.counter("rsdl_storage_corrupt_total", tier=tier))


def _tenant_counters(tenant_id: str) -> Tuple[object, object, object]:
    """(hits, misses, evictions) counters for one tenant — the series
    the per-tenant thrash detector (runtime/health.py) watches."""
    return (rt_metrics.counter("rsdl_tenant_storage_hits_total",
                               tenant=tenant_id),
            rt_metrics.counter("rsdl_tenant_storage_misses_total",
                               tenant=tenant_id),
            rt_metrics.counter("rsdl_tenant_storage_evictions_total",
                               tenant=tenant_id))


class DiskTier:
    """Decoded-table cache on local disk: Arrow IPC files, memory-mapped
    back on hit, every entry CRC'd.

    The cold regime's dominant per-epoch cost is Parquet decompression +
    decode, which the reference re-pays every epoch (reference:
    shuffle.py:208) and the RAM cache can only skip while the decoded
    corpus fits in memory. This tier removes the constraint: the FIRST
    decode of a file writes the decoded table as an UNCOMPRESSED Arrow
    IPC file to local scratch; every later epoch memory-maps it — no
    decompression, no parse, zero-copy columns whose pages fault in
    lazily and remain reclaimable page cache, so RSS stays bounded no
    matter how large the corpus is. Measured on the bench host: parquet
    decode ~184 ns/row vs mmap open ~0; the one-time IPC write costs
    ~132 ns/row.

    Integrity: ``put`` records the written file's ``native.crc32``;
    ``get`` re-verifies before trusting the mapping (sequential page-in
    of bytes the decode was about to touch anyway) — a mismatch evicts
    the entry and returns ``None`` so the caller falls through to the
    next tier (remote refetch, bit-identical by source determinism).

    Disk usage is budgeted (``max_bytes``). With ``evict=True`` (the
    tiered default) insertion past the budget evicts least-recently-hit
    entries; with ``evict=False`` (the legacy ``DiskTableCache``
    behavior) further files simply re-decode parquet each epoch. With
    ``charge_ledger=True`` every on-disk byte is registered with
    ``native.buffer_ledger()`` and reported via ``bytes_cached`` so the
    budget machinery (spill.make_budget_state) sees one consistent
    account; the legacy subclass keeps both off.
    """

    #: Metrics tier label.
    tier = "disk"

    def __init__(self, max_bytes: int, cache_dir: Optional[str] = None,
                 evict: bool = True, charge_ledger: bool = True):
        self.max_bytes = max_bytes
        if cache_dir is None:
            cache_dir = tempfile.mkdtemp(prefix="rsdl_decoded_cache_")
            self._owns_dir = True
        else:
            os.makedirs(cache_dir, exist_ok=True)
            self._owns_dir = False
        self.cache_dir = cache_dir
        self._evict = evict
        self._charge_ledger = charge_ledger
        self._bytes = 0
        # key -> (path, bytes, crc, ledger buf_id or None); ordered by
        # recency of use (LRU eviction order).
        self._paths: "collections.OrderedDict[str, Tuple[str, int, int, Optional[int]]]" = \
            collections.OrderedDict()
        self._inflight: set = set()  # keys with a write in progress
        self._lock = threading.Lock()
        self._closed = False
        (self._hits, self._misses, self._evictions,
         self._corrupt) = _tier_counters(self.tier)
        self._bytes_gauge = rt_metrics.gauge(
            "rsdl_storage_tier_bytes", tier=self.tier)

    def _path_for(self, key: str) -> str:
        digest = hashlib.sha1(key.encode()).hexdigest()[:16]
        return os.path.join(self.cache_dir, f"{digest}.arrow")

    def _uncharge(self, buf_id: Optional[int]) -> None:
        if buf_id is not None:
            native.buffer_ledger().decref(buf_id)

    def _forget(self, key: str, path: str, nbytes: int) -> None:
        """Drop a bad/stale entry: uncharge the budget, delete the file."""
        buf_id = None
        with self._lock:
            entry = self._paths.get(key)
            if entry is not None and entry[0] == path:
                buf_id = entry[3]
                del self._paths[key]
                self._bytes -= nbytes
                self._bytes_gauge.set(self._bytes)
        self._uncharge(buf_id)
        try:
            os.remove(path)
        except OSError:
            pass

    def _evict_lru(self, incoming: int) -> None:
        """Drop least-recently-used entries until ``incoming`` fits
        (lock held by caller is NOT assumed; takes its own). The sweep
        is bounded by the entry count: every iteration pops one."""
        dropped = []
        with self._lock:
            while self._bytes + incoming > self.max_bytes and self._paths:
                key, (path, nbytes, _crc, buf_id) = \
                    self._paths.popitem(last=False)
                self._bytes -= nbytes
                dropped.append((path, buf_id))
            self._bytes_gauge.set(self._bytes)
        for path, buf_id in dropped:
            self._evictions.inc()
            self._uncharge(buf_id)
            try:
                os.remove(path)
            except OSError:
                pass

    def get(self, key: str) -> Optional[pa.Table]:
        with self._lock:
            entry = self._paths.get(key)
            if entry is not None:
                self._paths.move_to_end(key)  # LRU touch
        if entry is None:
            self._misses.inc()
            return None
        path, nbytes, crc, _buf_id = entry
        try:
            actual = _file_crc(path)
            if actual != crc:
                self._corrupt.inc()
                logger.warning(
                    "decoded-cache CRC mismatch for %s (%08x != %08x); "
                    "dropping entry, falling through to refetch",
                    key, actual, crc)
                self._forget(key, path, nbytes)
                self._misses.inc()
                return None
            with pa.memory_map(path) as source:
                table = pa.ipc.open_file(source).read_all()
            self._hits.inc()
            return table
        except (OSError, pa.ArrowInvalid) as e:
            logger.warning("decoded-cache read failed for %s (%s); "
                           "re-decoding", key, e)
            self._forget(key, path, nbytes)
            self._misses.inc()
            return None

    def put(self, key: str, table: pa.Table) -> bool:
        """Write-if-budget-allows; returns True if the file was cached."""
        nbytes = table.nbytes
        if self._evict:
            self._evict_lru(nbytes)
        with self._lock:
            if self._closed:
                return False
            if key in self._paths:
                return True
            if key in self._inflight:
                # Another epoch's map task is writing this key right now
                # (concurrent epochs map the same files); it keeps its own
                # decoded table for this epoch, the writer's file serves
                # the next.
                return False
            if self._bytes + nbytes > self.max_bytes:
                return False
            # Reserve under the lock so concurrent map tasks cannot
            # overshoot the budget together; release on failure below.
            self._bytes += nbytes
            self._inflight.add(key)
        path = self._path_for(key)
        # Writer-unique tmp name: _inflight already serializes same-key
        # writers, this guards against a stale .tmp from a crashed run.
        tmp_path = f"{path}.{id(table):x}.tmp"
        try:
            with pa.OSFile(tmp_path, "wb") as sink:
                with pa.ipc.new_file(sink, table.schema) as writer:
                    writer.write_table(table)
            os.replace(tmp_path, path)
            crc = _file_crc(path)
        except OSError as e:
            logger.warning("decoded-cache write failed for %s (%s); "
                           "cold reads continue from parquet", key, e)
            with self._lock:
                self._bytes -= nbytes
                self._inflight.discard(key)
            try:
                os.remove(tmp_path)
            except OSError:
                pass
            return False
        # Charge the REAL on-disk size against the budget, not
        # table.nbytes: IPC framing, schema/footer metadata, and 8/64-byte
        # alignment padding make the file larger than the raw column bytes
        # (ADVICE r5 — the drift compounds over thousands of files and let
        # the cache overshoot its disk budget).
        try:
            disk_bytes = os.stat(path).st_size
        except OSError:
            disk_bytes = nbytes  # keep the reservation if stat fails
        buf_id = (native.buffer_ledger().register(disk_bytes)
                  if self._charge_ledger and disk_bytes > 0 else None)
        with self._lock:
            self._inflight.discard(key)
            self._bytes += disk_bytes - nbytes  # re-charge at actual size
            if self._closed:  # closed while writing: drop the orphan
                self._bytes -= disk_bytes
                try:
                    os.remove(path)
                except OSError:
                    pass
                self._uncharge(buf_id)
                return False
            self._paths[key] = (path, disk_bytes, crc, buf_id)
            self._bytes_gauge.set(self._bytes)
        return True

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._paths

    @property
    def bytes_cached(self) -> int:
        """Ledger-visible bytes: what make_budget_state must discount.
        Zero unless this tier charges the ledger (the legacy subclass
        pins no accounted memory at all)."""
        if not self._charge_ledger:
            return 0
        with self._lock:
            return self._bytes

    @property
    def disk_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def close(self) -> None:
        """Delete cached files (safe even with live mmaps: POSIX keeps
        unlinked mappings valid) and, if this cache made its own scratch
        dir, the dir itself."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries = list(self._paths.values())
            self._paths.clear()
            self._bytes = 0
            self._bytes_gauge.set(0)
        for path, _nbytes, _crc, buf_id in entries:
            self._uncharge(buf_id)
            try:
                os.remove(path)
            except OSError:
                pass
        if self._owns_dir:
            try:
                os.rmdir(self.cache_dir)
            except OSError:
                pass


class DiskTableCache(DiskTier):
    """The pre-storage/ disk cache, now a thin legacy face over
    :class:`DiskTier`: no LRU eviction (once full, further files
    re-decode each epoch), no ledger charge, ``bytes_cached == 0``
    (it pins no accounted RAM — the budget machinery must not discount
    reclaimable page cache). New code should compose :class:`DiskTier`
    inside a :class:`TieredStore` instead."""

    def __init__(self, max_bytes: int, cache_dir: Optional[str] = None):
        super().__init__(max_bytes, cache_dir=cache_dir, evict=False,
                         charge_ledger=False)


class TieredStore:
    """hot (RAM LRU) over disk (:class:`DiskTier`) over remote (the
    installed :class:`StorageSource`), behind the ``FileTableCache``
    protocol so it plugs into ``shuffle(file_cache=...)`` unchanged.

    ``get`` promotes a disk hit into the hot tier; a hot insertion past
    the byte budget demotes by LRU — dropped from RAM but still served
    by its disk copy (``put`` writes through). A ``get`` miss on both
    tiers returns ``None`` and the caller's ordinary read path performs
    the remote fetch, which is also what a CRC-corrupt disk entry
    degrades to: sources are deterministic, so the refetched table is
    bit-identical to the lost one.

    ``warm(path)`` is the prefetch entry point: fetch + decode + (same
    transform the map stage applies) + insert, so a later ``get`` is a
    hit. ``make_prefetcher(plan)`` hands the plan scheduler a
    :class:`storage.prefetch.PrefetchManager` over the plan's map
    files — the duck-typed seam ``_shuffle_epoch_thread`` looks for.
    """

    def __init__(self, hot_bytes: int,
                 disk: Optional[DiskTier] = None,
                 source: Optional[object] = None,
                 tenant_quotas: Optional[Dict[str, int]] = None):
        self.hot_bytes = hot_bytes
        self.disk = disk
        self._source = source
        self._transform = None
        self._hot: "collections.OrderedDict[str, pa.Table]" = \
            collections.OrderedDict()
        self._hot_bytes_used = 0
        self._lock = threading.Lock()
        self._prefetched: set = set()
        # Tenancy partition of the hot tier: tenant_quotas caps each
        # tenant's RESIDENT bytes; the ambient TenantContext's
        # cache_quota_bytes fills in for tenants not in the table.
        # Over-quota insertion evicts the tenant's OWN LRU entries, so
        # one tenant's cold scan demotes its own pages, never a
        # neighbor's working set.
        self._tenant_quotas: Dict[str, int] = dict(tenant_quotas or {})
        self._key_tenant: Dict[str, str] = {}
        self._tenant_hot_bytes: Dict[str, int] = {}
        self._tenant_metrics: Dict[str, Tuple[object, object, object]] = {}
        # key -> Event for warms in flight: a reader that misses both
        # tiers JOINS the warm (waits for the fetch already running on
        # a prefetch thread) instead of racing it with a duplicate
        # remote GET. The event always fires (warm sets it in finally).
        self._warming: Dict[str, threading.Event] = {}
        (self._hot_hits, self._hot_misses, self._hot_evictions,
         _unused) = _tier_counters("hot")
        self._remote_misses = rt_metrics.counter(
            "rsdl_storage_misses_total", tier="remote")
        self._hot_gauge = rt_metrics.gauge(
            "rsdl_storage_tier_bytes", tier="hot")
        self._prefetch_hits = rt_metrics.counter(
            "rsdl_storage_prefetch_hits_total",
            "prefetched entries later hit by a real map task")

    # -- FileTableCache protocol ---------------------------------------

    def get(self, key: str) -> Optional[pa.Table]:
        # Bounded: each pass either returns or waits for ONE in-flight
        # warm of this key; when the warm resolves (success or not) the
        # re-probe either hits a tier or finds no warm and returns None.
        tenant_id = rt_tenancy.current_tenant().tenant_id
        t_hits, t_misses, _ = self._tenant_counters_for(tenant_id)
        while True:
            with self._lock:
                table = self._hot.get(key)
                if table is not None:
                    self._hot.move_to_end(key)
                    was_prefetched = key in self._prefetched
                    self._prefetched.discard(key)
                else:
                    was_prefetched = False
            if table is not None:
                self._hot_hits.inc()
                t_hits.inc()
                if was_prefetched:
                    self._prefetch_hits.inc()
                return table
            self._hot_misses.inc()
            if self.disk is not None:
                table = self.disk.get(key)  # CRC-verified; None if corrupt
                if table is not None:
                    with self._lock:
                        was_prefetched = key in self._prefetched
                        self._prefetched.discard(key)
                    if was_prefetched:
                        self._prefetch_hits.inc()
                    self._promote(key, table)
                    t_hits.inc()
                    return table
            with self._lock:
                event = self._warming.get(key)
            if event is None:
                self._remote_misses.inc()
                t_misses.inc()
                return None
            # A prefetch thread is already fetching this key: join it —
            # the wait is the REMAINDER of a transfer that started on
            # idle time, never a fresh full fetch, and never a
            # duplicate remote GET for bytes already on the wire.
            event.wait()

    def put(self, key: str, table: pa.Table) -> bool:
        """Insert into hot (LRU-evicting to fit) and write through to
        the disk tier; True if either tier holds it afterwards."""
        in_hot = self._promote(key, table)
        on_disk = self.disk.put(key, table) if self.disk is not None \
            else False
        return in_hot or on_disk

    @property
    def bytes_cached(self) -> int:
        """Every ledger-charged byte this store holds resident — the
        quantity spill.make_budget_state discounts from the transient
        ledger: hot tables (charged via native.account_table at decode
        time) plus the disk tier's ledger-charged file bytes."""
        with self._lock:
            hot = self._hot_bytes_used
        return hot + (self.disk.bytes_cached if self.disk is not None
                      else 0)

    def close(self) -> None:
        with self._lock:
            self._hot.clear()
            self._hot_bytes_used = 0
            self._hot_gauge.set(0)
            self._prefetched.clear()
            self._key_tenant.clear()
            for tenant_id in self._tenant_hot_bytes:
                rt_metrics.gauge("rsdl_tenant_cache_bytes",
                                 tenant=tenant_id).set(0)
            self._tenant_hot_bytes.clear()
        if self.disk is not None:
            self.disk.close()

    # -- internals -----------------------------------------------------

    def _tenant_counters_for(self, tenant_id: str
                             ) -> Tuple[object, object, object]:
        counters = self._tenant_metrics.get(tenant_id)
        if counters is None:
            counters = _tenant_counters(tenant_id)
            self._tenant_metrics[tenant_id] = counters
        return counters

    def _tenant_quota(self, tenant_id: str) -> Optional[int]:
        """This tenant's hot-tier byte cap: the explicit quota table
        first, the ambient context's cache_quota_bytes second, None
        (share the global budget unpartitioned) otherwise."""
        quota = self._tenant_quotas.get(tenant_id)
        if quota is None:
            ctx = rt_tenancy.current_tenant()
            if ctx.tenant_id == tenant_id:
                quota = ctx.cache_quota_bytes
        if quota is not None:
            rt_metrics.gauge("rsdl_tenant_cache_quota_bytes",
                             tenant=tenant_id).set(quota)
        return quota

    def _drop_hot_locked(self, key: str, table: pa.Table) -> str:
        """Remove ``key`` from the hot tier (caller holds ``_lock`` —
        the ``_locked`` suffix is the contract); returns the tenant the
        entry was charged to."""
        # rsdl-lint: disable=lock-mutation
        self._hot_bytes_used -= table.nbytes
        tenant_id = self._key_tenant.pop(key, rt_tenancy.DEFAULT_TENANT_ID)
        # rsdl-lint: disable=lock-mutation
        self._tenant_hot_bytes[tenant_id] = \
            self._tenant_hot_bytes.get(tenant_id, 0) - table.nbytes
        return tenant_id

    def _promote(self, key: str, table: pa.Table) -> bool:
        nbytes = table.nbytes
        tenant_id = rt_tenancy.current_tenant().tenant_id
        quota = self._tenant_quota(tenant_id)
        evicted = []  # (key, charged tenant)
        with self._lock:
            if key in self._hot:
                self._hot.move_to_end(key)
                return True
            if quota is not None and nbytes > quota:
                return False  # can never fit this tenant's partition
            # Tenant-preferential eviction: an over-quota tenant demotes
            # its OWN least-recent entries; neighbors' working sets stay
            # resident no matter how cold this tenant's scan runs.
            if quota is not None:
                while (self._tenant_hot_bytes.get(tenant_id, 0) + nbytes
                       > quota):
                    victim = next(
                        (k for k in self._hot
                         if self._key_tenant.get(k) == tenant_id), None)
                    if victim is None:
                        break
                    old = self._hot.pop(victim)
                    evicted.append((victim, self._drop_hot_locked(
                        victim, old)))
            while (self._hot_bytes_used + nbytes > self.hot_bytes
                   and self._hot):
                old_key, old = self._hot.popitem(last=False)
                evicted.append((old_key, self._drop_hot_locked(
                    old_key, old)))
            if self._hot_bytes_used + nbytes > self.hot_bytes:
                self._hot_gauge.set(self._hot_bytes_used)
                ok = False
            else:
                self._hot[key] = table
                self._hot_bytes_used += nbytes
                self._key_tenant[key] = tenant_id
                self._tenant_hot_bytes[tenant_id] = \
                    self._tenant_hot_bytes.get(tenant_id, 0) + nbytes
                self._hot_gauge.set(self._hot_bytes_used)
                ok = True
            touched = {tenant_id} | {t for _, t in evicted}
            tenant_bytes = {t: self._tenant_hot_bytes.get(t, 0)
                            for t in touched}
        for _, victim_tenant in evicted:
            # Demotion, not loss: put() wrote the entry through to disk,
            # so the evicted key keeps serving from the next tier down.
            self._hot_evictions.inc()
            self._tenant_counters_for(victim_tenant)[2].inc()
        for t, used in tenant_bytes.items():
            rt_metrics.gauge("rsdl_tenant_cache_bytes",
                             tenant=t).set(used)
        return ok

    # -- prefetch seam -------------------------------------------------

    def set_transform(self, transform) -> None:
        """The map stage caches TRANSFORMED tables; warm() must apply
        the same transform or a prefetched hit would change the
        delivered stream. shuffle() wires this before the first epoch."""
        self._transform = transform

    def resident(self, key: str) -> bool:
        with self._lock:
            if key in self._hot:
                return True
        return self.disk is not None and key in self.disk

    def resident_bytes(self, key: str) -> int:
        """Size of ``key``'s resident copy (hot table bytes, else the
        disk entry's on-disk bytes, else 0) — prefetch quota
        accounting."""
        with self._lock:
            table = self._hot.get(key)
            if table is not None:
                return table.nbytes
        if self.disk is not None:
            with self.disk._lock:
                entry = self.disk._paths.get(key)
                if entry is not None:
                    return entry[1]
        return 0

    def warm(self, key: str) -> bool:
        """Fetch + decode + transform + insert ``key`` so a later map
        task's ``get`` hits — or, if the get arrives mid-fetch, JOINS
        this warm instead of duplicating the remote GET. Returns True
        when the entry is resident afterwards (already-resident keys
        short-circuit; a concurrent warm of the same key is waited on,
        not raced)."""
        if self.resident(key):
            return True
        with self._lock:
            event = self._warming.get(key)
            if event is None:
                event = threading.Event()
                self._warming[key] = event
                owner = True
            else:
                owner = False
        if not owner:
            event.wait()
            return self.resident(key)
        ok = False
        try:
            source = self._source
            if source is None:
                from ray_shuffling_data_loader_tpu import storage \
                    as rt_storage
                source = rt_storage.get_source()
            table = source.read_table(key)
            if self._transform is not None:
                table = self._transform(table)
            # Single-chunk like the map path, so later epochs' numpy
            # views stay zero-copy. rsdl-lint: disable=copy-in-hot-path
            table = table.combine_chunks()
            native.account_table(table)
            ok = self.put(key, table)
            if ok:
                with self._lock:
                    self._prefetched.add(key)
            return ok
        finally:
            with self._lock:
                self._warming.pop(key, None)
            event.set()

    def make_prefetcher(self, plan):
        """A PrefetchManager over ``plan``'s map files — the epoch-N
        plan names exactly the files epoch N+1 re-reads, so warming
        them on idle lanes turns the next epoch's cold reads warm."""
        from ray_shuffling_data_loader_tpu.storage.prefetch import \
            PrefetchManager
        files = [node.meta["file"] for node in plan.maps()
                 if node.meta.get("file")]
        # Pin the tenant at construction: the plan's tenant_id if it
        # carries one, else whoever is building the prefetcher — pool
        # threads running the tasks later may sit in a different
        # ambient scope.
        tenant = getattr(plan, "tenant_id", None) \
            or rt_tenancy.current_tenant()
        return PrefetchManager(self, files, tenant=tenant)
