"""Plan-driven cache warming on idle scheduler lanes.

The epoch plan is an explicit IR (plan/ir.py): epoch N's plan already
names exactly which files the map stage reads — and epoch N+1 reads
the same list. That turns prefetch from a heuristic (readahead,
access-pattern guessing) into a lookup: any lane the scheduler has
nothing real for can spend its idleness warming the tiered cache with
the files the next epoch will fault on.

Priority contract (enforced by plan/scheduler.py): real ready nodes
first, then work stealing, then speculation — prefetch runs strictly
below all three, and a lane occupied by a prefetch is treated as IDLE
by the scheduler: arriving real work cancels the prefetch (best
effort — a transfer already in flight finishes and still warms the
cache; only its lane bookkeeping is released immediately).

Accounting: ``issued`` counts prefetches that actually started,
``canceled`` those the scheduler reclaimed before start, and ``hits``
(counted by the TieredStore) prefetched entries a real map task later
consumed. ``efficiency = hits / issued`` is the honest number — a
prefetcher that warms files nobody reads reports it.

Failures are deliberately swallowed (logged, counted as a miss by
omission): prefetch is an optimization, and the real read path owns
retries, quarantine, and chaos-fault surfacing for the task that
actually needs the bytes.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from ray_shuffling_data_loader_tpu import tenancy as rt_tenancy
from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)


class PrefetchTask:
    """One cancelable warm-one-file unit of lane work."""

    __slots__ = ("manager", "path", "_cancel", "_started")

    def __init__(self, manager: "PrefetchManager", path: str):
        self.manager = manager
        self.path = path
        self._cancel = threading.Event()
        self._started = threading.Event()

    def cancel(self) -> None:
        """Scheduler-side reclaim: a real task needs the lane. A task
        that never started is counted canceled; one already fetching
        finishes (and still warms the cache)."""
        self._cancel.set()
        if not self._started.is_set():
            self.manager._canceled.inc()

    def run(self) -> bool:
        """Pool-side body; returns True when the entry became (or
        already was) resident."""
        if self._cancel.is_set():
            return False
        if not self.manager._under_quota():
            # Quota throttle, not cancellation: the tenant spent its
            # prefetch byte allowance; the real read path still fetches
            # on demand, this lane just stops speculating for it.
            return False
        self._started.set()
        self.manager._issued.inc()
        try:
            warmed = self.manager.store.warm(self.path)
            if warmed:
                self.manager._charge(self.path)
            return warmed
        except Exception as e:  # noqa: BLE001 - optimization, not truth
            logger.debug("prefetch of %s failed (%s); the real read "
                         "path will fetch it", self.path, e)
            return False


class PrefetchManager:
    """Hands the scheduler one :class:`PrefetchTask` at a time, in plan
    order, skipping files already resident in the store."""

    def __init__(self, store, files, tenant=None):
        self.store = store
        # The owning tenant (ambient unless pinned): its
        # prefetch_quota_bytes caps how many bytes of speculation this
        # plan may warm — demand reads are never throttled, only the
        # idle-lane speculation stops once the allowance is spent.
        self.tenant = rt_tenancy.resolve(tenant)
        self._quota = self.tenant.prefetch_quota_bytes
        self._warmed_bytes = 0
        self._pending = deque(files)
        self._lock = threading.Lock()
        self._issued = rt_metrics.counter(
            "rsdl_storage_prefetch_issued_total",
            "prefetch tasks that started fetching")
        self._canceled = rt_metrics.counter(
            "rsdl_storage_prefetch_canceled_total",
            "prefetch tasks reclaimed by real work before starting")
        self._throttled = rt_metrics.counter(
            "rsdl_tenant_prefetch_throttled_total",
            "prefetch tasks skipped by the tenant's byte quota",
            tenant=self.tenant.tenant_id)

    def _under_quota(self) -> bool:
        if self._quota is None:
            return True
        with self._lock:
            ok = self._warmed_bytes < self._quota
        if not ok:
            self._throttled.inc()
        return ok

    def _charge(self, path: str) -> None:
        sizer = getattr(self.store, "resident_bytes", None)
        if sizer is None:
            return
        try:
            nbytes = int(sizer(path))
        except Exception:  # noqa: BLE001 - accounting only
            return
        with self._lock:
            self._warmed_bytes += nbytes

    def next(self) -> Optional[PrefetchTask]:
        """The next non-resident file as a task; None when drained.
        Bounded: every pass pops one pending entry."""
        while self._pending:
            with self._lock:
                if not self._pending:
                    return None
                path = self._pending.popleft()
            try:
                if self.store.resident(path):
                    continue
            except Exception:  # noqa: BLE001 - residency probe only
                continue
            return PrefetchTask(self, path)

    def stats(self) -> dict:
        """{issued, canceled, hits, efficiency} — hits come from the
        store's prefetch-hit counter (a hit is only countable where
        the consuming get() runs)."""
        issued = self._issued.value
        hits = rt_metrics.counter(
            "rsdl_storage_prefetch_hits_total").value
        return {
            "issued": issued,
            "canceled": self._canceled.value,
            "hits": hits,
            "efficiency": hits / issued if issued else 0.0,
        }
