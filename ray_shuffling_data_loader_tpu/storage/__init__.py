"""storage/: remote object storage as a first-class source.

Every dataset read in the pipeline funnels through this package:

- :mod:`storage.source` — WHERE bytes live: :class:`LocalSource`,
  :class:`HTTPRangeSource`, :class:`SimulatedObjectStore` behind the
  one :class:`StorageSource` contract.
- :mod:`storage.cache` — the explicit hot (RAM) → disk (CRC'd Arrow
  IPC) → remote tier hierarchy (:class:`TieredStore`,
  :class:`DiskTier`), every tier on the one buffer ledger.
- :mod:`storage.prefetch` — plan-driven warming on idle scheduler
  lanes (:class:`PrefetchManager`).

This module owns the PROCESS-WIDE source: :func:`get_source` resolves
it lazily from the ``RSDL_STORAGE_BACKEND`` policy knob ("local" |
"sim"), :func:`set_source` installs one programmatically (tests, the
bench's remote leg — same process-local caveat as programmatic chaos:
process-backend workers resolve their own from the inherited env).

:func:`read_table` / :func:`open_parquet` are the routed read calls
``shuffle._read_map_table`` and the fused streaming pipeline use; they
fire the ``storage_read`` / ``storage_stall`` chaos sites OUTSIDE the
in-place retry, so an injected fault surfaces to lineage recovery
instead of being absorbed as IO weather (the ``map_read`` discipline,
runtime/faults.py).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
from ray_shuffling_data_loader_tpu.runtime import retry as rt_retry
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.storage.cache import (DiskTableCache,
                                                         DiskTier,
                                                         TieredStore)
from ray_shuffling_data_loader_tpu.storage.prefetch import (PrefetchManager,
                                                            PrefetchTask)
from ray_shuffling_data_loader_tpu.storage.source import (HTTPRangeSource,
                                                          LocalSource,
                                                          SimulatedObjectStore,
                                                          StorageSource)

__all__ = [
    "StorageSource", "LocalSource", "HTTPRangeSource",
    "SimulatedObjectStore", "DiskTier", "DiskTableCache", "TieredStore",
    "PrefetchManager", "PrefetchTask", "get_source", "set_source",
    "read_table", "open_parquet",
]

_lock = threading.Lock()
_source: Optional[StorageSource] = None


def _resolve_default() -> StorageSource:
    backend = str(rt_policy.resolve("storage", "storage_backend")).lower()
    if backend == "sim":
        return SimulatedObjectStore()
    if backend != "local":
        raise ValueError(
            f"RSDL_STORAGE_BACKEND must be 'local' or 'sim' (install "
            f"anything else via storage.set_source), got {backend!r}")
    return LocalSource()


def get_source() -> StorageSource:
    """The process-wide source, resolved from policy on first use."""
    global _source
    with _lock:
        if _source is None:
            _source = _resolve_default()
        return _source


def set_source(source: Optional[StorageSource]) -> Optional[StorageSource]:
    """Install ``source`` process-wide (None = re-resolve from policy
    on next use); returns the previous source for save/restore."""
    global _source
    with _lock:
        previous, _source = _source, source
    return previous


def _inject(epoch: Optional[int], task: Optional[int]) -> None:
    # Two sites, one boundary: storage_read is the lost-GET failure
    # shape, storage_stall the slow-first-byte delay shape (delayN
    # sleeps instead of raising). Both free when chaos is inactive.
    rt_faults.inject("storage_read", epoch=epoch, task=task)
    t0 = time.monotonic()
    rt_faults.inject("storage_stall", epoch=epoch, task=task)
    if rt_faults.active():
        # Surface the measured stall (usually 0; the injected delay when
        # a delayN rule fired) as a plain stage event so a storage_stall
        # fault is JOINABLE by its (kind, epoch, task) key in the
        # chaos/telemetry correlation (bench.py `fault_events_joinable`)
        # — a raise-shape stall joins through the recovery re-read that
        # lands here with the rule already spent. No entry in
        # trace.STAGE_RANK, so it never enters critical-path
        # attribution, and with chaos inactive no event is recorded.
        rt_telemetry.record("storage_stall", epoch=epoch, task=task,
                            dur_s=time.monotonic() - t0)


def read_table(path: str, epoch: Optional[int] = None,
               task: Optional[int] = None,
               retry: Optional[rt_retry.RetryPolicy] = None,
               source: Optional[StorageSource] = None) -> pa.Table:
    """Fetch + decode one dataset file through the installed source.

    The chaos sites fire BEFORE the (optionally retried) fetch: an
    injected fault is a lost task for the recovery machinery, not IO
    weather for ``retry`` to absorb.
    """
    src = source if source is not None else get_source()
    _inject(epoch, task)
    t0 = time.monotonic()
    if retry is None:
        table = src.read_table(path)
    else:
        table = retry.call(src.read_table, path,
                           describe=f"storage read {path}")
    # Plain stage event for the chaos/telemetry join: a storage_read
    # fault shares this (kind, epoch, task) key — the recovery re-read
    # lands here, so even a raise-shape injection is joinable. Absent
    # from trace.STAGE_RANK => never on the critical path.
    rt_telemetry.record("storage_read", epoch=epoch, task=task,
                        dur_s=time.monotonic() - t0)
    return table


def open_parquet(path: str, epoch: Optional[int] = None,
                 task: Optional[int] = None,
                 source: Optional[StorageSource] = None) -> pq.ParquetFile:
    """A streaming-reader handle through the installed source (the
    fused map pipeline's entry); same chaos-site discipline."""
    src = source if source is not None else get_source()
    _inject(epoch, task)
    t0 = time.monotonic()
    handle = src.open_parquet(path)
    rt_telemetry.record("storage_read", epoch=epoch, task=task,
                        dur_s=time.monotonic() - t0)
    return handle
