"""Loader-state checkpoint / resume.

The reference has no checkpointing anywhere (SURVEY.md §5) and *cannot*
add it: its shuffle draws from unseeded ``np.random`` in remote tasks
(reference: shuffle.py:213,240), so a killed run can never reproduce the
epoch order it was consuming. Our shuffle is keyed by (seed, epoch, task),
which makes every epoch's batch stream a pure function of
``(seed, epoch)`` — so resuming is just: re-run the shuffle for the
current epoch and fast-skip the batches already consumed.

``LoaderCheckpoint`` captures that state; ``resume_iterator`` wraps a
dataset to skip + count batches and keep the checkpoint current.
Checkpoints are JSON — human-readable, atomic-rename durable. Model/optimizer
state belongs to orbax; this covers the input pipeline half, which is the
half the reference ecosystem is missing.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import tempfile
import threading
from typing import Dict, Iterator, Optional

from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

FORMAT_VERSION = 1


@dataclasses.dataclass
class LoaderCheckpoint:
    """Everything needed to resume the input pipeline deterministically."""

    seed: int
    epoch: int
    batches_consumed: int  # within the current epoch
    num_epochs: int
    num_trainers: int
    rank: int
    batch_size: int
    version: int = FORMAT_VERSION

    def save(self, path: str) -> None:
        """Atomic durable write: tmp file + fsync + rename + dir fsync."""
        payload = json.dumps(dataclasses.asdict(self), indent=2)
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        fd, tmp_path = tempfile.mkstemp(dir=directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp_path, path)
            dir_fd = os.open(directory, os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)
        except BaseException:
            if os.path.exists(tmp_path):
                os.remove(tmp_path)
            raise

    @classmethod
    def load(cls, path: str) -> "LoaderCheckpoint":
        with open(path) as f:
            data = json.load(f)
        version = data.get("version", 0)
        if version != FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format version {version} != {FORMAT_VERSION}")
        return cls(**data)


@dataclasses.dataclass
class WatermarkEntry:
    """Latest journaled state of one queue index: the last acked frame
    seq, cumulative table rows delivered through it, whether the
    epoch-end sentinel itself has been acked, and the journaled birth
    stamps of still-unacked frames.

    ``births`` maps frame seq -> ``(pid, t_mono, t_unix)``: the frame's
    ORIGINAL payload birth (runtime/latency.py stamp), journaled when
    the frame was first built. A queue index never existed -> entry
    with ``seq == -1`` (nothing delivered) carrying only births — the
    restored server's ``next_seq`` math (``seq + 1``) and the resume
    query's ``skip_items`` (``seq + 1``) both read that as zero."""

    seq: int
    rows: int
    done: bool = False
    births: Dict[int, tuple] = dataclasses.field(default_factory=dict)


def shard_journal_path(path: str, shard_index: int, num_shards: int) -> str:
    """The watermark-journal path for one serving-plane shard.

    Each shard journals ONLY its owned ranks' queues (the
    ``plan.ir.queue_shard`` placement), so the PR 5 recovery matrix
    holds per shard: a restarted shard replays from its own journal
    without reading (or racing) its siblings'. The single-shard name is
    unchanged so pre-sharding journals keep resuming."""
    if num_shards <= 1:
        return path
    return f"{path}.shard{shard_index}"


def crc_line(entry: dict) -> str:
    """Encode one journal record in THE crc'd-line discipline every
    append-only journal in this repo shares (watermarks, stream ingest,
    membership views): the ``crc`` field covers the canonical encoding
    (sorted keys, compact separators) of ``entry``, so a torn tail — the
    process died mid-write — is detected on load, never misread."""
    from ray_shuffling_data_loader_tpu import native
    body = json.dumps(entry, sort_keys=True, separators=(",", ":"))
    crc = native.crc32(body.encode()) & 0xFFFFFFFF
    return json.dumps({"crc": crc, "entry": entry}, sort_keys=True,
                      separators=(",", ":"))


def parse_crc_line(line: str) -> dict:
    """Decode one :func:`crc_line` record, raising ``ValueError`` on a
    missing or mismatched CRC (the torn-tail shape loaders skip)."""
    from ray_shuffling_data_loader_tpu import native
    record = json.loads(line)
    entry = record["entry"]
    body = json.dumps(entry, sort_keys=True, separators=(",", ":"))
    if native.crc32(body.encode()) & 0xFFFFFFFF != record["crc"]:
        raise ValueError("crc mismatch")
    return entry


class WatermarkJournal:
    """Crc'd append-only journal of per-queue delivered watermarks.

    The queue server (multiqueue_service.QueueServer) appends a record
    every time a consumer's ack watermark advances; a restarted server
    process loads the journal and regenerates ONLY the undelivered
    remainder from the deterministic shuffle lineage. Each line is a
    JSON record whose ``crc`` field covers the canonical encoding of the
    other fields — a torn tail (the server died mid-write) is skipped on
    load, never misread. :meth:`compact` rewrites the latest-per-queue
    state with the same atomic tmp + fsync + rename discipline as
    :class:`LoaderCheckpoint`.
    """

    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()
        self._file = None

    @staticmethod
    def _encode(entry: dict) -> str:
        return crc_line(entry)

    def record(self, queue_index: int, seq: int, rows: int,
               done: bool = False) -> None:
        """Append one watermark advance; flushed + fsync'd so a
        ``kill -9`` loses at most acks the consumer will simply re-see
        (replay is idempotent by seq)."""
        entry = {"q": int(queue_index), "seq": int(seq),
                 "rows": int(rows), "done": bool(done)}
        self._append(entry, durable=True)

    def record_birth(self, queue_index: int, seq: int, pid: int,
                     t_mono: float, t_unix: float) -> None:
        """Journal a frame's ORIGINAL payload birth when the frame is
        first built, so a restarted server regenerating the undelivered
        remainder re-attaches the original stamps and a kill -9 replay
        reports its true (crash-spanning) delivery latency instead of a
        laundered recompute-fresh one. Flushed but NOT fsync'd: losing
        a tail birth record merely under-reports one frame's latency
        (the regenerated stamp takes over) — never correctness."""
        self._append({"q": int(queue_index), "bseq": int(seq),
                      "pid": int(pid), "tm": float(t_mono),
                      "tu": float(t_unix)}, durable=False)

    def _append(self, entry: dict, durable: bool) -> None:
        line = self._encode(entry) + "\n"
        with self._lock:
            if self._file is None:
                directory = os.path.dirname(os.path.abspath(self._path))
                os.makedirs(directory, exist_ok=True)
                self._file = open(self._path, "a", encoding="utf-8")
            self._file.write(line)
            self._file.flush()
            if durable:
                os.fsync(self._file.fileno())

    @classmethod
    def load(cls, path: str) -> Dict[int, WatermarkEntry]:
        """Latest watermark per queue index; lines with a bad/missing
        CRC (torn tail) are skipped with a warning."""
        state: Dict[int, WatermarkEntry] = {}
        births: Dict[int, Dict[int, tuple]] = \
            collections.defaultdict(dict)
        if not os.path.exists(path):
            return state
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = parse_crc_line(line)
                    queue_index = int(entry["q"])
                    if "bseq" in entry:
                        # Frame-birth record: retained only while its
                        # seq is past the queue's watermark (acked
                        # frames never replay, so their births are
                        # dead weight).
                        births[queue_index][int(entry["bseq"])] = (
                            int(entry["pid"]), float(entry["tm"]),
                            float(entry["tu"]))
                        continue
                except (ValueError, KeyError, TypeError) as e:
                    logger.warning(
                        "watermark journal %s line %d unreadable (%s); "
                        "skipping (torn tail from a crash is expected)",
                        path, lineno, e)
                    continue
                previous = state.get(queue_index)
                if previous is None or entry["seq"] >= previous.seq:
                    state[queue_index] = WatermarkEntry(
                        seq=int(entry["seq"]), rows=int(entry["rows"]),
                        done=bool(entry["done"]))
        for queue_index, stamps in births.items():
            entry = state.get(queue_index)
            if entry is None:
                entry = state[queue_index] = WatermarkEntry(seq=-1, rows=0)
            entry.births = {seq: stamp for seq, stamp in stamps.items()
                            if seq > entry.seq}
        return state

    def resume_plan(self, num_epochs: int, num_trainers: int
                    ) -> "tuple[int, Dict[int, int]]":
        """``(start_epoch, skip_items)`` for a producer resuming against
        this journal — delegated to the epoch-plan query
        (``plan.ir.resume_from_watermarks``), the single home of the
        journal-resume math the restarted queue server and the tests
        both consult."""
        from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
        return plan_ir.resume_from_watermarks(self.load(self._path),
                                              num_epochs, num_trainers)

    def compact(self) -> None:
        """Rewrite the journal as one latest record per queue, atomic
        tmp + fsync + rename (the LoaderCheckpoint discipline) — run at
        server restart so the append-only file cannot grow unboundedly
        across crash/recovery cycles."""
        state = self.load(self._path)
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            directory = os.path.dirname(os.path.abspath(self._path))
            os.makedirs(directory, exist_ok=True)
            fd, tmp_path = tempfile.mkstemp(dir=directory, suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    for queue_index in sorted(state):
                        entry = state[queue_index]
                        if entry.seq >= 0:
                            f.write(self._encode(
                                {"q": queue_index, "seq": entry.seq,
                                 "rows": entry.rows, "done": entry.done})
                                + "\n")
                        # Unacked frames' birth stamps survive
                        # compaction — they are exactly the frames a
                        # restart will regenerate and re-stamp from.
                        for seq in sorted(entry.births):
                            pid, t_mono, t_unix = entry.births[seq]
                            f.write(self._encode(
                                {"q": queue_index, "bseq": seq,
                                 "pid": pid, "tm": t_mono,
                                 "tu": t_unix}) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp_path, self._path)
                dir_fd = os.open(directory, os.O_RDONLY)
                try:
                    os.fsync(dir_fd)
                finally:
                    os.close(dir_fd)
            except BaseException:
                if os.path.exists(tmp_path):
                    os.remove(tmp_path)
                raise

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


class StreamJournal:
    """Crc'd append-only journal of INGEST-side stream records — the
    :class:`WatermarkJournal` line discipline (crc-covered canonical
    JSON, torn tails skipped on load) applied to the other end of the
    pipe. Delivery watermarks journal what consumers have durably seen;
    this journals what the stream has durably *admitted*:

    - ``DirectoryTailSource`` manifest records (``{"kind": "file", "n",
      "path", "size"}``): the discovery ORDER of arriving files, so a
      recovered source re-discovers the identical sequence regardless of
      directory-listing order.
    - window ingest watermarks (``{"kind": "watermark", "window",
      "events", "files"}``, ``streaming/window.py``): monotone count of
      events sealed into closed windows — the minuend of the
      ``watermark_lag`` health detector.

    Records are appended durably (flush + fsync) by default: a
    ``kill -9`` between a window close and its journal record re-closes
    the same window from the manifest, which is idempotent because
    window assembly is deterministic in the admitted-event order.
    """

    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()
        self._file = None

    @property
    def path(self) -> str:
        return self._path

    def append(self, entry: dict, durable: bool = True) -> None:
        line = WatermarkJournal._encode(dict(entry)) + "\n"
        with self._lock:
            if self._file is None:
                directory = os.path.dirname(os.path.abspath(self._path))
                os.makedirs(directory, exist_ok=True)
                self._file = open(self._path, "a", encoding="utf-8")
            self._file.write(line)
            self._file.flush()
            if durable:
                os.fsync(self._file.fileno())

    @classmethod
    def load(cls, path: str) -> "list[dict]":
        """Every intact record, in append order; lines with a bad or
        missing CRC (torn tail) are skipped with a warning."""
        entries: "list[dict]" = []
        if not os.path.exists(path):
            return entries
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = parse_crc_line(line)
                except (ValueError, KeyError, TypeError) as e:
                    logger.warning(
                        "stream journal %s line %d unreadable (%s); "
                        "skipping (torn tail from a crash is expected)",
                        path, lineno, e)
                    continue
                entries.append(entry)
        return entries

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


def resume_iterator(dataset,
                    checkpoint: LoaderCheckpoint,
                    checkpoint_path: Optional[str] = None,
                    checkpoint_every: int = 0) -> Iterator:
    """Iterate ``dataset`` from ``checkpoint``, optionally persisting.

    Re-derives the current epoch (the shuffle is seeded, so batch order
    replays exactly), silently skips ``batches_consumed`` batches, then
    yields the remainder and all later epochs. The caller must have
    constructed ``dataset`` with the checkpoint's seed/batch_size/etc.
    (validated here where the dataset exposes them).

    With ``checkpoint_path`` set, the checkpoint advances after every
    ``checkpoint_every`` batches (0 = only at epoch ends). Persistence is
    **at-least-once**: a batch is recorded as consumed only when the caller
    comes back for the next one, so a crash while processing batch N
    replays batch N on resume — batches can repeat across a crash, but
    none are ever skipped.

    After the final epoch the checkpoint is left at
    ``(epoch=num_epochs, batches_consumed=0)``, so resuming a finished run
    is a clean no-op instead of a silent extra epoch.

    Skipping is cheap when the dataset supports
    ``set_epoch(epoch, skip_batches=N)`` (ours do): already-consumed
    batches are dropped as zero-copy Arrow slices before any
    NumPy conversion or device transfer happens. Foreign datasets fall
    back to materialize-and-discard.
    """
    for field in ("batch_size", "seed", "num_epochs"):
        have = getattr(dataset, field, None)
        want = getattr(checkpoint, field)
        if have is not None and have != want:
            raise ValueError(
                f"dataset {field} {have} != checkpoint {field} {want}")

    def _maybe_save():
        if checkpoint_path is not None:
            checkpoint.save(checkpoint_path)
            # Replaying-queue integration: once the position is durable,
            # release the server's replay buffer up to it. A dataset fed
            # by a manual-ack RemoteQueue forwards this to the queue's
            # commit(); everything since the previous save stays
            # replayable for a crash-resumed trainer.
            commit = getattr(dataset, "commit_consumed", None)
            if commit is not None:
                commit()

    from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
    for epoch in plan_ir.epoch_range(checkpoint.epoch,
                                     checkpoint.num_epochs):
        skip = checkpoint.batches_consumed if epoch == checkpoint.epoch else 0
        checkpoint.epoch = epoch
        fallback_skip = 0
        if skip:
            try:
                dataset.set_epoch(epoch, skip_batches=skip)
            except TypeError:  # foreign dataset: discard batches ourselves
                dataset.set_epoch(epoch)
                fallback_skip = skip
        else:
            dataset.set_epoch(epoch)
        index = skip - fallback_skip
        for batch in dataset:
            index += 1
            if index <= skip:
                continue  # replayed batch, already consumed pre-crash
            checkpoint.batches_consumed = index
            yield batch
            if checkpoint_every and index % checkpoint_every == 0:
                _maybe_save()
        checkpoint.batches_consumed = 0
        checkpoint.epoch = epoch + 1
        _maybe_save()


class TrainStateCheckpointer:
    """Orbax-backed checkpoints of the OTHER half: sharded model/optimizer
    state, saved together with the loader checkpoint so one ``restore``
    resumes both the trainer and the exact batch stream position.

    Works with :class:`parallel.trainer.SpmdTrainer` (or anything exposing
    ``params`` / ``opt_state`` pytrees of ``jax.Array``s): orbax persists
    each array with its sharding, so a v4-32 run restores straight into
    HBM across the same mesh without a host gather.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._manager = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    def save(self, step: int, trainer,
             loader_checkpoint: Optional[LoaderCheckpoint] = None,
             wait: bool = True) -> None:
        ocp = self._ocp
        args = {
            "state": ocp.args.StandardSave(
                {"params": trainer.params, "opt_state": trainer.opt_state}),
        }
        if loader_checkpoint is not None:
            args["loader"] = ocp.args.JsonSave(
                dataclasses.asdict(loader_checkpoint))
        self._manager.save(step, args=ocp.args.Composite(**args))
        if wait:
            self._manager.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def restore(self, trainer,
                step: Optional[int] = None) -> Optional[LoaderCheckpoint]:
        """Restore trainer state in place (sharded, straight into HBM);
        returns the saved LoaderCheckpoint if one was stored."""
        ocp = self._ocp
        if step is None:
            step = self._manager.latest_step()
        if step is None:
            raise ValueError("no checkpoint found to restore")
        template = {"params": trainer.params, "opt_state": trainer.opt_state}
        args = {"state": ocp.args.StandardRestore(template)}
        has_loader = "loader" in (
            self._manager.item_metadata(step).keys() or ())
        if has_loader:
            args["loader"] = ocp.args.JsonRestore()
        restored = self._manager.restore(step,
                                         args=ocp.args.Composite(**args))
        # Re-lay restored arrays out for the trainer's mesh. The template's
        # own shardings are not enough: scalars that were jit constants
        # (e.g. a fresh optimizer's step count) carry SingleDeviceSharding,
        # and committing restored arrays to a single device makes the next
        # jitted step fail with incompatible device sets — so anything not
        # already a NamedSharding restores as replicated over the mesh.
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        def target_sharding(x):
            sharding = x.sharding
            if isinstance(sharding, NamedSharding):
                return sharding
            return NamedSharding(trainer.mesh, PartitionSpec())

        state = jax.device_put(restored["state"],
                               jax.tree.map(target_sharding, template))
        trainer.params = state["params"]
        trainer.opt_state = state["opt_state"]
        loader_data = restored.get("loader") if has_loader else None
        if loader_data is None:
            return None
        version = loader_data.get("version", 0)
        if version != FORMAT_VERSION:
            raise ValueError(
                f"loader checkpoint format version {version} != "
                f"{FORMAT_VERSION}")
        return LoaderCheckpoint(**loader_data)

    def close(self) -> None:
        self._manager.close()

    def __enter__(self) -> "TrainStateCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
