"""Synthetic DLRM-style Parquet data generation.

Capability parity with the reference's generator (reference:
data_generation.py:14-111): DLRM-like tabular rows — 17 int64 embedding
columns with fixed cardinalities, 2 small categorical columns, a float64
label, and a globally-unique monotonically increasing ``key`` — written as
``input_data_{i}.parquet.snappy`` with a controlled row-group size. The
``key`` column is what the tests use to prove every row appears exactly
once per shuffled epoch.

TPU-native differences: files are generated in parallel on the host's
thread pool (pyarrow's writer releases the GIL) instead of Ray tasks; the
random fills use the threaded native C++ generator (xoshiro256**) when
available, seeded per (seed, file) so datasets are reproducible — the
reference's ``np.random`` is unseeded. ``max_row_group_skew`` is accepted
but must be 0.0, matching the reference's unimplemented TODO
(reference: data_generation.py:16-17).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from ray_shuffling_data_loader_tpu import executor as ex
from ray_shuffling_data_loader_tpu import native
from ray_shuffling_data_loader_tpu.utils import fileio
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

# Column spec: name -> (low, high, dtype)
# (reference: data_generation.py:74-95).
DATA_SPEC = {
    "embeddings_name0": (0, 2385, np.int64),
    "embeddings_name1": (0, 201, np.int64),
    "embeddings_name2": (0, 201, np.int64),
    "embeddings_name3": (0, 6, np.int64),
    "embeddings_name4": (0, 19, np.int64),
    "embeddings_name5": (0, 1441, np.int64),
    "embeddings_name6": (0, 201, np.int64),
    "embeddings_name7": (0, 22, np.int64),
    "embeddings_name8": (0, 156, np.int64),
    "embeddings_name9": (0, 1216, np.int64),
    "embeddings_name10": (0, 9216, np.int64),
    "embeddings_name11": (0, 88999, np.int64),
    "embeddings_name12": (0, 941792, np.int64),
    "embeddings_name13": (0, 9405, np.int64),
    "embeddings_name14": (0, 83332, np.int64),
    "embeddings_name15": (0, 828767, np.int64),
    "embeddings_name16": (0, 945195, np.int64),
    "one_hot0": (0, 3, np.int64),
    "one_hot1": (0, 50, np.int64),
    "labels": (0, 1, np.float64),
}

EMBEDDING_COLUMNS = [c for c in DATA_SPEC if c.startswith("embeddings")]
ONE_HOT_COLUMNS = [c for c in DATA_SPEC if c.startswith("one_hot")]
FEATURE_COLUMNS = EMBEDDING_COLUMNS + ONE_HOT_COLUMNS
LABEL_COLUMN = "labels"
KEY_COLUMN = "key"


def _fill_int(n: int, low: int, high: int, seed: int) -> np.ndarray:
    if native.available():
        return low + native.fill_random_int64(n, high - low, seed)
    rng = np.random.Generator(np.random.Philox(np.random.SeedSequence(seed)))
    return rng.integers(low, high, size=n, dtype=np.int64)


def _fill_float(n: int, low: float, high: float, seed: int) -> np.ndarray:
    if native.available():
        return low + (high - low) * native.fill_random_double(n, seed)
    rng = np.random.Generator(np.random.Philox(np.random.SeedSequence(seed)))
    return low + (high - low) * rng.random(n)


def generate_row_group(group_index: int, global_row_index: int,
                       num_rows_in_group: int,
                       seed: int = 0) -> pa.Table:
    """One row group as a pyarrow Table (reference: data_generation.py:98-111)."""
    columns = {
        KEY_COLUMN: np.arange(global_row_index,
                              global_row_index + num_rows_in_group,
                              dtype=np.int64),
    }
    for col_index, (col, (low, high, dtype)) in enumerate(DATA_SPEC.items()):
        col_seed = (seed * 1_000_003 + global_row_index) * 53 + col_index
        if np.issubdtype(dtype, np.integer):
            columns[col] = _fill_int(num_rows_in_group, low, high, col_seed)
        else:
            columns[col] = _fill_float(num_rows_in_group, low, high, col_seed)
    return pa.table(columns)


def generate_file(file_index: int, global_row_index: int,
                  num_rows_in_file: int, num_row_groups_per_file: int,
                  data_dir: str, seed: int = 0) -> Tuple[str, int]:
    """Write one Parquet file; returns (path, in-memory byte size)
    (reference: data_generation.py:48-71)."""
    rows_per_group = max(1, num_rows_in_file // num_row_groups_per_file)
    tables = []
    for group_index, group_start in enumerate(
            range(0, num_rows_in_file, rows_per_group)):
        num_rows_in_group = min(rows_per_group,
                                num_rows_in_file - group_start)
        tables.append(
            generate_row_group(group_index, global_row_index + group_start,
                               num_rows_in_group, seed=seed))
    # Row groups of ONE generated file share a single schema by
    # construction (generate_row_group builds them from one spec), so
    # offset-width mixing is impossible here:
    # rsdl-lint: disable=arrow-concat-promote
    table = pa.concat_tables(tables)
    filename = fileio.join(data_dir,
                           f"input_data_{file_index}.parquet.snappy")
    fileio.write_parquet(table, filename, compression="snappy",
                         row_group_size=rows_per_group)
    return filename, table.nbytes


def _file_plan(num_rows: int, num_files: int):
    """(file_index, global_row_index, rows_in_file) covering all rows
    (reference's stride arithmetic, data_generation.py:19-23)."""
    rows_per_file = max(1, num_rows // num_files)
    plan = []
    for file_index, start in enumerate(range(0, num_rows, rows_per_file)):
        plan.append((file_index, start, min(rows_per_file, num_rows - start)))
    return plan


def generate_data(num_rows: int, num_files: int,
                  num_row_groups_per_file: int, max_row_group_skew: float,
                  data_dir: str, seed: int = 0,
                  num_workers: Optional[int] = None
                  ) -> Tuple[List[str], int]:
    """Parallel generation on the host pool (reference: data_generation.py:14-28)."""
    assert max_row_group_skew == 0.0, "row-group skew is not implemented"
    fileio.makedirs(data_dir)
    with ex.Executor(num_workers=num_workers,
                     thread_name_prefix="rsdl-datagen") as pool:
        refs = [
            pool.submit(generate_file, file_index, start, n,
                        num_row_groups_per_file, data_dir, seed)
            for file_index, start, n in _file_plan(num_rows, num_files)
        ]
        results = ex.get(refs)
    filenames, sizes = zip(*results)
    logger.info("generated %d files, %d rows, %.1f MB in-memory",
                len(filenames), num_rows, sum(sizes) / 1e6)
    return list(filenames), sum(sizes)


def generate_data_local(num_rows: int, num_files: int,
                        num_row_groups_per_file: int,
                        max_row_group_skew: float, data_dir: str,
                        seed: int = 0) -> Tuple[List[str], int]:
    """Sequential variant (reference: data_generation.py:31-45)."""
    assert max_row_group_skew == 0.0, "row-group skew is not implemented"
    fileio.makedirs(data_dir)
    results = [
        generate_file(file_index, start, n, num_row_groups_per_file,
                      data_dir, seed)
        for file_index, start, n in _file_plan(num_rows, num_files)
    ]
    filenames, sizes = zip(*results)
    return list(filenames), sum(sizes)
