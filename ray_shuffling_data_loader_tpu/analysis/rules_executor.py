"""Executor / retry-safety rules.

The executor's retry policy (``task_retries``) is only sound for
idempotent tasks. Two contracts from executor.py's prose become
mechanical here:

- a function that consumes one-shot transport messages (``.recv`` —
  each tag is delivered exactly once) must be submitted with
  ``submit_once``; a retrying ``submit`` would re-run it against
  already-consumed tags, block to the recv timeout, and mask the real
  error (see parallel/distributed.py's reduce tasks);
- every random draw in a shuffle task must be keyed by
  ``(seed, epoch, task)``. Global-state RNG (``np.random.*`` module
  functions, stdlib ``random``) makes a retried task produce different
  output than the original — silent data corruption under retries, and
  it breaks replayable epochs (checkpoint/resume).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         dotted_name,
                                                         register)

#: numpy.random module attributes that are seeded CONSTRUCTORS, not
#: global-state draws.
_SEEDED_CONSTRUCTORS = {
    "Generator", "SeedSequence", "Philox", "PCG64", "PCG64DXSM", "MT19937",
    "SFC64", "BitGenerator", "RandomState", "default_rng",
}


def _function_name(func: ast.expr) -> Optional[str]:
    """Resolve a callable argument to a def name this module may hold:
    a bare ``Name`` or a ``self.<method>`` attribute."""
    if isinstance(func, ast.Name):
        return func.id
    if (isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"):
        return func.attr
    return None


@register
class OneShotSubmitRule(Rule):
    id = "oneshot-submit"
    category = "executor-safety"
    description = ("function that consumes one-shot transport messages "
                   "(.recv) submitted via retrying `submit` instead of "
                   "`submit_once`")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        consumers: Set[str] = set(ctx.config.oneshot_functions)
        recv_methods = set(ctx.config.oneshot_recv_methods)
        # Pass 1: functions that directly call a one-shot receive.
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in recv_methods):
                    consumers.add(node.name)
                    break
        if not consumers:
            return
        # Pass 2: retrying submits of those functions.
        for node in ast.walk(tree):
            # (`submit_once` and argument-less `submit()` fall through.)
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "submit" and node.args):
                continue
            target = _function_name(node.args[0])
            if target in consumers:
                yield ctx.violation(
                    self, node,
                    f"`{target}` consumes one-shot transport messages "
                    "(.recv); submit it with `submit_once` — a retrying "
                    "`submit` would re-run it against already-consumed "
                    "tags and block until the recv timeout")


@register
class UnseededRandomRule(Rule):
    id = "unseeded-random"
    category = "executor-safety"
    description = ("global-state RNG draw (np.random.* / random.*) — "
                   "breaks the (seed, epoch, task) determinism contract "
                   "that makes task retries and checkpoint replay safe")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        np_draws = set(ctx.config.unseeded_random_names)
        stdlib_draws = set(ctx.config.stdlib_random_names)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            parts = name.split(".")
            if len(parts) == 3 and parts[0] in ("np", "numpy") \
                    and parts[1] == "random":
                tail = parts[2]
                if tail == "default_rng" and not node.args \
                        and not node.keywords:
                    yield ctx.violation(
                        self, node,
                        "`default_rng()` with no seed draws OS entropy; "
                        "key it by (seed, epoch, task) — e.g. "
                        "`np.random.default_rng(np.random.SeedSequence("
                        "[seed, task_index]))`")
                elif tail in np_draws and tail not in _SEEDED_CONSTRUCTORS:
                    yield ctx.violation(
                        self, node,
                        f"`np.random.{tail}` uses the global RNG; use a "
                        "Generator keyed by (seed, epoch, task) "
                        "(ops/partition.py map_rng/reduce_rng) so retries "
                        "and epoch replay are bit-identical")
            elif len(parts) == 2 and parts[0] == "random" \
                    and parts[1] in stdlib_draws:
                yield ctx.violation(
                    self, node,
                    f"stdlib `random.{parts[1]}` uses global RNG state; "
                    "use a seeded `random.Random(...)` or a numpy "
                    "Generator keyed by (seed, epoch, task)")
