"""Command-line front end for rsdl-lint.

Usage (also via ``tools/rsdl_lint.py`` and format.sh)::

    python -m ray_shuffling_data_loader_tpu.analysis \\
        ray_shuffling_data_loader_tpu tests benchmarks

Exit codes: 0 clean (modulo pragmas/baseline), 1 violations,
2 usage/internal error — the contract format.sh's gate relies on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ray_shuffling_data_loader_tpu.analysis import baseline as baseline_mod
from ray_shuffling_data_loader_tpu.analysis import core

DEFAULT_BASELINE = ".rsdl-lint-baseline.json"


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="rsdl-lint",
        description="Project-invariant static analyzer for the "
                    "ray_shuffling_data_loader_tpu pipeline (lock "
                    "discipline, executor/one-shot safety, JAX host-sync "
                    "hygiene, Arrow schema rules).")
    parser.add_argument("paths", nargs="*", default=["."],
                        help="files or directories to analyze "
                             "(default: .)")
    parser.add_argument("--format", choices=("human", "json"),
                        default="human", help="output format")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help="baseline file of grandfathered findings "
                             f"(default: ./{DEFAULT_BASELINE} when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write current findings to the baseline file "
                             "and exit 0")
    parser.add_argument("--config", default=None, metavar="FILE",
                        help="JSON file overriding analysis.core.Config "
                             "fields")
    parser.add_argument("--select", default=None, metavar="IDS",
                        help="comma-separated rule ids to run exclusively")
    parser.add_argument("--disable", default=None, metavar="IDS",
                        help="comma-separated rule ids to skip")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the registered rules and exit")
    return parser


def _split_ids(value: Optional[str]) -> List[str]:
    if not value:
        return []
    return [v.strip() for v in value.split(",") if v.strip()]


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    registry = core.all_rules()

    if args.list_rules:
        width = max(len(rule_id) for rule_id in registry)
        for rule_id, rule in sorted(registry.items()):
            print(f"{rule_id:<{width}}  [{rule.category}] "
                  f"{rule.description}")
        return core.EXIT_CLEAN

    unknown = [r for r in _split_ids(args.select) + _split_ids(args.disable)
               if r not in registry]
    if unknown:
        print(f"rsdl-lint: unknown rule id(s): {', '.join(unknown)} "
              f"(see --list-rules)", file=sys.stderr)
        return core.EXIT_ERROR

    config = core.Config()
    if args.config:
        try:
            with open(args.config, "r", encoding="utf-8") as f:
                config = core.Config.from_dict(json.load(f))
        except (OSError, ValueError, TypeError) as e:
            print(f"rsdl-lint: bad --config {args.config}: {e}",
                  file=sys.stderr)
            return core.EXIT_ERROR

    selected = set(_split_ids(args.select) or registry)
    selected -= set(_split_ids(args.disable))
    rules = [rule for rule_id, rule in sorted(registry.items())
             if rule_id in selected]

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"rsdl-lint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return core.EXIT_ERROR

    violations, files_checked = core.check_paths(args.paths, config, rules)

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)
    if args.write_baseline:
        path = args.baseline or DEFAULT_BASELINE
        baseline_mod.write_baseline(path, violations)
        print(f"rsdl-lint: wrote {len(violations)} finding(s) to {path}")
        return core.EXIT_CLEAN

    suppressed = 0
    if baseline_path and not args.no_baseline:
        try:
            allowed = baseline_mod.load_baseline(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"rsdl-lint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return core.EXIT_ERROR
        violations, suppressed = baseline_mod.apply_baseline(
            violations, allowed)

    if args.format == "json":
        print(json.dumps({
            "violations": [v.as_dict() for v in violations],
            "files_checked": files_checked,
            "baseline_suppressed": suppressed,
        }, indent=2))
    else:
        for violation in violations:
            print(violation.format())
        summary = (f"rsdl-lint: {len(violations)} finding(s) in "
                   f"{files_checked} file(s)")
        if suppressed:
            summary += f" ({suppressed} baselined)"
        print(summary if violations
              else summary.replace("finding(s)", "findings"))
    return core.EXIT_VIOLATIONS if violations else core.EXIT_CLEAN


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
