"""Command-line front end for rsdl-lint.

Usage (also via ``tools/rsdl_lint.py`` and format.sh)::

    python -m ray_shuffling_data_loader_tpu.analysis \\
        ray_shuffling_data_loader_tpu tests benchmarks

Exit codes: 0 clean (modulo pragmas/baseline), 1 violations,
2 usage/internal error — the contract format.sh's gate relies on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ray_shuffling_data_loader_tpu.analysis import baseline as baseline_mod
from ray_shuffling_data_loader_tpu.analysis import core

DEFAULT_BASELINE = ".rsdl-lint-baseline.json"


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="rsdl-lint",
        description="Project-invariant static analyzer for the "
                    "ray_shuffling_data_loader_tpu pipeline (lock "
                    "discipline, executor/one-shot safety, JAX host-sync "
                    "hygiene, Arrow schema rules).")
    parser.add_argument("paths", nargs="*", default=["."],
                        help="files or directories to analyze "
                             "(default: .)")
    parser.add_argument("--format", choices=("human", "json"),
                        default="human", help="output format")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help="baseline file of grandfathered findings "
                             f"(default: ./{DEFAULT_BASELINE} when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write current findings to the baseline file "
                             "and exit 0")
    parser.add_argument("--config", default=None, metavar="FILE",
                        help="JSON file overriding analysis.core.Config "
                             "fields")
    parser.add_argument("--select", default=None, metavar="IDS",
                        help="comma-separated rule ids to run exclusively")
    parser.add_argument("--disable", default=None, metavar="IDS",
                        help="comma-separated rule ids to skip")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the registered rules and exit")
    parser.add_argument("--concurrency", action="store_true",
                        help="additionally run the whole-program "
                             "concurrency pass (lock-order cycles, "
                             "cross-module unguarded mutations) over the "
                             "library files among PATHS")
    parser.add_argument("--emit-order-graph", default=None, metavar="FILE",
                        help="with --concurrency: write the static "
                             "lock-order graph JSON to FILE")
    parser.add_argument("--locksan-graph", default=None, metavar="FILE",
                        help="with --concurrency: cross-check the static "
                             "graph against a runtime locksan dump "
                             "(RSDL_LOCKSAN=1 test run artifact)")
    return parser


def _split_ids(value: Optional[str]) -> List[str]:
    if not value:
        return []
    return [v.strip() for v in value.split(",") if v.strip()]


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    registry = core.all_rules()
    program_registry = core.program_rules()

    if args.list_rules:
        combined = dict(registry)
        combined.update(program_registry)
        width = max(len(rule_id) for rule_id in combined)
        for rule_id, rule in sorted(combined.items()):
            scope = " (whole-program, --concurrency)" \
                if rule_id in program_registry else ""
            print(f"{rule_id:<{width}}  [{rule.category}] "
                  f"{rule.description}{scope}")
        return core.EXIT_CLEAN

    unknown = [r for r in _split_ids(args.select) + _split_ids(args.disable)
               if r not in registry and r not in program_registry]
    if unknown:
        print(f"rsdl-lint: unknown rule id(s): {', '.join(unknown)} "
              f"(see --list-rules)", file=sys.stderr)
        return core.EXIT_ERROR

    config = core.Config()
    if args.config:
        try:
            with open(args.config, "r", encoding="utf-8") as f:
                config = core.Config.from_dict(json.load(f))
        except (OSError, ValueError, TypeError) as e:
            print(f"rsdl-lint: bad --config {args.config}: {e}",
                  file=sys.stderr)
            return core.EXIT_ERROR

    default_ids = list(registry) + (list(program_registry)
                                    if args.concurrency else [])
    selected = set(_split_ids(args.select) or default_ids)
    selected -= set(_split_ids(args.disable))
    rules = [rule for rule_id, rule in sorted(registry.items())
             if rule_id in selected]

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"rsdl-lint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return core.EXIT_ERROR

    violations, files_checked = core.check_paths(args.paths, config, rules)

    if args.concurrency:
        locksan_graph = None
        if args.locksan_graph:
            try:
                with open(args.locksan_graph, "r", encoding="utf-8") as f:
                    locksan_graph = json.load(f)
            except (OSError, ValueError) as e:
                print(f"rsdl-lint: bad --locksan-graph "
                      f"{args.locksan_graph}: {e}", file=sys.stderr)
                return core.EXIT_ERROR
        program_rules = [rule for rule_id, rule
                         in sorted(program_registry.items())
                         if rule_id in selected]
        program_violations, analysis = core.check_program_paths(
            args.paths, config, program_rules,
            locksan_graph=locksan_graph)
        violations = sorted(
            violations + program_violations,
            key=lambda v: (v.path, v.line, v.col, v.rule))
        if args.emit_order_graph:
            graph = analysis.static_graph()
            with open(args.emit_order_graph, "w", encoding="utf-8") as f:
                json.dump(graph, f, indent=2, sort_keys=True)
                f.write("\n")
    elif args.emit_order_graph or args.locksan_graph:
        print("rsdl-lint: --emit-order-graph/--locksan-graph require "
              "--concurrency", file=sys.stderr)
        return core.EXIT_ERROR

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)
    if args.write_baseline:
        path = args.baseline or DEFAULT_BASELINE
        baseline_mod.write_baseline(path, violations)
        print(f"rsdl-lint: wrote {len(violations)} finding(s) to {path}")
        return core.EXIT_CLEAN

    suppressed = 0
    if baseline_path and not args.no_baseline:
        try:
            allowed = baseline_mod.load_baseline(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"rsdl-lint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return core.EXIT_ERROR
        violations, suppressed = baseline_mod.apply_baseline(
            violations, allowed)

    if args.format == "json":
        print(json.dumps({
            "violations": [v.as_dict() for v in violations],
            "files_checked": files_checked,
            "baseline_suppressed": suppressed,
        }, indent=2))
    else:
        for violation in violations:
            print(violation.format())
        summary = (f"rsdl-lint: {len(violations)} finding(s) in "
                   f"{files_checked} file(s)")
        if suppressed:
            summary += f" ({suppressed} baselined)"
        print(summary if violations
              else summary.replace("finding(s)", "findings"))
    return core.EXIT_VIOLATIONS if violations else core.EXIT_CLEAN


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
