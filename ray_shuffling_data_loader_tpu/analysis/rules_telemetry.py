"""Telemetry-discipline rules.

The causal tracing layer added a manual span API
(``telemetry.span_begin`` / ``telemetry.span_end``) for measurements a
``with`` block cannot express — a wait spanning loop iterations, a
handoff between threads. Manual spans revive the classic paired-call
bug class the ``with`` form made impossible: an exit path that skips
the close leaves the thread's active-kind registry pointing at a dead
span (the sampling profiler then bills every later sample to it) and
loses the duration event entirely — the trace silently under-reports
exactly the code path that failed, which is when the trace matters.

``span-unbalanced`` pins the only safe shape: every ``span_begin``
must be paired with a ``span_end`` that runs on ALL exit paths, i.e.
inside a ``finally`` block of the same function (``span_end(None)`` is
a no-op by contract, so the ``finally`` form needs no enabled-guard).
A ``span_begin`` whose token is immediately returned is exempt — that
is a deliberate helper handing the obligation to its caller.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         dotted_name,
                                                         register)

_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)


def _scope_nodes(func: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's OWN body (nested defs/classes own their spans)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, _SCOPE_BARRIERS):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_call_tail(node: ast.AST, tail: str) -> bool:
    return (isinstance(node, ast.Call)
            and dotted_name(node.func).rsplit(".", 1)[-1] == tail)


@register
class SpanUnbalancedRule(Rule):
    id = "span-unbalanced"
    category = "telemetry"
    description = ("telemetry `span_begin` without a `span_end` on all "
                   "paths: the close must sit in a `finally` (or the "
                   "token be returned to the caller), else a raising "
                   "exit loses the span and poisons the profiler's "
                   "active-kind registry")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        for func in ast.walk(tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            begins = [n for n in _scope_nodes(func)
                      if _is_call_tail(n, "span_begin")]
            if not begins:
                continue
            # Tokens handed straight to the caller: the obligation
            # moves with them.
            returned = {
                id(stmt.value) for stmt in _scope_nodes(func)
                if isinstance(stmt, ast.Return) and stmt.value is not None
            }
            has_end = any(_is_call_tail(n, "span_end")
                          for n in _scope_nodes(func))
            end_in_finally = False
            for node in _scope_nodes(func):
                if not isinstance(node, ast.Try) or not node.finalbody:
                    continue
                for stmt in node.finalbody:
                    if any(_is_call_tail(n, "span_end")
                           for n in ast.walk(stmt)):
                        end_in_finally = True
                        break
                if end_in_finally:
                    break
            for begin in begins:
                if id(begin) in returned:
                    continue
                if not has_end:
                    yield ctx.violation(
                        self, begin,
                        "span_begin has no matching span_end in "
                        f"`{func.name}` — the span never closes")
                elif not end_in_finally:
                    yield ctx.violation(
                        self, begin,
                        "span_end is not in a `finally` block in "
                        f"`{func.name}` — a raising exit path loses "
                        "the span (span_end(None) is a no-op; the "
                        "finally form needs no guard)")
