"""``python -m ray_shuffling_data_loader_tpu.analysis`` entry point."""

import sys

from ray_shuffling_data_loader_tpu.analysis.cli import main

sys.exit(main())
