"""Whole-program concurrency rules (the ``--concurrency`` pass).

Two rules over :mod:`.locksets`' interprocedural results:

``inconsistent-lock-order``
    The static lock-order graph (acquire B while holding A => edge
    A->B) contains a cycle: two code paths take the same locks in
    opposite orders, so two threads can deadlock. The finding anchors
    at one acquisition site of the cycle and names every edge with its
    ``file:line`` witness so both chains are readable from the one
    message. When a locksan dump is supplied (``--locksan-graph``),
    three cross-check findings join in: an *order-relevant* dynamic
    edge the static graph lacks (the destination lock has outgoing
    edges in the merged graph, so the gap could extend a chain — the
    static pass under-resolved a call path; edges into pure leaf
    locks can never close a cycle and are recorded as benign, not
    flagged), a cycle that appears only once runtime edges merge into
    the static graph (each view acyclic alone, deadlock together),
    and a static cycle every edge of which was actually observed at
    runtime (no longer "potential": a hard failure).

``unguarded-shared-mutation``
    An attribute/global written under a specific lock at >=
    ``concurrency_min_guarded_sites`` sites is taken to be guarded by
    convention; a site that writes it with no lock held — after
    crediting locks the caller provably holds (the entry-held
    intersection) — is flagged. Covers what the per-file lock-mutation
    rule structurally cannot: writes from *other* modules/classes,
    module-global mutations, and container-mutating calls
    (``.append``/``.pop``/``.update``/...).

Both follow the house rule contract: real ``path:line`` anchors, so
``# rsdl-lint: disable=<rule>`` pragmas and the baseline file apply
unchanged.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, Iterator, List, Optional, Tuple

from ray_shuffling_data_loader_tpu.analysis import core, locksets


def _split_key(key: str) -> Tuple[str, int]:
    path, _, line = key.rpartition(":")
    try:
        return path, int(line)
    except ValueError:
        return key, 1


def _anchor(rule: core.ProgramRule, program, path: str, line: int,
            message: str) -> core.Violation:
    mod = program.modules_by_path.get(path)
    snippet = ""
    if mod is not None:
        lines = mod.source.splitlines()
        if 1 <= line <= len(lines):
            snippet = lines[line - 1].strip()
    return core.Violation(rule=rule.id, path=path, line=line, col=0,
                          message=message, snippet=snippet)


def _owner(analysis: locksets.LockAnalysis, key: str) -> str:
    decl = analysis.decls.get(key)
    return decl.owner if decl is not None else key


@core.register_program
class InconsistentLockOrderRule(core.ProgramRule):
    id = "inconsistent-lock-order"
    category = "concurrency"
    description = ("cycle in the whole-program lock-order graph (or a "
                   "runtime-observed acquisition edge the static graph "
                   "is missing): two paths can deadlock")

    def check_program(self, program, analysis: locksets.LockAnalysis,
                      config: core.Config,
                      locksan_graph: Optional[dict] = None
                      ) -> Iterator[core.Violation]:
        confirmed_edge_sets: List[set] = []
        if locksan_graph is not None:
            report = locksets.crosscheck(analysis.static_graph(),
                                         locksan_graph)
            for cycle_edges in report["confirmed_cycles"]:
                confirmed_edge_sets.append({tuple(e) for e in cycle_edges})
            for cycle_edges in report["union_cycles"]:
                # A cycle neither view shows alone: static edges plus
                # runtime-observed ones close a loop. Anchor at the
                # first edge's src construction site.
                chains = "; ".join(
                    f"`{_owner(analysis, a)}` -> `{_owner(analysis, b)}`"
                    for a, b in cycle_edges)
                path, line = _split_key(cycle_edges[0][0])
                yield _anchor(
                    self, program, path, line,
                    "DEADLOCK (static + runtime edges combined): "
                    f"lock-order cycle — {chains}; the static graph "
                    "alone is acyclic but runtime-observed "
                    "acquisitions close the loop — make every path "
                    "acquire these locks in one global order")
            for edge in report["missing_edges"]:
                # Anchor at the SRC lock's construction site: the gap
                # is in what its critical section calls, so that is
                # where a pragma justifying the opaque call belongs
                # (and one pragma covers every edge out of that lock).
                path, line = _split_key(edge["src"])
                yield _anchor(
                    self, program, path, line,
                    f"runtime lock sanitizer observed "
                    f"`{_owner(analysis, edge['src'])}` -> "
                    f"`{_owner(analysis, edge['dst'])}` "
                    "(acquired-while-held), but the static order graph "
                    "has no such edge; the static pass is missing a "
                    "call path — teach locksets.py about it or record "
                    "the ordering intent here")
        for cycle in analysis.cycles():
            if not cycle:
                continue
            edge_keys = {(e["src"], e["dst"]) for e in cycle}
            dynamic = any(edge_keys <= s for s in confirmed_edge_sets)
            chains = "; ".join(
                f"`{_owner(analysis, e['src'])}` -> "
                f"`{_owner(analysis, e['dst'])}` at {e['where']} "
                f"in {e['func']}" + (f" ({e['via']})" if e["via"] else "")
                for e in cycle)
            path, line = _split_key(cycle[0]["where"])
            severity = ("DEADLOCK CONFIRMED at runtime by locksan"
                        if dynamic else "potential deadlock")
            yield _anchor(
                self, program, path, line,
                f"{severity}: lock-order cycle — {chains}; make every "
                "path acquire these locks in one global order (or drop "
                "one lock from the nested scope)")


@core.register_program
class UnguardedSharedMutationRule(core.ProgramRule):
    id = "unguarded-shared-mutation"
    category = "concurrency"
    description = ("attribute/global is written under a lock at several "
                   "sites but mutated bare here (callers' held locks "
                   "credited interprocedurally)")

    def check_program(self, program, analysis: locksets.LockAnalysis,
                      config: core.Config,
                      locksan_graph: Optional[dict] = None
                      ) -> Iterator[core.Violation]:
        by_target: Dict[str, List[Tuple[locksets.Write, frozenset]]] = {}
        for conc in analysis.funcs.values():
            for write in conc.writes:
                held = frozenset(analysis.effective_held(conc, write.held))
                by_target.setdefault(write.target, []).append((write, held))
        for target, writes in sorted(by_target.items()):
            guarded = [(w, h) for w, h in writes if h and not w.setup]
            bare = [(w, h) for w, h in writes if not h and not w.setup]
            if not bare or \
                    len(guarded) < config.concurrency_min_guarded_sites:
                continue
            lock_votes = Counter()
            for _, held in guarded:
                for key in held:
                    lock_votes[key] += 1
            dominant, votes = lock_votes.most_common(1)[0]
            if votes < config.concurrency_min_guarded_sites:
                continue
            example = guarded[0][0]
            example_path = analysis.funcs[example.func].info.module.path
            for write, _ in bare:
                mod = analysis.funcs[write.func].info.module
                verb = ("mutated in place" if write.kind == "mutate"
                        else "written")
                yield _anchor(
                    self, program, mod.path, write.line,
                    f"`{target.partition(':')[2]}` is written under "
                    f"`{_owner(analysis, dominant)}` at {votes} site(s) "
                    f"(e.g. {example_path}:{example.line}) but {verb} "
                    f"here in {write.func.partition(':')[2]} with no "
                    "lock held; take the lock, or pragma with the "
                    "ownership argument if this access is "
                    "single-thread-confined")
