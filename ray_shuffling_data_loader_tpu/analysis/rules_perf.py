"""Hot-path copy-discipline rules.

The shuffle's throughput story is built on zero-copy Arrow handoff: map
outputs are index plans over mmap-able tables, the fused reduce gathers
straight from source buffers, and the process backend hands whole tables
across processes as shared-memory segments. One careless conversion in a
hot path silently re-materializes the very bytes the design avoids
copying — and the regression shows up only as a throughput drift nobody
can attribute (the r03 -> r05 ingest regression was exactly such a
drift).

``copy-in-hot-path`` pins the discipline in the three hot-path modules
(``shuffle.py``, ``dataset.py``, ``jax_dataset.py``):

- ``.astype(...)`` without ``copy=False`` — NumPy copies even when the
  dtype already matches; ``copy=False`` makes the no-op case free and
  documents that a copy is conditional, not assumed.
- ``.to_numpy(zero_copy_only=False)`` — permission to copy on every
  call. Legitimate only at the blessed conversion sites whose results
  are cached (``_table_numpy_columns`` behind ``MapShard``'s per-shard
  cache, the device-conversion boundary in ``jax_dataset``); those carry
  a pragma with their justification.
- ``.combine_chunks()`` — concatenates every chunk into fresh buffers.
  Blessed only where the copy is paid ONCE and amortized (the decode
  path right before a table enters a cross-epoch cache); per-call sites
  must operate on the chunked form instead.

Escape hatch: ``# rsdl-lint: disable=copy-in-hot-path`` on the line (or
the line above), with the justification in prose next to it — the
pragma IS the blessing mechanism.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         get_keyword,
                                                         is_constant,
                                                         register)

#: Repo-relative path globs of the hot-path modules the rule covers.
#: (fnmatch ``*`` crosses directories, so ``*/dataset.py`` matches the
#: module at any depth but NOT ``jax_dataset.py`` — no ``/`` precedes
#: its ``dataset.py`` suffix.)
HOT_PATH_GLOBS = ("*/shuffle.py", "shuffle.py",
                  "*/dataset.py", "dataset.py",
                  "*/jax_dataset.py", "jax_dataset.py")


@register
class CopyInHotPathRule(Rule):
    id = "copy-in-hot-path"
    category = "perf"
    description = ("flag copying conversions (.astype without copy=False, "
                   ".to_numpy(zero_copy_only=False), .combine_chunks()) in "
                   "the shuffle/dataset hot-path modules outside blessed "
                   "cached sites")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        if not ctx.path_matches(HOT_PATH_GLOBS):
            return
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            method = node.func.attr
            if method == "astype":
                copy_kw = get_keyword(node, "copy")
                if not is_constant(copy_kw, False):
                    yield ctx.violation(
                        self, node,
                        "hot-path .astype() without copy=False copies even "
                        "when the dtype already matches; pass copy=False "
                        "(or bless the site with a pragma + justification)")
            elif method == "to_numpy":
                zco = get_keyword(node, "zero_copy_only")
                if is_constant(zco, False):
                    yield ctx.violation(
                        self, node,
                        "hot-path to_numpy(zero_copy_only=False) permits a "
                        "copy on every call; only blessed cached conversion "
                        "sites may carry it (pragma + justification)")
            elif method == "combine_chunks" and not node.args \
                    and not node.keywords:
                yield ctx.violation(
                    self, node,
                    "hot-path combine_chunks() concatenates every chunk "
                    "into fresh buffers; bless only once-per-cache-entry "
                    "sites (pragma + justification) — per-call sites must "
                    "stay chunked")
