"""Hot-path copy-discipline rules.

The shuffle's throughput story is built on zero-copy Arrow handoff: map
outputs are index plans over mmap-able tables, the fused reduce gathers
straight from source buffers, and the process backend hands whole tables
across processes as shared-memory segments. One careless conversion in a
hot path silently re-materializes the very bytes the design avoids
copying — and the regression shows up only as a throughput drift nobody
can attribute (the r03 -> r05 ingest regression was exactly such a
drift).

``copy-in-hot-path`` pins the discipline in the three hot-path modules
(``shuffle.py``, ``dataset.py``, ``jax_dataset.py``):

- ``.astype(...)`` without ``copy=False`` — NumPy copies even when the
  dtype already matches; ``copy=False`` makes the no-op case free and
  documents that a copy is conditional, not assumed.
- ``.to_numpy(zero_copy_only=False)`` — permission to copy on every
  call. Legitimate only at the blessed conversion sites whose results
  are cached (``_table_numpy_columns`` behind ``MapShard``'s per-shard
  cache, the device-conversion boundary in ``jax_dataset``); those carry
  a pragma with their justification.
- ``.combine_chunks()`` — concatenates every chunk into fresh buffers.
  Blessed only where the copy is paid ONCE and amortized (the decode
  path right before a table enters a cross-epoch cache); per-call sites
  must operate on the chunked form instead.

``sendall-in-loop`` pins the wire-syscall discipline that made the
sendmsg scatter-gather path worth building: a ``.sendall`` call inside a
``for`` loop writes one syscall per buffer, when the loop is almost
always walking a collection of frames/chunks that could gather into a
single ``sendmsg`` (``multiqueue_service._sendmsg_all``). ``while``
protocol loops (heartbeats, request/response) are deliberately excused —
one logical message per iteration is not a gatherable batch. The legacy
sequential arm kept for ``RSDL_QUEUE_SENDMSG=0`` carries pragmas: it IS
the fallback the rule exists to keep rare.

Escape hatch: ``# rsdl-lint: disable=copy-in-hot-path`` on the line (or
the line above), with the justification in prose next to it — the
pragma IS the blessing mechanism.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         get_keyword,
                                                         is_constant,
                                                         register)

#: Repo-relative path globs of the hot-path modules the rule covers.
#: (fnmatch ``*`` crosses directories, so ``*/dataset.py`` matches the
#: module at any depth but NOT ``jax_dataset.py`` — no ``/`` precedes
#: its ``dataset.py`` suffix.)
HOT_PATH_GLOBS = ("*/shuffle.py", "shuffle.py",
                  "*/dataset.py", "dataset.py",
                  "*/jax_dataset.py", "jax_dataset.py")


@register
class CopyInHotPathRule(Rule):
    id = "copy-in-hot-path"
    category = "perf"
    description = ("flag copying conversions (.astype without copy=False, "
                   ".to_numpy(zero_copy_only=False), .combine_chunks()) in "
                   "the shuffle/dataset hot-path modules outside blessed "
                   "cached sites")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        if not ctx.path_matches(HOT_PATH_GLOBS):
            return
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            method = node.func.attr
            if method == "astype":
                copy_kw = get_keyword(node, "copy")
                if not is_constant(copy_kw, False):
                    yield ctx.violation(
                        self, node,
                        "hot-path .astype() without copy=False copies even "
                        "when the dtype already matches; pass copy=False "
                        "(or bless the site with a pragma + justification)")
            elif method == "to_numpy":
                zco = get_keyword(node, "zero_copy_only")
                if is_constant(zco, False):
                    yield ctx.violation(
                        self, node,
                        "hot-path to_numpy(zero_copy_only=False) permits a "
                        "copy on every call; only blessed cached conversion "
                        "sites may carry it (pragma + justification)")
            elif method == "combine_chunks" and not node.args \
                    and not node.keywords:
                yield ctx.violation(
                    self, node,
                    "hot-path combine_chunks() concatenates every chunk "
                    "into fresh buffers; bless only once-per-cache-entry "
                    "sites (pragma + justification) — per-call sites must "
                    "stay chunked")


@register
class SendallInLoopRule(Rule):
    id = "sendall-in-loop"
    category = "perf"
    description = ("flag `.sendall(...)` inside a for loop — one syscall "
                   "per buffer where a sendmsg scatter-gather batch would "
                   "write the whole collection in one; while-loop protocol "
                   "exchanges are excused")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        seen = set()
        for loop in ast.walk(tree):
            if not isinstance(loop, ast.For):
                continue
            for node in ast.walk(loop):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "sendall"
                        and id(node) not in seen):
                    seen.add(id(node))
                    yield ctx.violation(
                        self, node,
                        "`.sendall` inside a for loop pays one syscall per "
                        "buffer; gather the iteration's buffers and write "
                        "them with one scatter-gather sendmsg "
                        "(multiqueue_service._sendmsg_all), or bless a "
                        "deliberate sequential fallback with a pragma + "
                        "justification")


def _is_bytes_init(value: ast.expr) -> bool:
    """``b"..."`` literal, or a ``bytes(...)`` call — the accumulator
    shapes that make ``buf += chunk`` provably a bytes concatenation."""
    if isinstance(value, ast.Constant) and isinstance(value.value, bytes):
        return True
    return (isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id == "bytes")


@register
class BytesConcatInLoopRule(Rule):
    id = "bytes-concat-in-loop"
    category = "perf"
    description = ("flag `buf += chunk` / `buf = buf + chunk` inside a "
                   "loop when buf was initialized from a bytes literal "
                   "or bytes() — quadratic on large frames; accumulate "
                   "into a bytearray or collect chunks and b\"\".join")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        scopes = [tree] + [n for n in ast.walk(tree)
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
        for scope in scopes:
            # Accumulators PROVABLY bytes: assigned from a bytes literal
            # or bytes() anywhere in this scope (not a nested function).
            bytes_vars = set()
            for node in self._scope_walk(scope):
                if isinstance(node, ast.Assign) \
                        and _is_bytes_init(node.value):
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            bytes_vars.add(target.id)
            if not bytes_vars:
                continue
            for loop in self._scope_walk(scope):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                for node in ast.walk(loop):
                    name = self._concat_target(node)
                    if name in bytes_vars:
                        yield ctx.violation(
                            self, node,
                            f"`{name} += chunk` on a bytes accumulator "
                            "inside a loop re-copies every byte "
                            "accumulated so far (quadratic on large "
                            "frames); accumulate into a bytearray, or "
                            "collect chunks in a list and b\"\".join "
                            "once")

    @staticmethod
    def _scope_walk(scope: ast.AST) -> Iterator[ast.AST]:
        """ast.walk that does not descend into nested function scopes
        (their accumulators are their own scope's business)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _concat_target(node: ast.AST) -> Optional[str]:
        """The accumulator name of ``x += y`` / ``x = x + y`` (Add only),
        else None."""
        if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add) \
                and isinstance(node.target, ast.Name):
            return node.target.id
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.BinOp) \
                and isinstance(node.value.op, ast.Add):
            name = node.targets[0].id
            for operand in (node.value.left, node.value.right):
                if isinstance(operand, ast.Name) and operand.id == name:
                    return name
        return None
