"""Runtime liveness/flow-control rules.

The epoch-launch budget wait once flushed cycle-stuck table wrappers by
calling ``gc.collect()`` every second inside its poll loop. That pattern
is now structurally banned: releases are event-driven
(``runtime/release.py`` — the ledger notifies waiters on every decref),
and a ``gc.collect()`` inside a wait/poll loop is both a symptom (some
path still leaks frees through reference cycles instead of breaking
them) and a cost (a full-heap cycle collection per poll tick,
process-wide, while holding up the very pipeline it's trying to help).
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         dotted_name,
                                                         register)

#: Call tails that mark a `for` loop as a wait/poll loop (any `while`
#: loop qualifies by itself: re-checking a condition is what it does).
_WAIT_TAILS = {"sleep", "wait", "wait_for_release", "wait_while"}


def _gc_collect_names(tree: ast.Module) -> Set[str]:
    """Names that resolve to ``gc.collect`` in this module: dotted forms
    for ``import gc`` / ``import gc as _gc``, plus bare names bound by
    ``from gc import collect [as name]``."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "gc":
                    names.add(f"{alias.asname or alias.name}.collect")
        elif isinstance(node, ast.ImportFrom) and node.module == "gc":
            for alias in node.names:
                if alias.name == "collect":
                    names.add(alias.asname or alias.name)
    return names


def _is_wait_loop(loop: ast.AST) -> bool:
    if isinstance(loop, ast.While):
        return True
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            tail = dotted_name(node.func).rsplit(".", 1)[-1]
            if tail in _WAIT_TAILS:
                return True
    return False


@register
class GcCollectInWaitRule(Rule):
    id = "gc-collect-in-wait"
    category = "runtime"
    description = ("`gc.collect()` inside a wait/poll loop — releases are "
                   "event-driven (runtime/release.py); break the reference "
                   "cycle at its source instead of sweeping the whole heap "
                   "per poll tick")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        collect_names = _gc_collect_names(tree)
        # `import gc` inside a function body is also common; cover the
        # canonical dotted form even without a visible top-level import.
        collect_names.add("gc.collect")
        seen: Set[int] = set()
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.While, ast.For, ast.AsyncFor)):
                continue
            if not _is_wait_loop(loop):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                if dotted_name(node.func) in collect_names:
                    seen.add(id(node))
                    yield ctx.violation(
                        self, node,
                        "`gc.collect()` inside a wait/poll loop flushes "
                        "cycle-stuck frees by sweeping the whole heap every "
                        "tick; releases are event-driven — wake on "
                        "runtime.release events and break the reference "
                        "cycle that delays the free at its source")
