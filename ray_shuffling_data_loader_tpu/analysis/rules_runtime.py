"""Runtime liveness/flow-control rules.

The epoch-launch budget wait once flushed cycle-stuck table wrappers by
calling ``gc.collect()`` every second inside its poll loop. That pattern
is now structurally banned: releases are event-driven
(``runtime/release.py`` — the ledger notifies waiters on every decref),
and a ``gc.collect()`` inside a wait/poll loop is both a symptom (some
path still leaks frees through reference cycles instead of breaking
them) and a cost (a full-heap cycle collection per poll tick,
process-wide, while holding up the very pipeline it's trying to help).

Same story for ad-hoc retry loops (``unbounded-retry``): the repo
accumulated four independent retry idioms before ``runtime/retry.py``
unified them; a ``while True`` retry loop has no attempt budget and no
deadline (a permanently-failing resource hangs the pipeline forever),
and a fixed-interval ``time.sleep(N)`` retry re-hits a recovering
resource in lockstep with every other retrier. Both shapes must route
through :class:`runtime.retry.RetryPolicy` (bounded attempts,
decorrelated jitter, deadline, fault-stats accounting).

``socket-op-no-timeout`` guards the cross-process plane: a blocking
``recv``/``accept``/``connect`` on a socket with no timeout configured
waits forever on a wedged peer — past the watchdog, past the lease
sweeper, unkillable except by process death. Every socket must either
be created with a timeout (``create_connection(addr, timeout=...)``)
or have ``settimeout`` called on it; ``settimeout(None)`` counts as
configured — an *explicit* infinite wait is a reviewed decision, the
silent default is the bug (PR-5 satellite: queue timeouts now resolve
through ``RSDL_QUEUE_TIMEOUT``).
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         dotted_name,
                                                         register)

#: Call tails that mark a `for` loop as a wait/poll loop (any `while`
#: loop qualifies by itself: re-checking a condition is what it does).
_WAIT_TAILS = {"sleep", "wait", "wait_for_release", "wait_while"}


def _gc_collect_names(tree: ast.Module) -> Set[str]:
    """Names that resolve to ``gc.collect`` in this module: dotted forms
    for ``import gc`` / ``import gc as _gc``, plus bare names bound by
    ``from gc import collect [as name]``."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "gc":
                    names.add(f"{alias.asname or alias.name}.collect")
        elif isinstance(node, ast.ImportFrom) and node.module == "gc":
            for alias in node.names:
                if alias.name == "collect":
                    names.add(alias.asname or alias.name)
    return names


def _is_wait_loop(loop: ast.AST) -> bool:
    if isinstance(loop, ast.While):
        return True
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            tail = dotted_name(node.func).rsplit(".", 1)[-1]
            if tail in _WAIT_TAILS:
                return True
    return False


@register
class GcCollectInWaitRule(Rule):
    id = "gc-collect-in-wait"
    category = "runtime"
    description = ("`gc.collect()` inside a wait/poll loop — releases are "
                   "event-driven (runtime/release.py); break the reference "
                   "cycle at its source instead of sweeping the whole heap "
                   "per poll tick")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        collect_names = _gc_collect_names(tree)
        # `import gc` inside a function body is also common; cover the
        # canonical dotted form even without a visible top-level import.
        collect_names.add("gc.collect")
        seen: Set[int] = set()
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.While, ast.For, ast.AsyncFor)):
                continue
            if not _is_wait_loop(loop):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                if dotted_name(node.func) in collect_names:
                    seen.add(id(node))
                    yield ctx.violation(
                        self, node,
                        "`gc.collect()` inside a wait/poll loop flushes "
                        "cycle-stuck frees by sweeping the whole heap every "
                        "tick; releases are event-driven — wake on "
                        "runtime.release events and break the reference "
                        "cycle that delays the free at its source")


def _is_while_true(loop: ast.AST) -> bool:
    return (isinstance(loop, ast.While)
            and isinstance(loop.test, ast.Constant)
            and loop.test.value is True)


def _sleep_calls(loop: ast.AST):
    for node in ast.walk(loop):
        if isinstance(node, ast.Call) \
                and dotted_name(node.func).rsplit(".", 1)[-1] == "sleep":
            yield node


def _is_retry_loop(loop: ast.AST) -> bool:
    """A loop whose body is a try whose except handler STAYS in the loop
    (re-attempting the failed work). A handler that exits — ``return``,
    ``raise``, ``break`` — is failure propagation, not a retry; a try
    buried inside nested statements is stream processing (e.g. a monitor
    servicing many watches), not a retried operation."""
    for stmt in loop.body:
        if not isinstance(stmt, ast.Try):
            continue
        for handler in stmt.handlers:
            last = handler.body[-1] if handler.body else None
            if not isinstance(last, (ast.Return, ast.Raise, ast.Break)):
                return True
    return False


@register
class UnboundedRetryRule(Rule):
    id = "unbounded-retry"
    category = "runtime"
    description = ("`while True` retry loops and fixed-interval "
                   "`time.sleep(N)` retry loops — retries must route "
                   "through runtime.retry.RetryPolicy (bounded attempts, "
                   "decorrelated jitter, deadline)")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        # RetryPolicy's own engine is the one sanctioned retry loop.
        if ctx.path.endswith("runtime/retry.py"):
            return
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.While, ast.For, ast.AsyncFor)):
                continue
            if not _is_retry_loop(loop):
                continue  # no failure absorbed in-loop: not a retry
            if _is_while_true(loop):
                yield ctx.violation(
                    self, loop,
                    "`while True` retry loop has no attempt budget or "
                    "deadline — a permanently-failing resource hangs here "
                    "forever; route the call through "
                    "runtime.retry.RetryPolicy")
                continue
            for call in _sleep_calls(loop):
                if call.args and isinstance(call.args[0], ast.Constant):
                    yield ctx.violation(
                        self, call,
                        "fixed-interval sleep in a retry loop re-hits a "
                        "recovering resource in lockstep with every other "
                        "retrier; use runtime.retry.RetryPolicy "
                        "(exponential backoff with decorrelated jitter)")


#: Socket methods that block indefinitely without a configured timeout.
_BLOCKING_SOCKET_OPS = {"recv", "recv_into", "recvfrom", "accept",
                        "connect"}
#: Constructors whose result is a socket object.
_SOCKET_CONSTRUCTORS = {"socket.socket", "socket.create_connection"}


def _call_has_timeout(call: ast.Call) -> bool:
    """``create_connection(addr, timeout)`` / ``timeout=`` counts as a
    timeout configured at construction."""
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    tail = dotted_name(call.func).rsplit(".", 1)[-1]
    return tail == "create_connection" and len(call.args) >= 2


def _target_names(target: ast.AST):
    """Dotted names bound by an assignment target (plain or the first
    element of a tuple unpack — ``conn, peer = listener.accept()``)."""
    if isinstance(target, (ast.Name, ast.Attribute)):
        name = dotted_name(target)
        if "?" not in name:
            yield name
    elif isinstance(target, (ast.Tuple, ast.List)) and target.elts:
        yield from _target_names(target.elts[0])


@register
class SocketOpNoTimeoutRule(Rule):
    id = "socket-op-no-timeout"
    category = "runtime"
    description = ("blocking socket `recv`/`accept`/`connect` on a socket "
                   "with no timeout configured — waits forever on a wedged "
                   "peer, past the watchdog and the lease sweeper; call "
                   "`settimeout` (policy key RSDL_QUEUE_TIMEOUT for the "
                   "queue plane) or create with `timeout=`")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        tracked: Set[str] = set()      # names known to hold sockets
        configured: Set[str] = set()   # ... with a timeout configured
        # Pass 1: collect socket bindings and settimeout calls (order-
        # independent on purpose: configuration in __init__ covers ops
        # in methods defined earlier in the class body).
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                call = node.value
                callee = dotted_name(call.func)
                is_ctor = callee in _SOCKET_CONSTRUCTORS
                is_accept = callee.rsplit(".", 1)[-1] == "accept"
                if not (is_ctor or is_accept):
                    continue
                for name in (n for t in node.targets
                             for n in _target_names(t)):
                    tracked.add(name)
                    if is_ctor and _call_has_timeout(call):
                        configured.add(name)
            elif isinstance(node, ast.Call):
                callee = dotted_name(node.func)
                if callee.rsplit(".", 1)[-1] == "settimeout":
                    configured.add(callee.rsplit(".settimeout", 1)[0])
        # Pass 2: flag blocking ops on tracked-but-unconfigured names.
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            base, _, op = callee.rpartition(".")
            if op not in _BLOCKING_SOCKET_OPS or not base:
                continue
            if base in tracked and base not in configured:
                yield ctx.violation(
                    self, node,
                    f"blocking `{op}` on socket `{base}` with no timeout "
                    f"configured waits forever on a wedged peer (past the "
                    f"watchdog and the lease sweeper); call "
                    f"`{base}.settimeout(...)` — policy-resolved, e.g. "
                    f"RSDL_QUEUE_TIMEOUT — or construct it with "
                    f"`timeout=`; `settimeout(None)` is accepted as an "
                    f"explicit, reviewed infinite wait")
