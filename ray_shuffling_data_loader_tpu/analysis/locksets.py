"""Lockset inference and static lock-order analysis for rsdl-lint.

Built on :mod:`.callgraph`, this module answers two whole-program
questions the per-file rules cannot:

1. **Which lock guards which state?** Every ``threading.Lock`` /
   ``RLock`` / ``Condition`` constructed in the package becomes a
   :class:`LockDecl` keyed by its construction site (``path:line`` —
   the same key the runtime sanitizer records, so the static and
   dynamic graphs are directly comparable). Every attribute/global
   write is recorded with the set of locks held at that point,
   including locks a *caller* provably holds (private methods inherit
   the intersection of their call sites' held-sets). State written
   under a lock at several sites but bare at another is an
   ``unguarded-shared-mutation`` candidate.

2. **Is the acquisition order consistent?** Acquiring B while holding
   A adds the edge A->B to the lock-order graph — lexically (nested
   ``with``), and interprocedurally (calling a function that
   transitively acquires B while holding A). A cycle in that graph is
   a potential deadlock; the report names every edge of the cycle
   with its ``file:line`` witness.

Both analyses are deliberately under-approximate where resolution is
uncertain (an unresolvable receiver contributes nothing), and
self-edges are not treated as cycles: one static construction site can
serve many runtime instances (per-queue ``_QueueState.lock``), and
ordering *instances* of the same lock is invisible to a static pass —
the runtime sanitizer covers that side.
"""

from __future__ import annotations

import ast
import re
from typing import (Dict, Iterable, Iterator, List, Optional, Sequence, Set,
                    Tuple)

from ray_shuffling_data_loader_tpu.analysis import core
from ray_shuffling_data_loader_tpu.analysis.callgraph import (
    FunctionInfo, ModuleInfo, Program)

#: threading factory names treated as lock constructions.
_LOCK_FACTORIES = ("Lock", "RLock", "Condition")

#: Methods treated as acquisitions in setup code only (``__init__`` has
#: no concurrent peer yet) — matches rules_lock._SETUP_METHODS.
_SETUP_METHODS = ("__init__", "__new__", "__del__", "__init_subclass__",
                  "__post_init__")

#: Container-mutating method names: ``self._xs.append(...)`` mutates
#: shared state just as surely as ``self._xs[k] = v``, but only a
#: whole-program pass bothers tracking it (the per-file lock-mutation
#: rule predates this and only sees Assign/AugAssign/Delete).
_MUTATOR_METHODS = frozenset((
    "append", "extend", "insert", "remove", "discard", "pop", "popitem",
    "clear", "update", "setdefault", "add", "appendleft", "popleft",
    "sort", "reverse"))


class LockDecl:
    """One lock construction site.

    ``key`` is ``path:line`` of the ``threading.X(...)`` call — the
    identity shared with ``runtime/locksan.py``'s dynamic graph.
    """

    __slots__ = ("key", "kind", "owner", "attr", "path", "line", "cls")

    def __init__(self, key: str, kind: str, owner: str, attr: str,
                 path: str, line: int, cls: Optional[str]):
        self.key = key
        self.kind = kind        # Lock | RLock | Condition
        self.owner = owner      # "mod:Class.attr" or "mod:attr" (global)
        self.attr = attr        # bare attribute/global name
        self.path = path
        self.line = line
        self.cls = cls          # owning class qualname ("mod:Class") or None

    def as_dict(self) -> dict:
        return {"key": self.key, "kind": self.kind, "owner": self.owner}


class Acquisition:
    __slots__ = ("lock", "line", "held", "func")

    def __init__(self, lock: str, line: int, held: Tuple[str, ...],
                 func: str):
        self.lock = lock
        self.line = line
        self.held = held        # lock keys held when this one is taken
        self.func = func


class CallSite:
    __slots__ = ("callee", "line", "held", "func")

    def __init__(self, callee: str, line: int, held: Tuple[str, ...],
                 func: str):
        self.callee = callee
        self.line = line
        self.held = held
        self.func = func


class Write:
    __slots__ = ("target", "line", "col", "held", "func", "setup", "kind")

    def __init__(self, target: str, line: int, col: int,
                 held: Tuple[str, ...], func: str, setup: bool, kind: str):
        self.target = target    # "mod:Class.attr" or "mod:name" (global)
        self.line = line
        self.col = col
        self.held = held
        self.func = func
        self.setup = setup
        self.kind = kind        # "assign" | "mutate"


class FuncConc:
    """Concurrency-relevant facts for one function."""

    __slots__ = ("info", "acquisitions", "calls", "writes", "entry_held")

    def __init__(self, info: FunctionInfo):
        self.info = info
        self.acquisitions: List[Acquisition] = []
        self.calls: List[CallSite] = []
        self.writes: List[Write] = []
        #: Locks provably held on entry (interprocedural; None until
        #: the fixpoint assigns it).
        self.entry_held: Optional[Set[str]] = None


def _factory_kind(call: ast.Call, mod: ModuleInfo) -> Optional[str]:
    """``Lock``/``RLock``/``Condition`` when ``call`` constructs one."""
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in _LOCK_FACTORIES:
        base = core.dotted_name(func.value)
        resolved = mod.imports.get(base.partition(".")[0], base)
        if resolved == "threading" or resolved.startswith("threading."):
            return func.attr
    if isinstance(func, ast.Name) and func.id in _LOCK_FACTORIES:
        # ``from threading import Lock``
        if mod.imports.get(func.id, "").startswith("threading."):
            return func.id
    return None


class LockAnalysis:
    """The whole-program lockset/lock-order pass over a Program."""

    def __init__(self, program: Program, config: core.Config):
        self.program = program
        self.config = config
        self._lock_re = re.compile(config.lock_name_regex)
        self.decls: Dict[str, LockDecl] = {}        # by key
        self._class_locks: Dict[Tuple[str, str], LockDecl] = {}
        self._global_locks: Dict[Tuple[str, str], LockDecl] = {}
        self._attr_locks: Dict[str, List[LockDecl]] = {}
        #: Condition-over-existing-lock attrs aliasing the wrapped decl.
        self._aliases: Dict[Tuple[str, str], LockDecl] = {}
        #: attr name -> class qualnames that assign ``self.attr``.
        self._attr_classes: Dict[str, Set[str]] = {}
        self.funcs: Dict[str, FuncConc] = {}
        #: (src, dst) -> first witness string.
        self.edges: Dict[Tuple[str, str], Dict[str, str]] = {}
        self._trans_acquired: Dict[str, Dict[str, str]] = {}

    # -- discovery --------------------------------------------------------

    def _add_decl(self, kind: str, owner: str, attr: str, mod: ModuleInfo,
                  line: int, cls: Optional[str],
                  alias_of: Optional[LockDecl] = None) -> LockDecl:
        if alias_of is not None:
            decl = alias_of  # Condition(self._mutex): same identity
        else:
            key = f"{mod.path}:{line}"
            decl = self.decls.get(key)
            if decl is None:
                decl = LockDecl(key, kind, owner, attr, mod.path, line, cls)
                self.decls[key] = decl
        if cls is not None:
            self._class_locks.setdefault((cls, attr), decl)
            self._attr_locks.setdefault(attr, [])
            if decl not in self._attr_locks[attr]:
                self._attr_locks[attr].append(decl)
        else:
            self._global_locks.setdefault((mod.name, attr), decl)
        return decl

    def _discover_module(self, mod: ModuleInfo) -> None:
        # Module-level: NAME = threading.Lock()
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                kind = _factory_kind(node.value, mod)
                if kind is None:
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        self._add_decl(kind, f"{mod.name}:{target.id}",
                                       target.id, mod, node.value.lineno,
                                       None)
        # Class-level and self.X = threading.Lock() in method bodies.
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            cls_q = f"{mod.name}:{node.name}"
            deferred: List[Tuple[str, ast.Call]] = []
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Assign)
                        and isinstance(sub.value, ast.Call)):
                    continue
                kind = _factory_kind(sub.value, mod)
                if kind is None:
                    continue
                for target in sub.targets:
                    attr = _self_attr_target(target)
                    if attr is None and isinstance(target, ast.Name):
                        attr = target.id  # class-body assignment
                    if attr is None:
                        continue
                    if kind == "Condition" and sub.value.args:
                        deferred.append((attr, sub.value))
                        continue
                    self._add_decl(kind, f"{cls_q}.{attr}", attr, mod,
                                   sub.value.lineno, cls_q)
            # Second pass: Condition(wrapped_lock) aliases the wrapped
            # lock's identity — acquiring the condition IS acquiring it.
            for attr, call in deferred:
                wrapped = None
                arg = call.args[0]
                if isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id == "self":
                    wrapped = self._class_locks.get((cls_q, arg.attr))
                decl = self._add_decl(
                    "Condition", f"{cls_q}.{attr}", attr, mod,
                    call.lineno, cls_q, alias_of=wrapped)
                if wrapped is not None:
                    self._aliases[(cls_q, attr)] = decl
            # Attr ownership index for write attribution: ``self.X``
            # assignments anywhere in the class, plus class-body field
            # declarations (dataclass fields, class attributes) — both
            # make X "an attribute of this class", and attribution must
            # refuse when two classes share a name.
            for sub in ast.walk(node):
                for target in _write_targets(sub):
                    attr = _self_attr_target(target)
                    if attr is not None:
                        self._attr_classes.setdefault(attr,
                                                      set()).add(cls_q)
            for sub in node.body:
                for target in _write_targets(sub):
                    if isinstance(target, ast.Name):
                        self._attr_classes.setdefault(target.id,
                                                      set()).add(cls_q)

    # -- lock reference resolution ----------------------------------------

    def resolve_lock(self, expr: ast.expr,
                     func: FunctionInfo) -> Optional[LockDecl]:
        """The LockDecl an acquisition expression refers to, if known."""
        mod = func.module
        if isinstance(expr, ast.Subscript):
            return self.resolve_lock(expr.value, func)
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if isinstance(expr.value, ast.Name):
                base = expr.value.id
                if base == "self" and func.cls is not None:
                    cls_q = f"{mod.name}:{func.cls}"
                    decl = self._aliases.get((cls_q, attr)) or \
                        self._class_locks.get((cls_q, attr))
                    if decl is not None:
                        return decl
                else:
                    # module alias (``mod.GLOBAL_LOCK``)?
                    imported = mod.imports.get(base)
                    if imported is not None:
                        decl = self._global_locks.get((imported, attr))
                        if decl is not None:
                            return decl
            # Fall through: a foreign receiver (``state.lock``, a
            # handle passed in). Resolve only when the attribute name
            # names exactly one lock program-wide — ambiguity would
            # invent edges.
            candidates = self._attr_locks.get(attr, [])
            if len(candidates) == 1:
                return candidates[0]
            return None
        if isinstance(expr, ast.Name):
            decl = self._global_locks.get((mod.name, expr.id))
            if decl is not None:
                return decl
            imported = mod.imports.get(expr.id)
            if imported and "." in imported:
                owner_mod, _, leaf = imported.rpartition(".")
                return self._global_locks.get((owner_mod, leaf))
        return None

    def is_lock_name(self, name: str) -> bool:
        return bool(self._lock_re.search(name))

    # -- per-function scan -------------------------------------------------

    def _scan_functions(self) -> None:
        for qual, info in self.program.functions.items():
            conc = FuncConc(info)
            _FuncScanner(self, conc).run()
            self.funcs[qual] = conc

    # -- interprocedural fixpoints ----------------------------------------

    def _entry_held_fixpoint(self) -> None:
        """Private functions inherit the intersection of held-sets over
        all their (resolved) call sites; public entry points assume
        nothing. Iterated because a call site's held-set includes the
        caller's own entry set."""
        callsites: Dict[str, List[CallSite]] = {}
        for conc in self.funcs.values():
            for cs in conc.calls:
                callsites.setdefault(cs.callee, []).append(cs)
        def _internal(conc: FuncConc) -> bool:
            # ``_helper`` by name, or any method of a module-private
            # class (``_Frame.resolve_codec``): either way the call
            # sites are a closed world, so the intersection over them
            # is a sound entry assumption.
            name = conc.info.name
            if name.startswith("_") and not name.startswith("__"):
                return True
            return bool(conc.info.cls) and conc.info.cls.startswith("_")

        private = {
            q for q, conc in self.funcs.items()
            if _internal(conc) and callsites.get(q)
        }
        for q, conc in self.funcs.items():
            conc.entry_held = None if q in private else set()
        for _ in range(12):
            changed = False
            for q in private:
                acc: Optional[Set[str]] = None
                for cs in callsites[q]:
                    caller_entry = self.funcs[cs.func].entry_held
                    site_held = set(cs.held) | (caller_entry or set())
                    acc = site_held if acc is None else acc & site_held
                acc = acc or set()
                if self.funcs[q].entry_held != acc:
                    self.funcs[q].entry_held = acc
                    changed = True
            if not changed:
                break
        for conc in self.funcs.values():
            if conc.entry_held is None:
                conc.entry_held = set()

    def _transitive_acquired(self) -> None:
        """lock key -> witness site for every lock a function may
        acquire directly or through resolved callees (fixpoint)."""
        for q, conc in self.funcs.items():
            self._trans_acquired[q] = {
                a.lock: f"{conc.info.module.path}:{a.line}"
                for a in conc.acquisitions}
        for _ in range(16):
            changed = False
            for q, conc in self.funcs.items():
                mine = self._trans_acquired[q]
                for cs in conc.calls:
                    for lock, site in self._trans_acquired.get(
                            cs.callee, {}).items():
                        if lock not in mine:
                            mine[lock] = site
                            changed = True
            if not changed:
                break

    # -- lock-order graph --------------------------------------------------

    def _add_edge(self, src: str, dst: str, where: str, func: str,
                  via: str = "") -> None:
        if src == dst:
            return  # instance-order is the runtime sanitizer's job
        self.edges.setdefault((src, dst), {
            "src": src, "dst": dst, "where": where, "func": func,
            "via": via})

    def _build_edges(self) -> None:
        for q, conc in self.funcs.items():
            entry = conc.entry_held or set()
            path = conc.info.module.path
            for acq in conc.acquisitions:
                for held in set(acq.held) | entry:
                    self._add_edge(held, acq.lock, f"{path}:{acq.line}", q)
            for cs in conc.calls:
                held_at_call = set(cs.held) | entry
                if not held_at_call:
                    continue
                callee_entry = self.funcs[cs.callee].entry_held \
                    if cs.callee in self.funcs else set()
                for lock, site in self._trans_acquired.get(
                        cs.callee, {}).items():
                    # A lock the callee assumes held on entry is not
                    # *acquired* inside it; edges for its nested
                    # acquisitions were already drawn at their site.
                    if lock in held_at_call or lock in (callee_entry
                                                        or set()):
                        continue
                    for held in held_at_call:
                        self._add_edge(
                            held, lock, f"{path}:{cs.line}", q,
                            via=f"call to {cs.callee} which acquires it "
                                f"at {site}")

    # -- public API --------------------------------------------------------

    def run(self) -> "LockAnalysis":
        for mod in self.program.modules.values():
            self._discover_module(mod)
        self._scan_functions()
        self._entry_held_fixpoint()
        self._transitive_acquired()
        self._build_edges()
        return self

    def effective_held(self, conc: FuncConc,
                       held: Tuple[str, ...]) -> Set[str]:
        return set(held) | (conc.entry_held or set())

    def cycles(self) -> List[List[Dict[str, str]]]:
        """Each cycle as its list of edge-witness dicts (A->B, B->..->A)."""
        adj: Dict[str, List[str]] = {}
        for (src, dst) in self.edges:
            adj.setdefault(src, []).append(dst)
        sccs = _tarjan(adj)
        out: List[List[Dict[str, str]]] = []
        for scc in sccs:
            if len(scc) < 2:
                continue
            cycle = _cycle_path(adj, scc)
            out.append([self.edges[(a, b)]
                        for a, b in zip(cycle, cycle[1:] + cycle[:1])
                        if (a, b) in self.edges])
        return out

    def static_graph(self) -> dict:
        """The order graph in the JSON shape locksan dumps, for the
        static<->dynamic cross-check."""
        return {
            "kind": "rsdl-lock-order-graph",
            "source": "static",
            "nodes": [d.as_dict() for d in
                      sorted(self.decls.values(), key=lambda d: d.key)],
            "edges": [dict(w) for (_, _), w in sorted(self.edges.items())],
        }


def analyze(program: Program, config: Optional[core.Config] = None
            ) -> LockAnalysis:
    return LockAnalysis(program, config or core.Config()).run()


# ---------------------------------------------------------------------------
# Static <-> dynamic cross-check
# ---------------------------------------------------------------------------


def crosscheck(static_graph: dict, dynamic_graph: dict) -> dict:
    """Compare the static order graph against a locksan dump.

    Returns ``{"missing_edges", "benign_leaf_edges", "union_cycles",
    "confirmed_cycles"}``:

    - ``missing_edges``: dynamic edges between statically-known locks
      that the static pass has no edge for AND that are
      *order-relevant* — the destination lock has at least one
      outgoing edge in the merged (static + dynamic) graph, so the
      edge extends an acquisition chain that could some day close a
      cycle. These are analysis gaps -> findings.
    - ``benign_leaf_edges``: missing edges whose destination is a leaf
      in the merged graph (nothing is ever acquired while holding it,
      statically or at runtime). A leaf edge can never participate in
      a cycle, so it is recorded for transparency, not flagged —
      without this, every component lock held across a metrics
      increment would demand its own pragma.
    - ``union_cycles``: cycles that appear only once the runtime-
      observed edges are merged into the static graph — a deadlock
      neither view shows alone. Hard findings.
    - ``confirmed_cycles``: static cycles whose every edge was
      observed at runtime (hard failures).

    Dynamic edges touching locks the static pass never declared
    (test-local locks, closure locks) are ignored, as are same-site
    edges — the static graph cannot order instances of one
    construction site.
    """
    nodes = {n["key"] for n in static_graph.get("nodes", [])}
    static_edges = {(e["src"], e["dst"])
                    for e in static_graph.get("edges", [])}
    dynamic_edges = {}
    for e in dynamic_graph.get("edges", []):
        src, dst = e.get("src"), e.get("dst")
        if src == dst or src not in nodes or dst not in nodes:
            continue
        dynamic_edges[(src, dst)] = e
    union_out: Dict[str, List[str]] = {}
    for src, dst in static_edges | set(dynamic_edges):
        union_out.setdefault(src, []).append(dst)
    missing, benign = [], []
    for (src, dst), e in sorted(dynamic_edges.items()):
        if (src, dst) in static_edges:
            continue
        (missing if union_out.get(dst) else benign).append(e)
    confirmed = []
    adj: Dict[str, List[str]] = {}
    for src, dst in static_edges:
        adj.setdefault(src, []).append(dst)
    static_cyclic_nodes = set()
    for scc in _tarjan(adj):
        if len(scc) < 2:
            continue
        static_cyclic_nodes.update(scc)
        cycle = _cycle_path(adj, scc)
        edges = list(zip(cycle, cycle[1:] + cycle[:1]))
        if all((a, b) in dynamic_edges for a, b in edges):
            confirmed.append([list(e) for e in edges])
    union_cycles = []
    for scc in _tarjan(union_out):
        if len(scc) < 2 or set(scc) <= static_cyclic_nodes:
            continue
        cycle = _cycle_path(union_out, scc)
        union_cycles.append(list(zip(cycle, cycle[1:] + cycle[:1])))
    return {"missing_edges": missing, "benign_leaf_edges": benign,
            "union_cycles": union_cycles, "confirmed_cycles": confirmed}


# ---------------------------------------------------------------------------
# Function-body scanner
# ---------------------------------------------------------------------------


def _self_attr_target(node: ast.AST) -> Optional[str]:
    """``self.X`` / ``self.X[k]`` assignment target -> ``X``."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _write_targets(node: ast.AST) -> List[ast.expr]:
    if isinstance(node, ast.Assign):
        out: List[ast.expr] = []
        for t in node.targets:
            out.extend(t.elts if isinstance(t, ast.Tuple) else [t])
        return out
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return [node.target]
    if isinstance(node, ast.Delete):
        return list(node.targets)
    return []


class _FuncScanner:
    """Linear walk of one function body tracking the held-lock stack."""

    def __init__(self, analysis: LockAnalysis, conc: FuncConc):
        self.an = analysis
        self.conc = conc
        self.info = conc.info
        self.held: List[str] = []
        self.setup = conc.info.name in _SETUP_METHODS
        self._globals: Set[str] = set()
        self._fresh_locals: Set[str] = set()

    def run(self) -> None:
        for node in ast.walk(self.info.node):
            if isinstance(node, ast.Global):
                self._globals.update(node.names)
        self._find_fresh_locals()
        self._scan_block(self.info.node.body)

    def _find_fresh_locals(self) -> None:
        """Locals that only ever hold an object constructed HERE (every
        assignment is ``name = SomeProgramClass(...)``): writes through
        them are to an unpublished object no other thread can see yet
        (``load_slice``'s ``ring``), not shared-state mutations."""
        assigns: Dict[str, List[ast.expr]] = {}
        for node in ast.walk(self.info.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                assigns.setdefault(node.targets[0].id,
                                   []).append(node.value)
        args = self.info.node.args
        params = {a.arg for a in (args.args + args.kwonlyargs
                                  + getattr(args, "posonlyargs", []))}
        for name, values in assigns.items():
            if name in params:
                continue
            if all(isinstance(v, ast.Call)
                   and self.an.program.resolve_class(
                       self.info.module, v) is not None
                   for v in values):
                self._fresh_locals.add(name)

    # -- structure ---------------------------------------------------------

    def _scan_block(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            self._scan_stmt(stmt)

    def _scan_child_block(self, stmts: Sequence[ast.stmt]) -> None:
        # Branch-local acquire/release effects stay in the branch: the
        # continuation after an ``if``/loop body sees the entry state
        # (full restore, so a branch-local ``release()`` cannot strip a
        # lock the enclosing ``with`` still holds).
        saved = list(self.held)
        self._scan_block(stmts)
        self.held[:] = saved

    def _scan_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # Nested defs run on their own schedule (often another
            # thread): scan them lock-free, like rules_lock does.
            saved, self.held = self.held, []
            for sub in (stmt.body if isinstance(stmt, ast.ClassDef)
                        else [stmt]):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._scan_block(sub.body)
            self.held = saved
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in stmt.items:
                self._visit_expr(item.context_expr)
                decl = self.an.resolve_lock(item.context_expr, self.info)
                if decl is not None:
                    self._record_acquire(decl.key, item.context_expr.lineno)
                    acquired.append(decl.key)
            saved = list(self.held)
            self.held.extend(acquired)
            self._scan_block(stmt.body)
            self.held[:] = saved
            return
        if isinstance(stmt, ast.If):
            self._visit_expr(stmt.test)
            self._scan_child_block(stmt.body)
            self._scan_child_block(stmt.orelse)
            return
        if isinstance(stmt, (ast.While,)):
            self._visit_expr(stmt.test)
            self._scan_child_block(stmt.body)
            self._scan_child_block(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_expr(stmt.iter)
            self._record_writes(stmt.target, kind="assign")
            self._scan_child_block(stmt.body)
            self._scan_child_block(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self._scan_child_block(stmt.body)
            for handler in stmt.handlers:
                self._scan_child_block(handler.body)
            self._scan_child_block(stmt.orelse)
            # ``finally`` ALWAYS runs before the continuation, so its
            # acquire/release effects persist — this is what models the
            # ``release(); try: ... finally: acquire()`` bracket
            # (RemoteQueue.get_positioned) correctly.
            self._scan_block(stmt.finalbody)
            return
        # Leaf statements: writes, then every call in the expressions.
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                             ast.Delete)):
            for target in _write_targets(stmt):
                self._record_writes(target, kind="assign")
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._visit_call(node)

    # -- expressions -------------------------------------------------------

    def _visit_expr(self, expr: Optional[ast.expr]) -> None:
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._visit_call(node)

    def _visit_call(self, call: ast.Call) -> None:
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr == "acquire":
                decl = self.an.resolve_lock(func.value, self.info)
                if decl is not None:
                    self._record_acquire(decl.key, call.lineno)
                    self.held.append(decl.key)
                    return
            elif func.attr == "release":
                decl = self.an.resolve_lock(func.value, self.info)
                if decl is not None and decl.key in self.held:
                    # remove the innermost occurrence
                    for i in range(len(self.held) - 1, -1, -1):
                        if self.held[i] == decl.key:
                            del self.held[i]
                            break
                    return
            elif func.attr in _MUTATOR_METHODS:
                self._record_writes(func.value, kind="mutate")
        callee = self.an.program.resolve_call(self.info, call)
        if callee is not None:
            self.conc.calls.append(CallSite(
                callee, call.lineno, tuple(self.held),
                self.info.qualname))

    # -- recording ---------------------------------------------------------

    def _record_acquire(self, key: str, line: int) -> None:
        self.conc.acquisitions.append(Acquisition(
            key, line, tuple(self.held), self.info.qualname))

    def _record_writes(self, target: ast.expr, kind: str) -> None:
        mod = self.info.module
        if isinstance(target, ast.Tuple):
            for elt in target.elts:
                self._record_writes(elt, kind)
            return
        sub = isinstance(target, ast.Subscript)
        inner = target.value if isinstance(target, ast.Subscript) \
            else target
        attr = _self_attr_target(inner)
        target_id: Optional[str] = None
        if attr is not None and self.info.cls is not None:
            if self.an.is_lock_name(attr):
                return
            target_id = f"{mod.name}:{self.info.cls}.{attr}"
        elif isinstance(inner, ast.Attribute) and \
                isinstance(inner.value, ast.Name) and \
                inner.value.id != "self":
            if inner.value.id in self._fresh_locals:
                return  # constructed here, unpublished: not shared yet
            # Foreign receiver: attribute owned by exactly one class
            # program-wide, else unattributable.
            owners = self.an._attr_classes.get(inner.attr, set())
            if len(owners) == 1 and not self.an.is_lock_name(inner.attr):
                target_id = f"{next(iter(owners))}.{inner.attr}"
        elif isinstance(inner, ast.Name):
            name = inner.id
            is_global = name in mod.global_names and \
                (sub or kind == "mutate" or name in self._globals)
            if is_global and not self.an.is_lock_name(name):
                target_id = f"{mod.name}:{name}"
        if target_id is None:
            return
        self.conc.writes.append(Write(
            target_id, target.lineno, target.col_offset,
            tuple(self.held), self.info.qualname, self.setup, kind))


# ---------------------------------------------------------------------------
# Graph utilities
# ---------------------------------------------------------------------------


def _tarjan(adj: Dict[str, Iterable[str]]) -> List[List[str]]:
    """Strongly connected components (iterative Tarjan)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]
    nodes = set(adj)
    for vs in adj.values():
        nodes.update(vs)

    def strongconnect(root: str) -> None:
        work: List[Tuple[str, Iterator]] = [(root, iter(adj.get(root, ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(scc)

    for node in sorted(nodes):
        if node not in index:
            strongconnect(node)
    return sccs


def _cycle_path(adj: Dict[str, Iterable[str]], scc: Sequence[str]
                ) -> List[str]:
    """A simple cycle visiting nodes of one cyclic SCC (DFS walk)."""
    members = set(scc)
    start = sorted(scc)[0]
    path = [start]
    seen = {start}
    node = start
    while True:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start and len(path) > 1:
                return path
            if nxt in members and nxt not in seen:
                path.append(nxt)
                seen.add(nxt)
                node = nxt
                break
        else:
            # dead end inside the SCC: back out
            path.pop()
            if not path:
                return list(scc)
            node = path[-1]
        if len(path) > len(members):
            return path
